"""Headline benchmark: pairs/sec/chip for the tiled U-statistic core.

Prints ONE JSON line:
  {"metric": "pairs/sec/chip", "value": N, "unit": "pairs/s", "vs_baseline": R}

``--streaming`` switches to the serving-path benchmark instead: replay
a synthetic stream through the micro-batched request engine
(tuplewise_tpu.serving) and print ONE JSON line
  {"metric": "events/sec", "value": N, "unit": "events/s",
   "vs_baseline": R, ...}
where vs_baseline is the dynamic batcher's speedup over the same
engine forced to max_batch=1 (no coalescing) — the quantity the
micro-batching exists to improve. Per-event insert-latency
p50/p95/p99, the compaction-pause histogram, the exact-vs-oracle
parity check, and ``p99_insert_vs_sync_compact`` — the p99 win of the
background compactor over on-thread compaction at the same config
[ISSUE 2] — ride along in the same record. Submission is a bounded
closed loop (``--max-inflight``), so percentiles price per-event cost
rather than queue backlog. ``--chaos`` [ISSUE 3] reruns the streaming
bench under a seeded fault schedule (compactor crash, batcher crash,
poison events) and adds the recovery counters + admitted-events parity
to the record — throughput WITH failures, not just without. The
``delta_compaction`` cell [ISSUE 5] prices the sharded index's
compaction byte budget: host→device bytes per minor compaction with
delta runs + on-mesh major merges vs the PR 2 full re-placement, at
n=10^6 and S=4 by default (``--delta-bench-n 0`` skips). With
``--out``, the streaming record and the delta cell also land as JSONL
rows (the perf-trajectory file ``results/serving.jsonl``).

`value` is the complete-AUC pair-kernel throughput of the JAX/TPU tiled
reduction on one chip (BASELINE.json:2's metric). The reference repo
published no numbers (/root/reference was empty; BASELINE.md), so per
SURVEY §6 the recorded baseline is the frozen NumPy oracle path measured
on this same machine: vs_baseline = tpu_throughput / numpy_throughput.
NOTE vs_baseline compares DIFFERENT problem sizes (TPU at n=2^20 vs the
oracle at n=16384 — the oracle at 2^20 would take hours): it is
round-over-round bookkeeping of the same two measurements, not a
like-for-like speedup claim.

Diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

import dataclasses
import json
import sys
import time

import numpy as np


def _tpu_pairs_per_sec(n=1 << 20, tile_a=2048, tile_b=8192, reps=3):
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.ops import pair_tiles
    from tuplewise_tpu.ops.kernels import auc_kernel

    rng = np.random.default_rng(0)
    # DISTINCT inputs per rep: the axon runtime can memoize repeated
    # identical jitted calls, which makes same-input timing loops lie.
    # Array creation is LAZY through the tunnel — force each input
    # resident (host read of a reduction) so the timed window is
    # compute-only, not host->device transfer.
    inputs = [
        (
            jnp.asarray(rng.standard_normal(n), jnp.float32),
            jnp.asarray(rng.standard_normal(n), jnp.float32),
        )
        for _ in range(reps + 1)
    ]
    for a, b in inputs:
        float(jnp.sum(a) + jnp.sum(b))

    # Prefer the hand-tiled Pallas kernel (explicit sublane x lane layout,
    # SMEM row-block accumulators) — ~4x the lax.scan path at this size;
    # verified bit-equal to the exact O(n log n) rank AUC at n=2^20.
    # Fall back to the XLA tiled reduction if Pallas can't lower here.
    try:
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum

        def f(a, b):
            return pallas_pair_sum(
                a, b, kernel=auc_kernel, tile_a=tile_a, tile_b=tile_b
            ), n * n

        float(f(*inputs[0])[0])
        path = "pallas"
    except Exception as e:  # pragma: no cover - hardware-dependent
        print(f"[bench] pallas unavailable ({e!r}); XLA path", file=sys.stderr)
        # honor the requested tiles, shrunk to pair_stats' exact-count
        # bound (tile_a * tile_b < 2^24); shrink the larger dim each
        # step and never drive either below 1
        ta, tb = tile_a, tile_b
        while ta * tb >= 1 << 24 and (ta > 1 or tb > 1):
            if ta >= tb:
                ta = max(1, ta // 2)
            else:
                tb = max(1, tb // 2)
        tile_b = tb
        f = jax.jit(
            lambda a, b: pair_tiles.pair_stats(
                auc_kernel, a, b, tile_a=ta, tile_b=tile_b
            )
        )
        float(f(*inputs[0])[0])
        path = "xla"
    # (block_until_ready alone does not reliably wait through the axon
    # tunnel — time individual calls, each synced by a host read)
    times = []
    r = None
    for inp in inputs[1:]:
        t0 = time.perf_counter()
        r = f(*inp)
        float(r[0])
        times.append(time.perf_counter() - t0)
    dt = min(times)
    auc = float(r[0]) / float(r[1])
    print(
        f"[bench] device={jax.devices()[0]} path={path} n={n} dt={dt:.4f}s "
        f"auc={auc:.4f}", file=sys.stderr,
    )
    return (n * n) / dt


def _ring_pairs_per_sec(n=1 << 20, tile_a=2048, tile_b=8192, reps=3):
    """Per-chip throughput of the DISTRIBUTED path: the mesh backend's
    ppermute ring (mesh of 1 on this chip) with the mask-aware Pallas
    hot loop — the deliverable estimator, not just the raw kernel.
    Diagnostic only (stderr); the headline stays the raw kernel number
    so rounds stay comparable."""
    import jax.numpy as jnp

    from tuplewise_tpu.backends.mesh_backend import MeshBackend
    from tuplewise_tpu.ops.kernels import auc_kernel

    rng = np.random.default_rng(1)
    be = MeshBackend(
        auc_kernel, n_workers=1, tile_a=tile_a, tile_b=tile_b
    )
    packs = [
        (
            be._pack_complete(rng.standard_normal(n).astype(np.float32)),
            be._pack_complete(rng.standard_normal(n).astype(np.float32)),
        )
        for _ in range(reps + 1)
    ]
    for pa, pb in packs:  # force residency: see _tpu_pairs_per_sec
        for arr in (*pa, *pb):
            float(jnp.sum(arr))

    def f(pa, pb):
        (a, ma, ia), (b, mb, ib) = pa, pb
        # n % n_shards == 0 here: packing adds no padding, so the ring
        # may take the unmasked fast path (same contract as .complete())
        return be._complete(a, ma, ia, b, mb, ib, no_masks=True)

    float(f(*packs[0]))
    times = []
    for pa, pb in packs[1:]:
        t0 = time.perf_counter()
        float(f(pa, pb))
        times.append(time.perf_counter() - t0)
    dt = min(times)
    print(
        f"[bench] ring mesh-of-1 impl={be.impl} n={n} dt={dt:.4f}s "
        f"-> {(n * n) / dt:.3e} pairs/s", file=sys.stderr,
    )
    return (n * n) / dt


def _anyn_pairs_per_sec(n=(1 << 20) + 64, reps=3):
    """Throughput of the ANY-n interior/edge-decomposed path
    (pallas_pair_sum_any) at a non-tile-divisible size [VERDICT r4 next
    #7]: one extra number in the driver-captured JSON so round-over-
    round BENCH guards the interior/edge dispatch, not only the
    tile-divisible unmasked kernel. Returns None off-TPU (the decomposed
    path is a TPU construction; interpret mode would time emulation)."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform != "tpu":
        return None
    from tuplewise_tpu.ops.kernels import auc_kernel
    from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum_any

    rng = np.random.default_rng(2)
    inputs = [
        (
            jnp.asarray(rng.standard_normal(n), jnp.float32),
            jnp.asarray(rng.standard_normal(n), jnp.float32),
        )
        for _ in range(reps + 1)
    ]
    for a, b in inputs:  # force residency: see _tpu_pairs_per_sec
        float(jnp.sum(a) + jnp.sum(b))

    f = jax.jit(
        lambda a, b: pallas_pair_sum_any(a, b, kernel=auc_kernel)
    )
    float(f(*inputs[0]))
    times = []
    for inp in inputs[1:]:
        t0 = time.perf_counter()
        float(f(*inp))
        times.append(time.perf_counter() - t0)
    dt = min(times)
    print(
        f"[bench] any-n interior/edge n={n} dt={dt:.4f}s "
        f"-> {(n * n) / dt:.3e} pairs/s", file=sys.stderr,
    )
    return (n * n) / dt


def _numpy_pairs_per_sec(n=16384, reps=3):
    from tuplewise_tpu.backends.numpy_backend import NumpyBackend
    from tuplewise_tpu.ops.kernels import auc_kernel

    rng = np.random.default_rng(0)
    s1 = rng.standard_normal(n)
    s2 = rng.standard_normal(n)
    be = NumpyBackend(auc_kernel)
    be.complete(s1, s2)  # warm caches
    t0 = time.perf_counter()
    for _ in range(reps):
        be.complete(s1, s2)
    dt = (time.perf_counter() - t0) / reps
    print(f"[bench] numpy oracle n={n} dt={dt:.4f}s", file=sys.stderr)
    return (n * n) / dt


def _delta_compaction_cell(n_events=1_000_000, shards=4,
                           compact_every=1024, delta_fraction=0.25,
                           max_delta_runs=64, chunk=128, seed=0):
    """Bytes-shipped-per-compaction cell [ISSUE 5]: drive the SHARDED
    index directly (no request queue — per-insert latency is the
    index's own cost) through the same stream twice, delta compaction
    vs the PR 2 host-merge + full-re-placement path, and report
    host→device bytes per minor compaction plus insert-latency
    percentiles. Both runs compact SYNCHRONOUSLY, so every tier bills
    its true pause to the inserting thread — the honest apples-to-
    apples cost of the two compaction strategies (the background
    compactor's independent p99 win over sync mode is the main
    streaming record's ``p99_insert_vs_sync_compact``). ``chunk``
    defaults to the engine's TYPICAL coalesced micro-batch (~half of
    ``max_batch=256`` at the measured ~0.25-0.5 mean batch fill), so
    per-batch latency percentiles reflect what a serving batcher
    dispatch actually pays. Returns None when the platform has fewer
    than ``shards`` devices."""
    import jax

    from tuplewise_tpu.serving import ExactAucIndex
    from tuplewise_tpu.serving.replay import make_stream

    if jax.device_count() < shards:
        print(f"[bench] delta cell skipped: {jax.device_count()} "
              f"devices < {shards} shards", file=sys.stderr)
        return None
    scores, labels = make_stream(n_events, pos_frac=0.5,
                                 separation=1.0, seed=seed)
    scores = scores.astype(np.float32)
    out = {"n_events": n_events, "shards": shards,
           "compact_every": compact_every,
           "delta_fraction": delta_fraction,
           "max_delta_runs": max_delta_runs, "chunk": chunk}
    wins = {}

    def _drive(frac, record):
        idx = ExactAucIndex(engine="jax", compact_every=compact_every,
                            shards=shards, bg_compact=False,
                            delta_fraction=frac,
                            max_delta_runs=max_delta_runs)
        lats = []
        t_all = time.perf_counter()
        for i in range(0, n_events, chunk):
            t0 = time.perf_counter()
            idx.insert_batch(scores[i:i + chunk], labels[i:i + chunk])
            lats.append(time.perf_counter() - t0)
        wall = time.perf_counter() - t_all
        if not record:
            idx.close()
        return idx, lats, wall

    for mode, frac in (("delta", delta_fraction), ("host_merge", 0.0)):
        # warmup pass: the bucket-ladder kernels (multi-run counts,
        # on-mesh merges) compile as the base grows — a long-lived
        # service pays them once, so the timed pass measures steady
        # state (same discipline as replay(warmup=True))
        _drive(frac, record=False)
        idx, lats, wall = _drive(frac, record=True)
        snap = idx.metrics.snapshot()
        cb = snap.get("compaction_bytes", {})
        lat = np.asarray(lats) * 1e3
        out[mode] = {
            "wall_s": wall,
            "events_per_s": n_events / wall,
            "insert_latency_p50_ms": float(np.percentile(lat, 50)),
            "insert_latency_p99_ms": float(np.percentile(lat, 99)),
            "compactions": idx.n_compactions,
            "minor_compactions": cb.get("count", 0),
            "bytes_h2d": snap["bytes_h2d"]["value"],
            "bytes_h2d_saved": snap["bytes_h2d_saved"]["value"],
            "bytes_per_minor_compaction": cb.get("mean"),
            "major_merges": snap["major_merges_total"]["value"],
            "major_merge_fallbacks":
                snap["major_merge_fallbacks"]["value"],
            "major_merge_p99_ms": (
                None if snap["major_merge_s"].get("p99") is None
                else snap["major_merge_s"]["p99"] * 1e3),
        }
        wins[mode] = idx._wins2
        idx.close()
        print(
            f"[bench] delta cell [{mode}]: "
            f"{out[mode]['bytes_per_minor_compaction']:.0f} B/minor "
            f"({out[mode]['minor_compactions']} minors, "
            f"{out[mode]['major_merges']} majors), "
            f"insert p99={out[mode]['insert_latency_p99_ms']:.2f}ms",
            file=sys.stderr,
        )
    # the acceptance pair [ISSUE 5]: >= 10x fewer bytes per minor
    # compaction, p99 insert no worse — and exact parity between modes
    out["bytes_per_minor_ratio"] = round(
        out["host_merge"]["bytes_per_minor_compaction"]
        / out["delta"]["bytes_per_minor_compaction"], 1)
    out["p99_insert_vs_host_merge"] = round(
        out["host_merge"]["insert_latency_p99_ms"]
        / out["delta"]["insert_latency_p99_ms"], 2)
    out["p99_note"] = (
        "CPU caveat: host==device silicon, so the host-merge path "
        "pays no transfer penalty here and its O(n) per-minor cost "
        "only overtakes the delta tiers' flat cost at n~2e6 on CPU "
        "(p99 ratio crosses 1.0 there — run with "
        "--delta-bench-n 2000000); on accelerators the O(n) "
        "host->device re-ship dominates far earlier"
    )
    out["wins2_parity"] = wins["delta"] == wins["host_merge"]
    print(
        f"[bench] delta compaction: {out['bytes_per_minor_ratio']}x "
        f"fewer bytes/minor, p99 ratio "
        f"{out['p99_insert_vs_host_merge']}x, "
        f"parity={out['wins2_parity']}", file=sys.stderr,
    )
    return out


# Default --chaos schedule: one compactor crash, one batcher crash, and
# a few poison events — the recovery paths a serving deploy actually
# exercises, at bench scale. Shard death needs a multi-device mesh, so
# it lives in the CI chaos smoke / tests instead of the bench default.
_CHAOS_BENCH_SPEC = {"faults": [
    {"point": "compactor_build", "on_call": 1, "action": "error"},
    {"point": "batcher", "on_call": 50, "action": "error"},
    {"point": "poison", "at_events": [1000, 2500, 4000], "value": "nan"},
]}


def _batch_chaos_record(spec=None):
    """Batch-path chaos rider [ISSUE 4]: a small mesh Monte-Carlo sweep
    run under a device-loss schedule, with the elastic re-shard
    completing it over the survivors. Returns the sweep's recovery
    counters plus a parity bit against the fault-free sweep — the
    training-side twin of the --streaming --chaos record."""
    import jax

    from tuplewise_tpu.harness.variance import (
        VarianceConfig, run_variance_experiment,
    )
    from tuplewise_tpu.testing.chaos import FaultInjector

    n_dev = jax.device_count()
    width = min(2, n_dev)
    # dropping a worker needs a spare to backfill the fixed-width mesh
    dropped = [1] if n_dev >= 3 and width == 2 else []
    default = {"faults": [{"point": "mesh_mc", "on_call": 2,
                           "action": "error", "dropped": dropped}]}
    cfg = VarianceConfig(kernel="auc", scheme="local", backend="mesh",
                         n_pos=4096, n_neg=4096, n_workers=width,
                         n_reps=8, seed=0)
    ref = run_variance_experiment(cfg)
    chaos = FaultInjector.from_spec(spec or default)
    res = run_variance_experiment(
        cfg, chaos=chaos, checkpoint_path=None)
    rec = dict(res["recovery"])
    rec["mean_matches_fault_free"] = res["mean"] == ref["mean"]
    rec["n_reps"] = cfg.n_reps
    print(
        f"[bench] batch chaos: reshard_events={rec['reshard_events']} "
        f"retries={rec['retries_total']} "
        f"parity={rec['mean_matches_fault_free']}", file=sys.stderr,
    )
    return rec


def _streaming_events_per_sec(n_events=300_000, budget=64, max_batch=256,
                              window=None, baseline_events=2_000,
                              bg_compact=True, max_inflight=64,
                              flush_timeout_s=0.0005, chaos=None,
                              obs=None):
    """Micro-batched serving throughput + unbatched baseline + the
    on-thread-compaction latency comparison.

    Policy "block" so every event is applied (throughput of the full
    stream, not of the survivors). Submission is a bounded closed loop
    (``max_inflight``): unbounded submission saturates the queue and
    the latency percentiles measure backlog, not per-event cost — the
    bound is what lets compaction pauses surface in p99. The unbatched
    baseline measures the same per-event request path with coalescing
    disabled, on a shorter stream (per-event cost dominates, so the
    rate is length-stable). The sync run repeats the main config with
    ``bg_compact=False`` — the p99 gap is the pause the background
    compactor removes.
    """
    from tuplewise_tpu.serving import ServingConfig, make_stream, replay

    scores, labels = make_stream(n_events, pos_frac=0.5, separation=1.0,
                                 seed=0)
    cfg = ServingConfig(budget=budget, max_batch=max_batch, window=window,
                        policy="block", flush_timeout_s=flush_timeout_s,
                        compact_every=1024, bg_compact=bg_compact)
    # observability [ISSUE 6]: only the MAIN timed run is traced /
    # metric-streamed / profiled; baseline + sync comparison runs stay
    # bare so their numbers measure the engine, not the instruments
    obs = obs or {}
    rec = replay(scores, labels, config=cfg, warmup=True,
                 max_inflight=max_inflight, chaos=chaos, **obs)
    print(
        f"[bench] streaming n={n_events} batched (bg_compact="
        f"{bg_compact}): "
        f"{rec['events_per_s']:.0f} ev/s "
        f"insert p99={rec['insert_latency_p99_ms']:.1f}ms "
        f"fill={rec['mean_batch_fill']:.2f} "
        f"auc_err={rec.get('auc_abs_err')}", file=sys.stderr,
    )
    nb = min(baseline_events, n_events)
    base_cfg = ServingConfig(budget=budget, max_batch=1, window=window,
                             policy="block", flush_timeout_s=0.0,
                             bg_compact=bg_compact)
    base = replay(scores[:nb], labels[:nb], config=base_cfg, warmup=True,
                  max_inflight=max_inflight)
    print(
        f"[bench] streaming baseline (max_batch=1, n={nb}): "
        f"{base['events_per_s']:.0f} ev/s", file=sys.stderr,
    )
    sync = None
    if bg_compact:
        sync = replay(scores, labels,
                      config=dataclasses.replace(cfg, bg_compact=False),
                      warmup=True, max_inflight=max_inflight)
        pause = sync["compaction_pause_p99_ms"]   # None below 1 compaction
        print(
            f"[bench] streaming sync-compaction comparison: "
            f"{sync['events_per_s']:.0f} ev/s "
            f"insert p99={sync['insert_latency_p99_ms']:.1f}ms "
            f"pause p99="
            + (f"{pause:.1f}ms" if pause is not None else "n/a"),
            file=sys.stderr,
        )
    return rec, base, sync


def _multi_tenant_cell(n_events=20_000, tenant_counts=(1, 32, 256),
                       skew=1.0, budget=16, max_batch=256,
                       max_inflight=64):
    """Fleet scaling cell [ISSUE 8 satellite]: the same Zipf-skewed
    stream replayed through the ``MultiTenantEngine`` at increasing
    tenant counts — events/s, insert p99 (global + worst tenant),
    admission counters, and the one-jitted-count witness
    (``fleet_count_calls`` vs batches) per T. The per-tenant oracle
    parity guardrail runs on every cell: a fleet that drifts from T
    independent engines fails the bench, not just a test."""
    from tuplewise_tpu.serving import (
        ServingConfig, make_tenant_stream, replay_fleet,
    )

    cells = {}
    for T in tenant_counts:
        scores, labels, tenants = make_tenant_stream(
            n_events, T, skew=skew, seed=0)
        cfg = ServingConfig(budget=budget, max_batch=max_batch,
                            policy="block", flush_timeout_s=0.0005,
                            compact_every=512)
        rec = replay_fleet(scores, labels, tenants, config=cfg,
                           max_inflight=max_inflight, warmup=True)
        assert (rec.get("tenant_auc_max_abs_err") or 0.0) < 1e-6, (
            f"fleet parity broke at T={T}: "
            f"{rec.get('tenant_auc_max_abs_err')}")
        cells[str(T)] = {
            "events_per_s": round(rec["events_per_s"], 1),
            "insert_p99_ms": rec["insert_latency_p99_ms"],
            "tenant_insert_p99_max_ms": rec["tenant_insert_p99_max_ms"],
            "tenant_insert_p99_median_ms":
                rec["tenant_insert_p99_median_ms"],
            "admission": rec["admission"],
            "fleet_count_calls": rec["fleet_count_calls"],
            "batches": rec["batches"],
            "tenant_auc_max_abs_err": rec["tenant_auc_max_abs_err"],
        }
        print(
            f"[bench] multi_tenant T={T}: "
            f"{rec['events_per_s']:.0f} ev/s "
            f"insert p99={rec['insert_latency_p99_ms']:.1f}ms "
            f"count_calls={rec['fleet_count_calls']} "
            f"batches={rec['batches']} "
            f"parity_err={rec['tenant_auc_max_abs_err']:.1e}",
            file=sys.stderr,
        )
    return {"n_events": n_events, "skew": skew, "budget": budget,
            "cells": cells}


def _fleet_incremental_cell(n_events=40_000, tenants=256, skew=1.1,
                            shards=2, compact_every=128,
                            whale_threshold=1500, chunk=256, seed=0):
    """Incremental fleet hot-path cell [ISSUE 9]: the same Zipf-skewed
    T=256 stream (one natural whale at the head) driven through the
    ``TenantFleetIndex`` twice — the ISSUE 9 path (dirty-row placement
    + whale promotion + off-batcher tenant builds) vs the PR 8
    full-pack path (every re-place ships the whole [S, T_bucket, cap]
    block, every tenant compacts via the on-thread splice). Reports
    host→device bytes per re-place (the acceptance ratio), insert
    p50/p99 of the apply path, and the whale-vs-small p99 split —
    promotion should make the whale's tail flat instead of scaling
    with its size. Per-tenant wins2 parity between the two modes is
    asserted inline. Latencies are per coalesced apply (``chunk``
    events across however many tenants the chunk touched), the unit a
    serving batcher dispatch actually pays. Returns None when the
    platform has fewer than ``shards`` devices."""
    import jax

    from tuplewise_tpu.serving.replay import make_tenant_stream
    from tuplewise_tpu.serving.tenancy import TenantFleetIndex

    if shards and jax.device_count() < shards:
        print(f"[bench] fleet_incremental skipped: "
              f"{jax.device_count()} devices < {shards} shards",
              file=sys.stderr)
        return None
    scores, labels, tids = make_tenant_stream(
        n_events, tenants, skew=skew, seed=seed)
    scores = scores.astype(np.float32)
    whale_tid = "t0"                    # the Zipf head

    def _drive(incremental, whale, bg):
        fleet = TenantFleetIndex(
            compact_every=compact_every, shards=shards,
            incremental_placement=incremental, whale_threshold=whale,
            bg_compact=bg)
        lat_whale, lat_small = [], []
        t_all = time.perf_counter()
        for i in range(0, n_events, chunk):
            sl = slice(i, min(i + chunk, n_events))
            items, whale_items = [], []
            for t in np.unique(tids[sl]):
                m = tids[sl] == t
                item = (str(t), scores[sl][m], labels[sl][m])
                # the whale applies separately so its latency (and the
                # whale-size-dependent compaction cost the promotion
                # removes) is attributable — the split the record's
                # whale-vs-small p99 prices
                (whale_items if str(t) == whale_tid
                 else items).append(item)
            if whale_items:
                t0 = time.perf_counter()
                fleet.apply_inserts(whale_items)
                lat_whale.append(time.perf_counter() - t0)
            if items:
                t0 = time.perf_counter()
                fleet.apply_inserts(items)
                lat_small.append(time.perf_counter() - t0)
        wall = time.perf_counter() - t_all
        if bg:
            fleet.wait_idle()
        snap = fleet.metrics.snapshot()
        wins = {t: fleet.wins2(t) for t in fleet.tenants()}
        replaces = snap["pack_replaces_total"]["value"]
        lat_all = np.asarray(lat_whale + lat_small) * 1e3
        bytes_h2d = snap.get("bytes_h2d", {}).get("value", 0)
        rec = {
            "wall_s": wall,
            "events_per_s": n_events / wall,
            "insert_latency_p50_ms": float(np.percentile(lat_all, 50)),
            "insert_latency_p99_ms": float(np.percentile(lat_all, 99)),
            "whale_insert_p99_ms": float(np.percentile(
                np.asarray(lat_whale) * 1e3, 99)) if lat_whale else None,
            "small_insert_p99_ms": float(np.percentile(
                np.asarray(lat_small) * 1e3, 99)) if lat_small else None,
            "bytes_h2d": bytes_h2d,
            "bytes_h2d_saved": snap.get(
                "bytes_h2d_saved", {}).get("value", 0),
            "pack_replaces": replaces,
            "pack_full_replaces":
                snap["pack_full_replaces_total"]["value"],
            "bytes_per_replace": (bytes_h2d / replaces
                                  if replaces else None),
            "whale_promotions": snap["fleet_whale_promotions"]["value"],
            "compactions": snap["compactions_total"]["value"],
        }
        fleet.close()
        return rec, wins

    out = {"n_events": n_events, "tenants": tenants, "skew": skew,
           "shards": shards, "compact_every": compact_every,
           "whale_threshold": whale_threshold, "chunk": chunk}
    # warmup passes compile the bucket-ladder kernels; the timed passes
    # measure steady state (same discipline as the delta cell)
    _drive(True, whale_threshold, True)
    inc, wins_inc = _drive(True, whale_threshold, True)
    _drive(False, None, False)
    full, wins_full = _drive(False, None, False)
    out["incremental"] = inc
    out["full_pack"] = full
    assert wins_inc == wins_full, "fleet_incremental parity broke"
    out["wins2_parity"] = True
    if inc["bytes_per_replace"] and full["bytes_per_replace"]:
        out["bytes_per_replace_ratio"] = round(
            full["bytes_per_replace"] / inc["bytes_per_replace"], 1)
    if inc["whale_insert_p99_ms"] and inc["small_insert_p99_ms"]:
        out["whale_vs_small_p99"] = round(
            inc["whale_insert_p99_ms"] / inc["small_insert_p99_ms"], 2)
    if full["whale_insert_p99_ms"] and inc["whale_insert_p99_ms"]:
        out["whale_p99_vs_full_pack"] = round(
            full["whale_insert_p99_ms"] / inc["whale_insert_p99_ms"], 2)
    out["p99_note"] = (
        "CPU caveat: host==device silicon, so the full-pack re-ship "
        "pays no transfer penalty here and the dirty-row path's "
        "per-device scatter dispatches show up in the small-tenant "
        "tail; the deliverable is the whale split — promotion makes "
        "whale p99 flat in whale size (O(buffer) minors off the "
        "request thread) while full-pack whale p99 grows with it — "
        "and the bytes_per_replace_ratio, which on accelerators is "
        "the wall-clock story too"
    )
    # flat fields for scripts/perf_gate.py stage banding [ISSUE 9]
    out["events_per_s"] = round(inc["events_per_s"], 1)
    out["insert_latency_p99_ms"] = inc["insert_latency_p99_ms"]
    out["bytes_per_replace"] = inc["bytes_per_replace"]
    print(
        f"[bench] fleet_incremental T={tenants}: "
        f"{out['bytes_per_replace_ratio']}x fewer bytes/re-place, "
        f"whale p99 {inc['whale_insert_p99_ms']:.2f}ms "
        f"(full-pack {full['whale_insert_p99_ms']:.2f}ms, "
        f"whale/small {out.get('whale_vs_small_p99')}), "
        f"promotions={inc['whale_promotions']}, parity=True",
        file=sys.stderr,
    )
    return out


def _serving_kernel_cell(n_events=1_000_000, shards=2,
                         fleet_tenants=256, fleet_events=40_000,
                         skew=1.1, compact_every=1024, chunk=256,
                         seed=0):
    """Pallas-fused serving counts cell [ISSUE 10]: the same streams
    driven through the index/fleet twice — XLA counts vs the fused
    kernel (``count_kernel=True``) — at n=1e6, S=2 with delta tiers on
    (windowed, so tombstones ride the kernel) and through the fleet at
    T=256 Zipf 1.1. wins2 parity between the two engines is asserted
    inline (integers: bit-exact, not approximate), and the record
    carries the per-micro-batch dispatch-count witness — ONE kernel
    invocation per device per micro-batch once the base runs are
    placed. Off-TPU the kernel executes through the Pallas interpreter
    (a Python-level emulation): the cell SHRINKS the stream and
    records parity + dispatch counts, with the throughput claim gated
    on TPU in ``p99_note`` per the established convention. Returns
    None when the platform has fewer than ``shards`` devices."""
    import jax

    from tuplewise_tpu.serving import ExactAucIndex
    from tuplewise_tpu.serving.replay import (
        make_stream, make_tenant_stream,
    )
    from tuplewise_tpu.serving.tenancy import TenantFleetIndex

    if jax.device_count() < shards:
        print(f"[bench] serving_kernel skipped: {jax.device_count()} "
              f"devices < {shards} shards", file=sys.stderr)
        return None
    interpret = jax.default_backend() != "tpu"
    n_req, fleet_req = n_events, fleet_events
    if interpret:
        # interpret mode prices emulation, not silicon: shrink to the
        # parity/dispatch-witness scale and say so in the record
        n_events = min(n_events, 20_000)
        fleet_events = min(fleet_events, 10_000)
    scores, labels = make_stream(n_events, pos_frac=0.5,
                                 separation=1.0, seed=seed)
    scores = scores.astype(np.float32)
    window = n_events // 2
    out = {"n_events_requested": n_req, "n_events": n_events,
           "shards": shards, "compact_every": compact_every,
           "chunk": chunk, "window": window, "interpret": interpret}
    wins = {}

    def _drive(ck):
        idx = ExactAucIndex(engine="jax", compact_every=compact_every,
                            shards=shards, window=window,
                            delta_fraction=0.25, count_kernel=ck)
        # seed + place the base runs so the dispatch witness counts
        # steady state (pre-placement batches legitimately need zero
        # device dispatches)
        idx.insert_batch(scores[:chunk], labels[:chunk])
        idx.compact()
        snap0 = idx.metrics.snapshot()
        calls0 = snap0["count_kernel_calls_total"]["value"]
        lats, batches = [], 0
        t_all = time.perf_counter()
        for i in range(chunk, n_events, chunk):
            t0 = time.perf_counter()
            idx.insert_batch(scores[i:i + chunk], labels[i:i + chunk])
            lats.append(time.perf_counter() - t0)
            batches += 1
        wall = time.perf_counter() - t_all
        snap = idx.metrics.snapshot()
        lat = np.asarray(lats) * 1e3
        rec = {
            "wall_s": wall,
            "events_per_s": (n_events - chunk) / wall,
            "insert_latency_p50_ms": float(np.percentile(lat, 50)),
            "insert_latency_p99_ms": float(np.percentile(lat, 99)),
            "batches": batches,
            "kernel_calls":
                snap["count_kernel_calls_total"]["value"] - calls0,
            "kernel_fallbacks":
                snap["count_kernel_fallbacks_total"]["value"],
        }
        w2 = idx._wins2
        idx.close()
        return rec, w2

    for mode, ck in (("xla", False), ("kernel", True)):
        _drive(ck)                      # warmup: compiles off the clock
        rec, w2 = _drive(ck)
        out[mode] = rec
        wins[mode] = w2
        print(
            f"[bench] serving_kernel [{mode}]: "
            f"{rec['events_per_s']:.0f} ev/s "
            f"insert p99={rec['insert_latency_p99_ms']:.2f}ms "
            f"calls={rec['kernel_calls']}/{rec['batches']} batches "
            f"fallbacks={rec['kernel_fallbacks']}", file=sys.stderr,
        )
    out["wins2_parity"] = wins["kernel"] == wins["xla"]
    assert out["wins2_parity"], "serving_kernel parity broke"
    out["kernel_calls_per_batch"] = round(
        out["kernel"]["kernel_calls"] / out["kernel"]["batches"], 3)
    assert out["kernel_calls_per_batch"] == 1.0, (
        "fused path dispatched more than one kernel per micro-batch")
    assert out["kernel"]["kernel_fallbacks"] == 0

    # ------------------------------------------------------------- #
    # fleet leg: T=256 Zipf 1.1 through the tenant-axis kernel       #
    # ------------------------------------------------------------- #
    fs, fl, ft = make_tenant_stream(fleet_events, fleet_tenants,
                                    skew=skew, seed=seed)
    fs = fs.astype(np.float32)

    def _drive_fleet(ck):
        fleet = TenantFleetIndex(compact_every=128, shards=shards,
                                 count_kernel=ck)
        applies = 0
        lat_list = []
        t_all = time.perf_counter()
        for i in range(0, fleet_events, chunk):
            sl = slice(i, min(i + chunk, fleet_events))
            items = [(str(t), fs[sl][ft[sl] == t], fl[sl][ft[sl] == t])
                     for t in np.unique(ft[sl])]
            t0 = time.perf_counter()
            fleet.apply_inserts(items)
            lat_list.append(time.perf_counter() - t0)
            applies += 1
        wall = time.perf_counter() - t_all
        snap = fleet.metrics.snapshot()
        lat = np.asarray(lat_list) * 1e3
        rec = {
            "events_per_s": fleet_events / wall,
            "insert_latency_p99_ms": float(np.percentile(lat, 99)),
            "applies": applies,
            "fleet_count_calls":
                snap["fleet_count_calls_total"]["value"],
            "kernel_calls": snap["count_kernel_calls_total"]["value"],
            "kernel_fallbacks":
                snap["count_kernel_fallbacks_total"]["value"],
        }
        w2 = {t: fleet.wins2(t) for t in fleet.tenants()}
        fleet.close()
        return rec, w2

    fleet_out = {"tenants": fleet_tenants, "skew": skew,
                 "n_events_requested": fleet_req,
                 "n_events": fleet_events}
    fwins = {}
    for mode, ck in (("xla", False), ("kernel", True)):
        _drive_fleet(ck)
        rec, w2 = _drive_fleet(ck)
        fleet_out[mode] = rec
        fwins[mode] = w2
        print(
            f"[bench] serving_kernel fleet [{mode}]: "
            f"{rec['events_per_s']:.0f} ev/s "
            f"kernel_calls={rec['kernel_calls']} "
            f"applies={rec['applies']}", file=sys.stderr,
        )
    fleet_out["wins2_parity"] = fwins["kernel"] == fwins["xla"]
    assert fleet_out["wins2_parity"], "serving_kernel fleet parity broke"
    assert (fleet_out["kernel"]["kernel_calls"]
            == fleet_out["kernel"]["applies"]), (
        "fleet fused path dispatched more than one kernel per batch")
    out["fleet"] = fleet_out
    # flat fields for scripts/perf_gate.py stage banding [ISSUE 10]
    out["events_per_s"] = round(out["kernel"]["events_per_s"], 1)
    out["insert_latency_p99_ms"] = out["kernel"][
        "insert_latency_p99_ms"]
    out["p99_note"] = (
        "CPU caveat: off-TPU the kernel executes through the Pallas "
        "INTERPRETER (per-grid-step Python emulation), so the "
        "kernel-mode throughput/p99 here price the emulator, not the "
        "fusion — the deliverables on CPU are the bit-exact parity "
        "bits and kernel_calls_per_batch == 1.0 (one fused dispatch "
        "per device per micro-batch vs the XLA path's per-run "
        "searchsorted quartet + host tombstone pass); the throughput "
        "claim is gated on TPU, where the compare-count kernel runs "
        "the pallas_pairs grid at full VPU width"
    )
    print(
        f"[bench] serving_kernel: parity=True calls/batch="
        f"{out['kernel_calls_per_batch']} (interpret={interpret})",
        file=sys.stderr,
    )
    return out


def _controller_cell(n_events=30_000, tenants=32, skew=1.4,
                     queue_size=128, seed=0):
    """Control-plane defense cell [ISSUE 11]: the same Zipf flash-
    crowd stream (hot head, reject policy, small queue, UNBOUNDED
    submission — the replay thread outruns the batcher, so overload is
    real) replayed twice. Uncontrolled, the fleet sheds with hard
    ``BackpressureError``/quota rejects and typically breaches its
    saturation SLO; controlled, the ``FleetController`` throttles the
    head typed (``TenantThrottledError`` + retry hint) before the
    breach. The record prices the trade: events/s, typed-vs-hard shed
    split, SLO verdicts, actuation counts — and the per-tenant oracle
    parity guardrail runs whenever only typed sheds occurred."""
    from tuplewise_tpu.serving import (
        ServingConfig, TenancyConfig, make_tenant_stream, replay_fleet,
    )

    scores, labels, tids = make_tenant_stream(
        n_events, tenants, skew=skew, seed=seed)
    cfg = ServingConfig(queue_size=queue_size, policy="reject",
                        budget=16, flush_timeout_s=0.0005,
                        max_batch=128)
    slo = {"objectives": [
        {"name": "queue_sat", "type": "saturation",
         "metric": "queue_depth_live", "capacity": "queue_size",
         "max_fraction": 0.8},
        {"name": "no_hard_rejects", "type": "counter_max",
         "metric": "rejected_total", "max": 0},
    ]}
    ctl = {"knobs": ["shed", "flush"], "cooldown_s": 0.0,
           "up_ticks": 1, "down_ticks": 8, "throttle_s": 0.2}
    cells = {}
    for name, spec in (("controlled", ctl), ("uncontrolled", None)):
        rec = replay_fleet(
            scores, labels, tids, config=cfg,
            tenancy=TenancyConfig(max_tenants=tenants + 8,
                                  tenant_quota=4096),
            chunk=4, slo_spec=slo, controller_spec=spec,
            metrics_every_s=0.02, oracle_check=True)
        if "tenant_auc_max_abs_err" in rec:
            assert rec["tenant_auc_max_abs_err"] < 1e-6, (
                f"controller cell parity broke ({name}): "
                f"{rec['tenant_auc_max_abs_err']}")
        cells[name] = {
            "events_per_s": round(rec["events_per_s"], 1),
            "events_applied": rec["events_applied"],
            "events_tenant_throttled": rec["events_tenant_throttled"],
            "events_rejected": rec["events_rejected"],
            "events_tenant_rejected": rec["events_tenant_rejected"],
            "slo_healthy": rec["slo"]["healthy"],
            "actuations": (rec.get("controller") or {}).get(
                "actuations_total", 0),
            "tenant_auc_max_abs_err": rec.get("tenant_auc_max_abs_err"),
        }
        print(f"[bench] controller_defense {name}: "
              f"{rec['events_per_s']:.0f} ev/s "
              f"throttled={rec['events_tenant_throttled']} "
              f"rejected={rec['events_rejected']} "
              f"healthy={rec['slo']['healthy']}", file=sys.stderr)
    c, u = cells["controlled"], cells["uncontrolled"]
    shed_c = c["events_tenant_throttled"] + c["events_rejected"]
    return {"n_events": n_events, "tenants": tenants, "skew": skew,
            "queue_size": queue_size, "cells": cells,
            # the headline: what fraction of inevitable overload shed
            # became a typed, retry-after-hinted throttle instead of a
            # hard reject (1.0 = nobody saw BackpressureError)
            "typed_shed_fraction": (
                round(c["events_tenant_throttled"] / shed_c, 4)
                if shed_c else None),
            "hard_rejects_controlled": c["events_rejected"],
            "hard_rejects_uncontrolled": u["events_rejected"],
            "note": (
                "unbounded submission floods faster than any real "
                "client; the deterministic keeps-the-SLO-healthy "
                "acceptance lives in scripts/controller_smoke.py and "
                "tests/test_control.py — this cell prices the typed-"
                "vs-hard shed split under a worst-case open loop"),
            }


def _streaming_main(args):
    import uuid

    chaos = None
    if args.chaos:
        from tuplewise_tpu.testing.chaos import FaultInjector

        chaos = FaultInjector.from_spec(
            args.chaos_spec or _CHAOS_BENCH_SPEC)
    # run identity [ISSUE 7 satellite]: one id per bench invocation,
    # stamped (with the config digest replay adds) into every JSONL
    # row this run appends — scripts/perf_gate.py joins history on it
    run_id = uuid.uuid4().hex[:12]
    obs = {"run_id": run_id}
    if args.trace_out:
        obs["trace_out"] = args.trace_out
    if args.metrics_out:
        obs["metrics_out"] = args.metrics_out
        obs["metrics_every_s"] = args.metrics_every
    if args.profile_dir:
        obs["profile_dir"] = args.profile_dir
    if args.slo_spec:
        obs["slo_spec"] = args.slo_spec
    if args.prof or args.prof_out:
        # host-tax sampling profiler [ISSUE 14]: brackets only the
        # main timed run (replay starts/stops it around the window)
        obs["prof"] = True
        obs["prof_out"] = args.prof_out
    rec, base, sync = _streaming_events_per_sec(
        n_events=args.n_events, budget=args.budget,
        max_batch=args.max_batch, window=args.window,
        baseline_events=args.baseline_events,
        bg_compact=not args.sync_compact,
        max_inflight=args.max_inflight, chaos=chaos, obs=obs,
    )
    out = {
        "metric": "events/sec",
        "value": round(rec["events_per_s"], 1),
        "unit": "events/s",
        "run_id": run_id,
        "config_digest": rec.get("config_digest"),
        "vs_baseline": round(rec["events_per_s"] / base["events_per_s"], 2),
        "vs_baseline_note": (
            "same request path with the dynamic batcher disabled "
            "(max_batch=1): the coalescing speedup, like-for-like"
        ),
        "latency_p50_ms": rec["latency_p50_ms"],
        "latency_p99_ms": rec["latency_p99_ms"],
        "insert_latency_p50_ms": rec["insert_latency_p50_ms"],
        "insert_latency_p95_ms": rec["insert_latency_p95_ms"],
        "insert_latency_p99_ms": rec["insert_latency_p99_ms"],
        "compactions": rec["compactions"],
        "compaction_pause_p99_ms": rec["compaction_pause_p99_ms"],
        # per-stage p99 attribution [ISSUE 6]: where the insert p99
        # actually goes (queue wait vs index vs wal vs snapshot)
        "insert_stage_p99_ms": rec.get("insert_stage_p99_ms"),
        "stage_attribution": rec.get("stage_attribution"),
        # host-tax ledger [ISSUE 14]: the wall-clock split (host
        # Python vs device vs compile vs GC) the one-dispatch serving
        # core will be measured against; also stamped as its own
        # serving.jsonl stage row for the perf gate
        "host_tax": rec.get("host_tax"),
        "trace_out": rec.get("trace_out"),
        "metrics_out": rec.get("metrics_out"),
        "bg_compact": not args.sync_compact,
        "max_inflight": args.max_inflight,
        "mean_batch_fill": rec["mean_batch_fill"],
        "auc_abs_err": rec.get("auc_abs_err"),
        "n_events": rec["n_events"],
    }
    if chaos is not None:
        # the bench doubles as a chaos harness [ISSUE 3]: throughput
        # under a seeded fault schedule, plus the recovery counters and
        # the (admitted-events) oracle parity in the same record
        out["faults"] = rec.get("faults")
        out["events_poison_rejected"] = rec.get("events_poison_rejected")
    if rec.get("slo") is not None:
        # live SLO verdicts [ISSUE 7]: the bench run judged by the
        # same objectives a serve deploy would carry
        out["slo"] = rec["slo"]
    if sync is not None:
        out["sync_compact_insert_p99_ms"] = sync["insert_latency_p99_ms"]
        out["sync_compact_pause_p99_ms"] = sync["compaction_pause_p99_ms"]
        if rec["insert_latency_p99_ms"]:
            out["p99_insert_vs_sync_compact"] = round(
                sync["insert_latency_p99_ms"]
                / rec["insert_latency_p99_ms"], 2)
        out["p99_note"] = (
            "p99_insert_vs_sync_compact: same config with compaction "
            "forced back onto the batcher thread — the pause the "
            "background compactor removes from the request path"
        )
    if args.delta_bench_n:
        # delta-compaction byte budget [ISSUE 5]: bytes shipped per
        # minor compaction, delta mode vs the PR 2 full re-placement,
        # at n=10^6 S=4 by default (the acceptance cell)
        cell = _delta_compaction_cell(
            n_events=args.delta_bench_n, shards=args.delta_bench_shards)
        if cell is not None:
            out["delta_compaction"] = cell
    if args.tenant_bench_n:
        # multi-tenant fleet cell [ISSUE 8]: T=1/32/256 (plus
        # --tenants when given) over the same Zipf stream
        counts = sorted({1, 32, 256}
                        | ({args.tenants} if args.tenants > 1 else set()))
        out["multi_tenant"] = _multi_tenant_cell(
            n_events=args.tenant_bench_n, tenant_counts=counts,
            skew=args.tenant_skew, max_batch=args.max_batch,
            max_inflight=args.max_inflight)
    if args.fleet_bench_n:
        # incremental fleet cell [ISSUE 9]: dirty-row placement +
        # whale promotion vs the PR 8 full-pack path at T=256
        cell = _fleet_incremental_cell(
            n_events=args.fleet_bench_n,
            tenants=args.fleet_bench_tenants,
            shards=args.fleet_bench_shards)
        if cell is not None:
            out["fleet_incremental"] = cell
    if args.kernel_bench_n:
        # Pallas-fused counts cell [ISSUE 10]: XLA vs kernel at
        # n=1e6 S=2 delta tiers + fleet T=256 Zipf 1.1 (parity +
        # one-dispatch witness; throughput claim gated on TPU)
        cell = _serving_kernel_cell(
            n_events=args.kernel_bench_n,
            shards=args.kernel_bench_shards,
            fleet_tenants=args.fleet_bench_tenants)
        if cell is not None:
            out["serving_kernel"] = cell
    if args.controller_bench_n:
        # control-plane defense cell [ISSUE 11]: typed pre-breach
        # shedding vs the uncontrolled hard-reject flood
        out["controller_defense"] = _controller_cell(
            n_events=args.controller_bench_n)
    if rec.get("prof_out"):
        out["prof_out"] = rec["prof_out"]
        out["prof_samples"] = rec.get("prof_samples")
        out["prof_overhead_fraction"] = rec.get("prof_overhead_fraction")
    print(json.dumps(out))
    if args.out:
        rows = [dict(out, stage="bench_streaming")]
        if out.get("host_tax"):
            # the stamped host-tax row [ISSUE 14]: host_fraction /
            # device_fraction / compile_events / gc_pause_p99 join the
            # perf-gate trajectory under their own stage
            rows.append(dict(out["host_tax"], stage="host_tax",
                             run_id=run_id,
                             config_digest=out.get("config_digest")))
        if out.get("delta_compaction"):
            rows.append(dict(out["delta_compaction"],
                             stage="delta_compaction", run_id=run_id))
        if out.get("multi_tenant"):
            rows.append(dict(out["multi_tenant"], stage="multi_tenant",
                             run_id=run_id,
                             config_digest=out.get("config_digest")))
        if out.get("fleet_incremental"):
            rows.append(dict(out["fleet_incremental"],
                             stage="fleet_incremental", run_id=run_id,
                             config_digest=out.get("config_digest")))
        if out.get("serving_kernel"):
            rows.append(dict(out["serving_kernel"],
                             stage="serving_kernel", run_id=run_id,
                             config_digest=out.get("config_digest")))
        if out.get("controller_defense"):
            rows.append(dict(out["controller_defense"],
                             stage="controller_defense", run_id=run_id,
                             config_digest=out.get("config_digest")))
        with open(args.out, "a", encoding="utf-8") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--streaming", action="store_true",
                    help="benchmark the micro-batched serving path "
                         "instead of the batch pair kernel")
    ap.add_argument("--n-events", type=int, default=300_000)
    ap.add_argument("--budget", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--baseline-events", type=int, default=2_000)
    ap.add_argument("--max-inflight", type=int, default=64,
                    help="bound outstanding requests (closed-loop load;"
                         " percentiles measure per-event cost, not"
                         " backlog)")
    ap.add_argument("--sync-compact", action="store_true",
                    help="compact on the batcher thread (pre-PR2 "
                         "behavior); skips the sync comparison run")
    ap.add_argument("--delta-bench-n", type=int, default=1_000_000,
                    help="events for the delta-compaction byte cell "
                         "(bytes/minor-compaction, delta vs host-merge "
                         "mode, sharded index driven directly); 0 "
                         "skips it")
    ap.add_argument("--delta-bench-shards", type=int, default=4)
    ap.add_argument("--tenant-bench-n", type=int, default=20_000,
                    help="events per multi-tenant fleet cell "
                         "(events/s + insert p99 at T=1/32/256 through "
                         "the MultiTenantEngine, per-tenant oracle "
                         "parity asserted); 0 skips it [ISSUE 8]")
    ap.add_argument("--tenants", type=int, default=0,
                    help="with --streaming: add this tenant count to "
                         "the multi_tenant cell's T ladder (fleet "
                         "load generation; see also replay --tenants)")
    ap.add_argument("--tenant-skew", type=float, default=1.0,
                    help="Zipf exponent of the multi-tenant cell's "
                         "tenant assignment (0 = uniform)")
    ap.add_argument("--fleet-bench-n", type=int, default=40_000,
                    help="events for the incremental-fleet cell "
                         "(dirty-row placement + whale promotion vs "
                         "the full-pack path at T=256, Zipf 1.1, "
                         "driven directly through TenantFleetIndex); "
                         "0 skips it [ISSUE 9]")
    ap.add_argument("--fleet-bench-tenants", type=int, default=256)
    ap.add_argument("--fleet-bench-shards", type=int, default=2)
    ap.add_argument("--kernel-bench-n", type=int, default=1_000_000,
                    help="events for the Pallas-fused counts cell "
                         "(XLA vs count_kernel=True at S=2 delta "
                         "tiers + fleet T=256: bit parity, ONE kernel "
                         "dispatch per device per micro-batch; "
                         "auto-shrunk off-TPU where the kernel runs "
                         "in interpret mode); 0 skips it [ISSUE 10]")
    ap.add_argument("--kernel-bench-shards", type=int, default=2)
    ap.add_argument("--controller-bench-n", type=int, default=30_000,
                    help="events for the control-plane defense cell "
                         "[ISSUE 11]: a Zipf flash crowd replayed with "
                         "and without the FleetController — typed "
                         "pre-breach throttling vs the hard-reject "
                         "flood, SLO verdicts both ways (0 skips)")
    ap.add_argument("--out", type=str, default=None,
                    help="with --streaming: also append the record "
                         "(and the delta cell) as JSONL rows, e.g. "
                         "results/serving.jsonl")
    ap.add_argument("--chaos", action="store_true",
                    help="run under a seeded fault schedule: with "
                         "--streaming, the serving schedule (compactor "
                         "crash + batcher crash + poison); without, a "
                         "batch-path device-loss schedule through the "
                         "mesh Monte-Carlo sweep (elastic re-shard) — "
                         "recovery counters ride in the record either "
                         "way")
    ap.add_argument("--chaos-spec", type=str, default=None,
                    help="override the default --chaos schedule (JSON "
                         "inline, @file, or *.json path)")
    ap.add_argument("--slo-spec", type=str, default=None,
                    help="with --streaming: evaluate these SLO "
                         "objectives (obs.slo spec: JSON inline, "
                         "@file, or *.json) live during the main run; "
                         "verdicts land in the record's 'slo' block")
    ap.add_argument("--trace-out", type=str, default=None,
                    help="with --streaming: export the span trace of "
                         "the main timed run (*.jsonl = span JSONL, "
                         "else Chrome trace JSON for perfetto)")
    ap.add_argument("--metrics-out", type=str, default=None,
                    help="with --streaming: stream periodic registry "
                         "snapshots (JSONL) during the main run")
    ap.add_argument("--metrics-every", type=float, default=1.0)
    ap.add_argument("--profile-dir", type=str, default=None,
                    help="with --streaming: bracket the main run in a "
                         "jax.profiler trace written here")
    ap.add_argument("--prof", action="store_true",
                    help="with --streaming: run the host-tax sampling "
                         "profiler over the main timed run (<= 5%% "
                         "guarded overhead) [ISSUE 14]")
    ap.add_argument("--prof-out", type=str, default=None,
                    help="with --streaming: write the profile here "
                         "(*.collapsed/*.txt = folded stacks, else "
                         "speedscope JSON; implies --prof); digest "
                         "with scripts/trace_summary.py")
    args = ap.parse_args()
    if args.streaming:
        _streaming_main(args)
        return

    tpu = _tpu_pairs_per_sec()
    rec = {
        "metric": "pairs/sec/chip",
        "value": round(tpu, 1),
        "unit": "pairs/s",
    }
    try:
        ring = _ring_pairs_per_sec()
        print(
            f"[bench] ring/raw ratio = {ring / tpu:.2f}", file=sys.stderr
        )
        rec["ring_over_raw"] = round(ring / tpu, 3)
    except Exception as e:  # pragma: no cover - diagnostic only
        print(f"[bench] ring diagnostic failed ({e!r})", file=sys.stderr)
    try:
        anyn = _anyn_pairs_per_sec()
        if anyn is not None:
            rec["anyn_pairs_per_s"] = round(anyn, 1)
            rec["anyn_n"] = (1 << 20) + 64
    except Exception as e:  # pragma: no cover - diagnostic only
        print(f"[bench] any-n diagnostic failed ({e!r})", file=sys.stderr)
    if args.chaos:
        try:
            rec["batch_chaos"] = _batch_chaos_record(args.chaos_spec)
        except Exception as e:  # pragma: no cover - diagnostic only
            print(f"[bench] batch chaos failed ({e!r})", file=sys.stderr)
    ref = _numpy_pairs_per_sec()
    rec["vs_baseline"] = round(tpu / ref, 2)
    # the caveat the dashboard needs, IN the record, not just stderr
    # [VERDICT r3 weak #4 / next #8]: the two sides run different n
    rec["vs_baseline_note"] = (
        "self-baseline: frozen NumPy oracle on this host at n=16384 vs "
        "TPU at n=2^20 (reference repo shipped no numbers; round-over-"
        "round bookkeeping, not a like-for-like speedup)"
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
