"""CI observability smoke [ISSUE 6] — the acceptance harness.

A traced replay of a chaos schedule must produce, in one run:

(a) a perfetto-loadable Chrome trace whose per-stage spans sum to
    >= 95% of each measured insert latency (they tile the request's
    lifetime, so the real figure is ~100%);
(b) a ``metrics.jsonl`` with >= 2 periodic whole-registry snapshots,
    each stamped with wall+monotonic timestamps, platform, and config
    digest;
(c) a flight-recorder dump in which every injected fault and every
    compaction / major-merge / heal event appears exactly once, with a
    correlating (non-null) trace id on each injected fault;
(d) [ISSUE 14] a host-tax ledger whose bucket sums tile the measured
    insert latency EXACTLY (coverage == 1.0), >= 1 tail exemplar
    captured under the injected latency chaos (a scheduled batcher
    ``delay`` stalls queued requests past ``tail_exemplar_ms``), and a
    schema-valid speedscope + collapsed-stack profiler export —

while the span-JSONL export stays digestible by
``scripts/trace_summary.py`` (which must also digest the collapsed
stacks into the host-tax table). Any breach exits nonzero; the summary
row (stage "obs_smoke") lands in a JSONL the workflow uploads.

Usage: python scripts/obs_smoke.py [--n-events 4000]
                                   [--out results/obs_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the batcher 'delay' is the injected latency chaos [ISSUE 14]: a 60ms
# stall between batches ages every queued request past the 25ms
# exemplar threshold, so >= 1 tail_exemplar MUST land in the flight
# ring (the doctor resolves a delay fault as latency_absorbed)
TAIL_EXEMPLAR_MS = 25.0
CHAOS = {"faults": [
    {"point": "compactor_build", "on_call": 1, "action": "error"},
    {"point": "batcher", "on_call": 5, "action": "delay",
     "seconds": 0.06},
    {"point": "batcher", "on_call": 15, "action": "error"},
    {"point": "poison", "at_events": [150, 900], "value": "nan"},
]}

# live SLO objectives [ISSUE 7]: generous bounds a healthy CPU smoke
# always clears — the smoke asserts the EVALUATION ran (gauges + a
# healthy verdict), the breach path is pinned by tests/test_slo.py
SLO = {"objectives": [
    {"name": "insert_p99", "type": "latency",
     "metric": "insert_latency_s", "quantile": "p99",
     "threshold_ms": 2000.0},
    {"name": "availability", "type": "error_rate",
     "errors": ["rejected_total", "dropped_total",
                "deadline_expired_total"],
     "total": "requests_insert_total", "objective": 0.99,
     "windows": [{"window_s": 0.5, "burn": 20.0},
                 {"window_s": 2.0, "burn": 5.0}]},
    {"name": "no_heal_exhaustion", "type": "counter_max",
     "metric": "heal_exhausted_total", "max": 0},
    {"name": "queue_saturation", "type": "saturation",
     "metric": "queue_depth_live", "capacity": "queue_size",
     "max_fraction": 0.99},
]}


def _fail(msg: str) -> int:
    print(f"OBS SMOKE FAIL: {msg}", file=sys.stderr)
    return 1


def _check_chrome(path: str) -> int:
    """Chrome trace-event schema: the contract perfetto loads."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return _fail("trace has no traceEvents list")
    n_x = 0
    for e in evs:
        if not isinstance(e, dict) or "ph" not in e:
            return _fail(f"malformed trace event: {e!r}")
        if e["ph"] == "X":
            n_x += 1
            for k in ("name", "pid", "tid", "ts", "dur"):
                if k not in e:
                    return _fail(f"X event missing {k!r}: {e!r}")
            if not (isinstance(e["ts"], (int, float))
                    and isinstance(e["dur"], (int, float))
                    and e["dur"] >= 0):
                return _fail(f"X event bad ts/dur: {e!r}")
        elif e["ph"] == "M":
            if "name" not in e or "args" not in e:
                return _fail(f"M event missing name/args: {e!r}")
    if n_x == 0:
        return _fail("trace has no complete (X) events")
    print(f"  chrome trace OK: {n_x} X events", file=sys.stderr)
    return 0


def _check_stage_sums(spans_path: str) -> int:
    """Per-insert attribution: child stage spans must sum to >= 95% of
    each request.insert root span's duration."""
    from scripts.trace_summary import load_spans

    spans = load_spans(spans_path)
    children = {}
    for s in spans:
        if s.get("parent_id") is not None:
            children.setdefault(s["parent_id"], 0.0)
            children[s["parent_id"]] += s["dur_s"]
    roots = [s for s in spans if s["name"] == "request.insert"
             and s["parent_id"] is None]
    if not roots:
        return _fail("no request.insert root spans in the trace")
    bad = 0
    for r in roots:
        if r["dur_s"] <= 0:
            continue
        cov = children.get(r["span_id"], 0.0) / r["dur_s"]
        if cov < 0.95:
            bad += 1
    if bad:
        return _fail(f"{bad}/{len(roots)} insert traces have stage "
                     f"spans summing to < 95% of the measured latency")
    print(f"  stage sums OK: {len(roots)} insert traces all >= 95%",
          file=sys.stderr)
    return 0


def _check_metrics(path: str) -> int:
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if len(rows) < 2:
        return _fail(f"metrics.jsonl has {len(rows)} snapshots (< 2)")
    for r in rows:
        for k in ("seq", "ts_wall", "ts_mono", "platform",
                  "config_digest", "metrics"):
            if k not in r:
                return _fail(f"metrics row missing {k!r}")
    if rows[-1]["metrics"].get("events_total", {}).get("value", 0) < 1:
        return _fail("final metrics snapshot shows no applied events")
    print(f"  metrics OK: {len(rows)} snapshots", file=sys.stderr)
    return 0


def _check_slo(rec: dict, metrics_path: str) -> int:
    """Live SLO evaluation [ISSUE 7]: the verdict block exists, every
    objective was judged, nothing breached (the bounds are generous),
    and the slo_* gauges landed in the metrics stream itself."""
    slo = rec.get("slo")
    if not slo:
        return _fail("record has no slo block despite slo_spec")
    if set(slo["objectives"]) != {o["name"] for o in SLO["objectives"]}:
        return _fail(f"slo objectives mismatch: {sorted(slo['objectives'])}")
    if slo["evaluations"] < 2:
        return _fail(f"slo evaluated only {slo['evaluations']} times")
    if not slo["healthy"]:
        return _fail(f"healthy smoke breached SLOs: {slo['objectives']}")
    with open(metrics_path, "r", encoding="utf-8") as f:
        last = None
        for line in f:
            if line.strip():
                last = line
    m = json.loads(last)["metrics"]
    gauges = [k for k in m if k.startswith("slo_breached{")]
    if len(gauges) != len(SLO["objectives"]):
        return _fail(f"expected {len(SLO['objectives'])} slo_breached "
                     f"gauges in metrics.jsonl, found {gauges}")
    if any(m[g]["value"] != 0.0 for g in gauges):
        return _fail("slo_breached gauge stuck nonzero on healthy run")
    print(f"  slo OK: {len(slo['objectives'])} objectives x "
          f"{slo['evaluations']} evaluations, healthy", file=sys.stderr)
    return 0


def _check_host_tax(rec: dict, flight_path: str) -> int:
    """[ISSUE 14] Ledger tiling (coverage == 1.0 up to float
    rounding), sane fraction split, and >= 1 tail exemplar (with its
    full bucket ledger) captured under the injected latency chaos."""
    ht = rec.get("host_tax")
    if not ht:
        return _fail("record has no host_tax block")
    cov = ht.get("coverage")
    if cov is None or abs(cov - 1.0) > 1e-6:
        return _fail(f"ledger coverage {cov!r} != 1.0 — an interval "
                     "escaped the bucket tiling")
    fracs = (ht.get("host_fraction"), ht.get("device_fraction"))
    if any(f is None or not 0.0 <= f <= 1.0 for f in fracs):
        return _fail(f"host/device fractions out of range: {fracs}")
    if not ht.get("waves"):
        return _fail("ledger recorded no waves")
    from tuplewise_tpu.obs.flight import FlightRecorder

    exemplars = [e for e in FlightRecorder.load_dump(
        flight_path)["events"] if e["kind"] == "tail_exemplar"]
    if not exemplars:
        return _fail("no tail_exemplar under the injected 60ms delay "
                     f"(threshold {TAIL_EXEMPLAR_MS}ms)")
    for e in exemplars:
        if e.get("lat_ms", 0) < TAIL_EXEMPLAR_MS:
            return _fail(f"exemplar below threshold: {e}")
        b = e.get("buckets")
        if not b or "queue_wait" not in b or "host_python" not in b:
            return _fail(f"exemplar missing its bucket ledger: {e}")
    print(f"  host tax OK: coverage={cov:.9f} host="
          f"{fracs[0]:.3f} device={fracs[1]:.3f} "
          f"exemplars={len(exemplars)}", file=sys.stderr)
    return 0


def _check_speedscope(path: str) -> int:
    """[ISSUE 14] The profiler's speedscope export must be schema-
    valid: shared frame table, one sampled profile, index-consistent
    samples, weights aligned 1:1."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if "speedscope" not in doc.get("$schema", ""):
        return _fail(f"speedscope $schema missing: {doc.get('$schema')}")
    frames = doc.get("shared", {}).get("frames")
    if not isinstance(frames, list) or not frames \
            or not all(isinstance(fr, dict) and "name" in fr
                       for fr in frames):
        return _fail("speedscope shared.frames malformed")
    profs = doc.get("profiles")
    if not isinstance(profs, list) or not profs:
        return _fail("speedscope has no profiles")
    p = profs[0]
    if p.get("type") != "sampled" or p.get("unit") != "seconds":
        return _fail(f"speedscope profile wrong type/unit: {p.get('type')}"
                     f"/{p.get('unit')}")
    samples, weights = p.get("samples"), p.get("weights")
    if not isinstance(samples, list) or not samples \
            or len(samples) != len(weights):
        return _fail("speedscope samples/weights misaligned")
    nf = len(frames)
    for s in samples:
        if not s or any(not isinstance(i, int) or not 0 <= i < nf
                        for i in s):
            return _fail(f"speedscope sample indexes out of range: {s}")
    if abs(sum(weights) - p.get("endValue", -1)) > 1e-6:
        return _fail("speedscope endValue != sum(weights)")
    print(f"  speedscope OK: {len(samples)} samples over {nf} frames",
          file=sys.stderr)
    return 0


def _check_flight(path: str, rec: dict) -> int:
    from tuplewise_tpu.obs.flight import FlightRecorder

    dump = FlightRecorder.load_dump(path)
    evs = dump["events"]
    kinds = {}
    for e in evs:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    # every injected fault appears exactly once, with a trace id
    injected = [e for e in evs if e["kind"] == "chaos_inject"]
    scheduled = [f for f in CHAOS["faults"] if f["point"] != "poison"]
    if len(injected) != len(scheduled):
        return _fail(f"{len(injected)} chaos_inject events for "
                     f"{len(scheduled)} scheduled faults")
    seen_points = sorted(e["point"] for e in injected)
    if seen_points != sorted(f["point"] for f in scheduled):
        return _fail(f"chaos points mismatch: {seen_points}")
    for e in injected:
        if e.get("trace_id") is None:
            return _fail(f"chaos_inject without a trace id: {e}")
    # every compaction / major merge / heal appears exactly once:
    # the flight counts must equal the metric counters
    m = rec["report"]
    pairs = (("compaction-ish", kinds.get("compaction", 0)
              + kinds.get("major_merge", 0), m["compactions_total"]),
             ("major_merge", kinds.get("major_merge", 0),
              m["major_merges_total"]),
             ("heal", kinds.get("heal", 0), m["reshard_events"]))
    for name, n_flight, n_metric in pairs:
        if n_flight != n_metric:
            return _fail(f"{name}: {n_flight} flight events vs "
                         f"{n_metric} counted")
    # sequence numbers are strictly increasing (ring integrity)
    seqs = [e["seq"] for e in evs]
    if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
        return _fail("flight sequence numbers not strictly increasing")
    print(f"  flight OK: {kinds}", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-events", type=int, default=4_000)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "obs_smoke.jsonl"))
    ap.add_argument("--results-dir", type=str,
                    default=os.path.join(REPO, "results"))
    args = ap.parse_args(argv)
    os.makedirs(args.results_dir, exist_ok=True)
    trace_json = os.path.join(args.results_dir, "obs_trace.json")
    spans_jsonl = os.path.join(args.results_dir, "obs_spans.jsonl")
    metrics_out = os.path.join(args.results_dir, "metrics.jsonl")
    flight_out = os.path.join(args.results_dir, "obs_flight.jsonl")
    prof_speedscope = os.path.join(args.results_dir,
                                   "obs_prof.speedscope.json")
    prof_collapsed = os.path.join(args.results_dir,
                                  "obs_prof.collapsed")
    for p in (trace_json, spans_jsonl, metrics_out, flight_out,
              prof_speedscope, prof_collapsed):
        if os.path.exists(p):
            os.unlink(p)

    from tuplewise_tpu.obs.prof import SamplingProfiler
    from tuplewise_tpu.obs.tracing import Tracer
    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(args.n_events, pos_frac=0.5,
                                 separation=1.0, seed=0)
    cfg = ServingConfig(policy="block", flush_timeout_s=0.002,
                        compact_every=256, bg_compact=True,
                        tail_exemplar_ms=TAIL_EXEMPLAR_MS)
    tracer = Tracer(capacity=1 << 17)
    profiler = SamplingProfiler()   # [ISSUE 14]: the profiler leg
    rec = replay(scores, labels, config=cfg, max_inflight=256,
                 chaos=CHAOS, tracer=tracer, trace_out=trace_json,
                 metrics_out=metrics_out, metrics_every_s=0.2,
                 flight_out=flight_out, slo_spec=SLO,
                 prof=profiler, prof_out=prof_speedscope)
    profiler.export_collapsed(prof_collapsed)
    tracer.export_jsonl(spans_jsonl)
    if tracer.dropped:
        return _fail(f"tracer ring dropped {tracer.dropped} spans — "
                     "raise capacity, the checks below would lie")

    rc = (_check_chrome(trace_json)
          or _check_stage_sums(spans_jsonl)
          or _check_metrics(metrics_out)
          or _check_flight(flight_out, rec)
          or _check_slo(rec, metrics_out)
          or _check_host_tax(rec, flight_out)
          or _check_speedscope(prof_speedscope))
    if rc:
        return rc

    # the summarizer must digest every export (the CI artifacts a
    # reviewer actually reads): spans, Chrome trace, and the profiler
    # leg's host-tax table [ISSUE 14]
    from scripts.trace_summary import summarize_collapsed, summarize_spans

    summary = summarize_spans(spans_jsonl, 10)
    summarize_spans(trace_json, 5)
    host_tax_table = summarize_collapsed(prof_collapsed, 8)
    print(summary, file=sys.stderr)
    print(host_tax_table, file=sys.stderr)

    row = {
        "stage": "obs_smoke",
        "n_events": args.n_events,
        "events_per_s": rec["events_per_s"],
        "insert_stage_p99_ms": rec["insert_stage_p99_ms"],
        "stage_coverage": rec["stage_attribution"]["coverage"],
        "trace_spans": rec["trace_spans"],
        "flight_events": rec["flight_events"],
        "auc_abs_err": rec.get("auc_abs_err"),
        "slo_healthy": rec["slo"]["healthy"],
        "slo_evaluations": rec["slo"]["evaluations"],
        # host-tax leg [ISSUE 14]
        "host_tax_coverage": rec["host_tax"]["coverage"],
        "host_fraction": rec["host_tax"]["host_fraction"],
        "device_fraction": rec["host_tax"]["device_fraction"],
        "compile_events": rec["host_tax"]["compile_events"],
        "tail_exemplars": rec["host_tax"]["tail_exemplars"],
        "prof_samples": rec.get("prof_samples"),
        "prof_overhead_fraction": rec.get("prof_overhead_fraction"),
    }
    with open(args.out, "w", encoding="utf-8") as f:
        f.write(json.dumps(row) + "\n")
    print(f"obs smoke OK: {rec['trace_spans']} spans, coverage="
          f"{row['stage_coverage']:.6f}, ledger="
          f"{row['host_tax_coverage']:.6f}, flight="
          f"{rec['flight_events']} -> {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
