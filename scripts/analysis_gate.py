"""CI leg for the static invariant checkers [ISSUE 12]: run
``tuplewise check`` in-process, write the JSON report artifact, and
fail on any unwaived finding, waiver-file error, parse error, or
import cycle.

The ratchet lives in the waiver semantics themselves (each waiver
absorbs a bounded count — see analysis/waivers.py), so this gate has
no separate baseline file to drift: a new violation anywhere fails
even where old waived ones exist.

Usage: python scripts/analysis_gate.py [--out results/analysis_report.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "analysis_report.json"))
    args = ap.parse_args(argv)

    from tuplewise_tpu.analysis.runner import run_checks

    report = run_checks(root=REPO)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)

    s = report["summary"]
    print(f"ANALYSIS GATE: {s['files_analyzed']} files, "
          f"{s['findings_total']} findings "
          f"({s['waived']} waived, {s['unwaived']} unwaived), "
          f"{len(report['import_cycles'])} import cycles, "
          f"{len(report['dead_symbols'])} dead public symbols "
          f"(warn-only)", file=sys.stderr)
    for f_ in report["findings"]:
        print(f"  UNWAIVED {f_['rule']}: {f_['file']}:{f_['line']} "
              f"[{f_['symbol']}] {f_['message']}", file=sys.stderr)
    if report.get("waiver_error"):
        print(f"  WAIVER FILE ERROR: {report['waiver_error']}",
              file=sys.stderr)
    for w in report["unused_waivers"]:
        print(f"  stale waiver: {w['rule']} {w['file']} "
              f"[{w['symbol']}] (waivers.toml:{w['line']})",
              file=sys.stderr)
    # one machine-readable verdict line on stdout (the doctor/perf-gate
    # convention: tail -n 1 | json)
    print(json.dumps({"stage": "analysis_gate", "ok": report["ok"],
                      "unwaived": s["unwaived"],
                      "waived": s["waived"],
                      "unused_waivers": s["waivers_unused"]}))
    if not report["ok"]:
        print("ANALYSIS GATE FAIL (report in "
              f"{args.out})", file=sys.stderr)
        return 1
    print("ANALYSIS GATE OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
