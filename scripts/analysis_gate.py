"""CI leg for the static invariant checkers [ISSUE 12, dataflow tier
ISSUE 13, host-cost/lifecycle tier ISSUE 15]: run ``tuplewise check``
in-process, write the JSON report artifact (and optionally SARIF for
inline PR annotations), diff the overflow certificate AND the hotpath
cost certificate against their committed baselines, and fail on any
unwaived finding, waiver-file error, parse error, import cycle, or
certificate drift.

The finding ratchet lives in the waiver semantics (each waiver
absorbs a bounded count — analysis/waivers.py). Both certificates
HAVE baselines by design:

* ``tuplewise_tpu/analysis/exactness_bounds.toml`` — the int32 bound
  table is a function of the compile-ladder maxima, so a ladder bump
  that breaks int32 safety must fail with the violating bound NAMED.
* ``tuplewise_tpu/analysis/hotpath_budget.toml`` [ISSUE 15] — the
  per-request-path-root host-cost counters. A counter that GROWS (a
  per-event allocation/lock/dispatch added to the hot path) fails
  naming the root, the contributing sites, and the violated budget
  line. A counter that SHRINKS is the downward ratchet the
  one-dispatch refactor drives: the gate rewrites the budget file in
  place so the improvement is committed with the PR.

The gate also asserts the parse cache actually caches [ISSUE 15
satellite]: a second in-job corpus load must hit (> 0 hits) or the
gate fails — a cache that silently never hits is a perf regression
for every CI run after it.

Usage: python scripts/analysis_gate.py
           [--out results/analysis_report.json]
           [--sarif results/analysis_report.sarif]
           [--hotpath-out results/hotpath_certificate.json]
           [--update-hotpath-budget] [--no-cache]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE = os.path.join(
    REPO, "tuplewise_tpu", "analysis", "exactness_bounds.toml")
HOTPATH_BUDGET = os.path.join(
    REPO, "tuplewise_tpu", "analysis", "hotpath_budget.toml")

_SARIF_RULE_HELP = {
    "race-unguarded-shared":
        "attribute shared across thread roles with an unguarded "
        "access",
    "race-inconsistent-guard":
        "attribute shared across thread roles with no common guard "
        "lock",
    "count-float-taint":
        "float-tainted value flows into an integer win-count "
        "accumulator",
    "count-narrow-accumulator":
        "raw int32 device value accumulated without widening",
    "overflow-int32":
        "int32 accumulator bound exceeds 2^31-1 at ladder maxima",
    "overflow-unproved":
        "int32 accumulator the overflow classifier cannot bound",
    "hotpath-root-missing":
        "declared request-path root no longer defined in the corpus",
    "future-leak":
        "request futures can be stranded unresolved on an exception "
        "path",
    "future-double-resolve":
        "future resolution without done() guard or try arbitration "
        "in a multi-resolver class",
    "future-close-leak":
        "close() never reaches a drain that fails queued futures",
    "thread-undisciplined":
        "Thread/Timer neither daemonized nor joined/cancelled from a "
        "lifecycle method",
    "handle-leak":
        "file handle opened outside `with` with no owning close on "
        "the exception path",
    "error-unhandled-protocol":
        "typed serving error with no {\"error\": ...} wire handler",
    "error-not-doctor-visible":
        "typed serving error invisible to obs/report.py and "
        "obs/doctor.py",
    "error-undocumented":
        "typed serving error README/DESIGN never mention",
}


def to_sarif(report: dict) -> dict:
    """SARIF 2.1.0 — one run, one result per finding (waived findings
    ride along at 'note' level with a suppression record, so the PR
    annotation layer shows them greyed out, not red)."""
    rules = {}
    results = []

    def add(f: dict, level: str, suppressed: bool,
            reason: str = "") -> None:
        rid = f["rule"]
        rules.setdefault(rid, {
            "id": rid,
            "shortDescription": {
                "text": _SARIF_RULE_HELP.get(
                    rid, f"tuplewise invariant rule {rid}")},
        })
        res = {
            "ruleId": rid,
            "level": level,
            "message": {"text": f["message"]},
            "partialFingerprints": {
                "tuplewiseFingerprint/v1": f["fingerprint"]},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f["file"],
                        "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(int(f["line"]), 1)},
                }}],
        }
        if suppressed:
            res["suppressions"] = [{
                "kind": "external",
                "justification": reason or "waived in "
                "tuplewise_tpu/analysis/waivers.toml"}]
        results.append(res)

    for f in report["findings"]:
        add(f, "error", suppressed=False)
    for f in report.get("waived", ()):
        add(f, "note", suppressed=True, reason=f.get("reason", ""))
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tuplewise-check",
                "informationUri":
                    "docs/DESIGN.md#17-static-invariant-checks",
                "rules": sorted(rules.values(),
                                key=lambda r: r["id"]),
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "analysis_report.json"))
    ap.add_argument("--sarif", type=str, default=None,
                    help="also write a SARIF 2.1.0 report here "
                         "(uploaded next to the JSON so findings "
                         "render as inline PR annotations)")
    ap.add_argument("--hotpath-out", type=str,
                    default=os.path.join(REPO, "results",
                                         "hotpath_certificate.json"),
                    help="write the hotpath cost certificate artifact "
                         "here [ISSUE 15]")
    ap.add_argument("--update-hotpath-budget", action="store_true",
                    help="rewrite the committed hotpath budget from "
                         "the freshly derived certificate (first "
                         "generation / reviewed re-baseline)")
    ap.add_argument("--jobs", type=int, default=None,
                    help="worker processes for the passes (default "
                         "auto)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the content-sha parse cache")
    args = ap.parse_args(argv)

    from tuplewise_tpu.analysis import exactness, hotpath
    from tuplewise_tpu.analysis.runner import run_checks

    report = run_checks(root=REPO, use_cache=not args.no_cache,
                        jobs=args.jobs)

    # overflow-certificate baseline diff [ISSUE 13 satellite]: the
    # derived bound table must match the committed envelope exactly
    cert_errors = []
    if os.path.exists(BASELINE):
        with open(BASELINE, "r", encoding="utf-8") as f:
            cert_errors = exactness.compare_to_baseline(
                report["overflow_certificate"], f.read())
    else:
        cert_errors = [f"missing committed baseline {BASELINE}"]
    report["certificate_diff"] = cert_errors
    if cert_errors:
        report["ok"] = False

    # hotpath-budget diff [ISSUE 15]: growth fails naming root + site
    # + budget line; shrinkage ratchets the committed file downward.
    # The second-in-job cache probe runs FIRST — the budget rewrite
    # below changes the cache epoch, which must not void the probe.
    cache_second_hits = None
    if not args.no_cache:
        from tuplewise_tpu.analysis.cache import (
            ParseCache, compute_epoch,
        )
        from tuplewise_tpu.analysis.core import ModuleSet

        probe = ParseCache(REPO, epoch=compute_epoch(REPO))
        ModuleSet.from_repo(REPO, cache=probe)
        cache_second_hits = probe.stats()["hits"]
        if cache_second_hits <= 0:
            report["ok"] = False
            report.setdefault("gate_errors", []).append(
                "parse cache never hits: the second in-job corpus "
                "load re-parsed everything — the epoch/key logic "
                "broke (ISSUE 15 satellite contract)")

    hot_cert = report.get("hotpath_certificate")
    hot_errors, hot_shrinks = [], []
    if hot_cert is None:
        hot_errors = ["runner produced no hotpath certificate"]
    elif args.update_hotpath_budget:
        with open(HOTPATH_BUDGET, "w", encoding="utf-8") as f:
            f.write(hotpath.format_budget(hot_cert))
        print(f"hotpath budget rewritten: {HOTPATH_BUDGET}",
              file=sys.stderr)
    elif os.path.exists(HOTPATH_BUDGET):
        with open(HOTPATH_BUDGET, "r", encoding="utf-8") as f:
            hot_errors, hot_shrinks = hotpath.compare_to_budget(
                hot_cert, f.read())
        if not hot_errors and hot_shrinks:
            # the downward ratchet: commit the improvement
            with open(HOTPATH_BUDGET, "w", encoding="utf-8") as f:
                f.write(hotpath.format_budget(hot_cert))
    else:
        hot_errors = [f"missing committed budget {HOTPATH_BUDGET} — "
                      "generate it with --update-hotpath-budget and "
                      "commit after review"]
    report["hotpath_budget_diff"] = hot_errors
    report["hotpath_budget_ratchet"] = hot_shrinks
    if hot_errors:
        report["ok"] = False

    if args.hotpath_out and hot_cert is not None:
        os.makedirs(os.path.dirname(args.hotpath_out) or ".",
                    exist_ok=True)
        with open(args.hotpath_out, "w", encoding="utf-8") as f:
            json.dump({"stage": "hotpath_certificate",
                       "certificate": hot_cert,
                       "budget_diff": hot_errors,
                       "ratchet": hot_shrinks}, f, indent=2)

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
    if args.sarif:
        os.makedirs(os.path.dirname(args.sarif) or ".", exist_ok=True)
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(to_sarif(report), f, indent=2)

    s = report["summary"]
    c = s["cache"]
    t = s["timings"]
    print(f"ANALYSIS GATE: {s['files_analyzed']} files, "
          f"{s['findings_total']} findings "
          f"({s['waived']} waived, {s['unwaived']} unwaived), "
          f"{len(report['import_cycles'])} import cycles, "
          f"{len(report['dead_symbols'])} dead public symbols "
          f"(warn-only), cache {c['hits']}/{c['hits'] + c['misses']} "
          f"hits (2nd run {cache_second_hits}), "
          f"{t['total_s']:.2f}s jobs={t['jobs']}, certificate "
          f"{'OK' if not cert_errors else 'DRIFT'}, hotpath budget "
          f"{'OK' if not hot_errors else 'DRIFT'}"
          + (f" (ratcheted {len(hot_shrinks)} counters down)"
             if hot_shrinks else ""), file=sys.stderr)
    for f_ in report["findings"]:
        print(f"  UNWAIVED {f_['rule']}: {f_['file']}:{f_['line']} "
              f"[{f_['symbol']}] {f_['message']}", file=sys.stderr)
    if report.get("waiver_error"):
        print(f"  WAIVER FILE ERROR: {report['waiver_error']}",
              file=sys.stderr)
    for w in report["unused_waivers"]:
        print(f"  stale waiver: {w['rule']} {w['file']} "
              f"[{w['symbol']}] (waivers.toml:{w['line']})",
              file=sys.stderr)
    for e in cert_errors:
        print(f"  CERTIFICATE: {e}", file=sys.stderr)
    for e in hot_errors:
        print(f"  HOTPATH BUDGET: {e}", file=sys.stderr)
    for e in hot_shrinks:
        print(f"  hotpath ratchet (budget rewritten): {e}",
              file=sys.stderr)
    for e in report.get("gate_errors", ()):
        print(f"  GATE: {e}", file=sys.stderr)
    # one machine-readable verdict line on stdout (the doctor/perf-gate
    # convention: tail -n 1 | json)
    print(json.dumps({"stage": "analysis_gate", "ok": report["ok"],
                      "unwaived": s["unwaived"],
                      "waived": s["waived"],
                      "unused_waivers": s["waivers_unused"],
                      "certificate_ok": not cert_errors,
                      "hotpath_budget_ok": not hot_errors,
                      "hotpath_ratcheted": len(hot_shrinks),
                      "cache_hits": c["hits"],
                      "cache_second_run_hits": cache_second_hits}))
    if not report["ok"]:
        print("ANALYSIS GATE FAIL (report in "
              f"{args.out})", file=sys.stderr)
        return 1
    print("ANALYSIS GATE OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
