"""CI leg for the static invariant checkers [ISSUE 12, dataflow tier
ISSUE 13]: run ``tuplewise check`` in-process, write the JSON report
artifact (and optionally SARIF for inline PR annotations), diff the
overflow certificate against the committed baseline, and fail on any
unwaived finding, waiver-file error, parse error, import cycle, or
certificate drift.

The finding ratchet lives in the waiver semantics (each waiver
absorbs a bounded count — analysis/waivers.py). The overflow
certificate HAS a baseline by design
(``tuplewise_tpu/analysis/exactness_bounds.toml``): the bound table
is a function of the compile-ladder maxima, so a ladder bump that
breaks int32 safety must fail with the violating bound NAMED — that
requires committing the expected bounds, not just "no new findings".

Usage: python scripts/analysis_gate.py
           [--out results/analysis_report.json]
           [--sarif results/analysis_report.sarif]
           [--no-cache]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE = os.path.join(
    REPO, "tuplewise_tpu", "analysis", "exactness_bounds.toml")

_SARIF_RULE_HELP = {
    "race-unguarded-shared":
        "attribute shared across thread roles with an unguarded "
        "access",
    "race-inconsistent-guard":
        "attribute shared across thread roles with no common guard "
        "lock",
    "count-float-taint":
        "float-tainted value flows into an integer win-count "
        "accumulator",
    "count-narrow-accumulator":
        "raw int32 device value accumulated without widening",
    "overflow-int32":
        "int32 accumulator bound exceeds 2^31-1 at ladder maxima",
    "overflow-unproved":
        "int32 accumulator the overflow classifier cannot bound",
}


def to_sarif(report: dict) -> dict:
    """SARIF 2.1.0 — one run, one result per finding (waived findings
    ride along at 'note' level with a suppression record, so the PR
    annotation layer shows them greyed out, not red)."""
    rules = {}
    results = []

    def add(f: dict, level: str, suppressed: bool,
            reason: str = "") -> None:
        rid = f["rule"]
        rules.setdefault(rid, {
            "id": rid,
            "shortDescription": {
                "text": _SARIF_RULE_HELP.get(
                    rid, f"tuplewise invariant rule {rid}")},
        })
        res = {
            "ruleId": rid,
            "level": level,
            "message": {"text": f["message"]},
            "partialFingerprints": {
                "tuplewiseFingerprint/v1": f["fingerprint"]},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f["file"],
                        "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(int(f["line"]), 1)},
                }}],
        }
        if suppressed:
            res["suppressions"] = [{
                "kind": "external",
                "justification": reason or "waived in "
                "tuplewise_tpu/analysis/waivers.toml"}]
        results.append(res)

    for f in report["findings"]:
        add(f, "error", suppressed=False)
    for f in report.get("waived", ()):
        add(f, "note", suppressed=True, reason=f.get("reason", ""))
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tuplewise-check",
                "informationUri":
                    "docs/DESIGN.md#17-static-invariant-checks",
                "rules": sorted(rules.values(),
                                key=lambda r: r["id"]),
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "analysis_report.json"))
    ap.add_argument("--sarif", type=str, default=None,
                    help="also write a SARIF 2.1.0 report here "
                         "(uploaded next to the JSON so findings "
                         "render as inline PR annotations)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the content-sha parse cache")
    args = ap.parse_args(argv)

    from tuplewise_tpu.analysis import exactness
    from tuplewise_tpu.analysis.runner import run_checks

    report = run_checks(root=REPO, use_cache=not args.no_cache)

    # overflow-certificate baseline diff [ISSUE 13 satellite]: the
    # derived bound table must match the committed envelope exactly
    cert_errors = []
    if os.path.exists(BASELINE):
        with open(BASELINE, "r", encoding="utf-8") as f:
            cert_errors = exactness.compare_to_baseline(
                report["overflow_certificate"], f.read())
    else:
        cert_errors = [f"missing committed baseline {BASELINE}"]
    report["certificate_diff"] = cert_errors
    if cert_errors:
        report["ok"] = False

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
    if args.sarif:
        os.makedirs(os.path.dirname(args.sarif) or ".", exist_ok=True)
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(to_sarif(report), f, indent=2)

    s = report["summary"]
    c = s["cache"]
    print(f"ANALYSIS GATE: {s['files_analyzed']} files, "
          f"{s['findings_total']} findings "
          f"({s['waived']} waived, {s['unwaived']} unwaived), "
          f"{len(report['import_cycles'])} import cycles, "
          f"{len(report['dead_symbols'])} dead public symbols "
          f"(warn-only), cache {c['hits']}/{c['hits'] + c['misses']} "
          f"hits, certificate "
          f"{'OK' if not cert_errors else 'DRIFT'}", file=sys.stderr)
    for f_ in report["findings"]:
        print(f"  UNWAIVED {f_['rule']}: {f_['file']}:{f_['line']} "
              f"[{f_['symbol']}] {f_['message']}", file=sys.stderr)
    if report.get("waiver_error"):
        print(f"  WAIVER FILE ERROR: {report['waiver_error']}",
              file=sys.stderr)
    for w in report["unused_waivers"]:
        print(f"  stale waiver: {w['rule']} {w['file']} "
              f"[{w['symbol']}] (waivers.toml:{w['line']})",
              file=sys.stderr)
    for e in cert_errors:
        print(f"  CERTIFICATE: {e}", file=sys.stderr)
    # one machine-readable verdict line on stdout (the doctor/perf-gate
    # convention: tail -n 1 | json)
    print(json.dumps({"stage": "analysis_gate", "ok": report["ok"],
                      "unwaived": s["unwaived"],
                      "waived": s["waived"],
                      "unused_waivers": s["waivers_unused"],
                      "certificate_ok": not cert_errors,
                      "cache_hits": c["hits"]}))
    if not report["ok"]:
        print("ANALYSIS GATE FAIL (report in "
              f"{args.out})", file=sys.stderr)
        return 1
    print("ANALYSIS GATE OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
