"""CI chaos smoke [ISSUE 3 satellite].

Replays a seeded fault schedule — one shard death (when the platform
exposes >= 2 devices), one compactor crash, one batcher crash, and
injected poison events — through ``serving.replay`` and asserts the
two properties the fault-tolerance layer promises:

1. the run COMPLETES (no hang: self-heal, watchdog restart, supervisor
   restart, and edge rejection all did their jobs), with the recovery
   counters > 0 proving each path actually fired;
2. the final AUC is bit-identical to a fault-free run over the same
   admitted events — recovery repaired state, it did not corrupt it.

Appends the row (stage "chaos_smoke") to a JSONL the workflow uploads
as an artifact. Exits nonzero on any missed counter or parity breach.

Usage: python scripts/chaos_smoke.py [--n-events 3000]
                                     [--out results/chaos_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-events", type=int, default=3_000)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "chaos_smoke.jsonl"))
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    # shard death needs a 2-device mesh; some environments pin the
    # device count before our XLA flag lands — degrade to the
    # single-host schedule rather than fail the smoke for topology
    shards = 2 if jax.device_count() >= 2 else None
    faults = [
        {"point": "compactor_build", "on_call": 1, "action": "error"},
        {"point": "batcher", "on_call": 5, "action": "error"},
        {"point": "poison", "at_events": [137, 1500, 1501],
         "value": "nan"},
    ]
    if shards:
        # on_call must be safely below the worst-case call count: with
        # full 256-event coalescing a 3000-event stream still issues
        # ~20+ sharded count queries once the base is placed, so 12
        # fires regardless of how the batcher happens to coalesce
        faults.append({"point": "sharded_count", "on_call": 12,
                       "action": "error", "dropped": [1]})
    spec = {"faults": faults}

    cfg = ServingConfig(policy="block", flush_timeout_s=0.002,
                        compact_every=128, bg_compact=True,
                        mesh_shards=shards)
    scores, labels = make_stream(args.n_events, pos_frac=0.5,
                                 separation=1.0, seed=0)
    rec = replay(scores, labels, config=cfg, max_inflight=256, chaos=spec)
    rec["stage"] = "chaos_smoke"

    f = rec["faults"]
    missing = [k for k in ("bg_compactor_restarts", "batcher_restarts",
                           "poison_rejects") if not f.get(k)]
    if shards and not f.get("reshard_events"):
        missing.append("reshard_events")
    if missing:
        print(f"CHAOS SMOKE FAIL: recovery counters never fired: "
              f"{missing} (faults={f})", file=sys.stderr)
        return 1

    # parity: fault-free run over the same admitted events must give
    # the bit-identical exact AUC (recovery must not corrupt wins2)
    admitted = np.ones(args.n_events, dtype=bool)
    admitted[rec["shed_events"]] = False
    ref = replay(scores[admitted], labels[admitted],
                 config=ServingConfig(policy="block", compact_every=128,
                                      bg_compact=True),
                 max_inflight=256)
    if rec["auc_exact"] != ref["auc_exact"]:
        print(f"CHAOS SMOKE FAIL: auc under faults {rec['auc_exact']!r}"
              f" != fault-free {ref['auc_exact']!r}", file=sys.stderr)
        return 1
    rec["auc_fault_free"] = ref["auc_exact"]

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(
        f"chaos smoke OK: shards={shards} "
        f"reshard={f.get('reshard_events')} "
        f"bg_restarts={f['bg_compactor_restarts']} "
        f"batcher_restarts={f['batcher_restarts']} "
        f"poison={f['poison_rejects']} "
        f"auc bit-identical to fault-free -> {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
