"""Learning-side trade-off suite [SURVEY §1.3, §4.4; VERDICT r2 next #1].

The paper's second half: distributed pairwise SGD where repartitioning
every n_r steps trades communication for gradient quality. Two
instruments:

* the SIMULATED-N trainer (models.sim_learner — vmap over workers AND
  Monte-Carlo seeds, parity-tested against the mesh trainer) sweeps
  repartition period x worker count x pair budget in the small-block
  regime where the trade-off is visible, with honest held-out
  evaluation (fresh-draw Gaussian test sets / stratified Adult split);
* the MESH trainer (models.pairwise_sgd) supplies the on-hardware
  throughput rows: steps/s at production sizes on the chip (mesh of 1)
  and on the 8-virtual-CPU mesh (true multi-worker semantics).

What the sweeps measure (and the figures show): the MEAN held-out-AUC
learning curve per n_r, and the ACROSS-SEED variance of the final
model — the learning analogue of the estimator's 1/T variance decay:
a fixed partition (n_r = never) converges to a partition-dependent
optimum whose spread across partition draws is the price of skipping
communication; frequent repartitioning averages that randomness out
during training. Both axes are committed per config row.

Stages (platform is process-global, so chip and CPU stages are separate
invocations):

  python scripts/learning_suite.py --stages gauss,adult,mesh8,figs
      # sim sweeps + 8-virtual-CPU mesh rows (forces the CPU platform)
  python scripts/learning_suite.py --stages chip
      # mesh-of-1 training throughput on the attached TPU chip
  python scripts/learning_suite.py --stages gauss-chip
      # platform-independence check: sweep cells re-run on the chip
  python scripts/learning_suite.py --stages trace
      # profiler digest of a training run (repartition-event cost)

Outputs: results/learning_gauss.jsonl, results/learning_adult.jsonl,
results/learning_throughput{,_chip}.jsonl,
results/trace_train_chip_summary.txt, results/figures/learning_*.png.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
RESULTS = os.path.join(REPO, "results")
FIGS = os.path.join(RESULTS, "figures")

T0 = time.perf_counter()
# repartition_every sentinel for "never" — shared with the row builder
from tuplewise_tpu.models.sim_learner import NEVER  # noqa: E402
QUICK = False     # set by main(); quick output NEVER touches full files


def log(msg):
    print(f"[learning +{time.perf_counter() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_touched = set()


from tuplewise_tpu.utils.results_io import quick_sibling  # noqa: E402


def _quick_name(name: str) -> str:
    """Quick runs write to *_quick siblings (JSONL and figures alike)
    so a smoke test can never truncate/replace committed artifacts —
    rule shared via utils.results_io."""
    return quick_sibling(name, QUICK)


def _out_path(name: str) -> str:
    return os.path.join(RESULTS, _quick_name(name))


def emit(rec, out_name):
    """Rows accumulate in a .partial sibling; finalize_outputs() renames
    onto the real file only when the invocation completes — a crash or
    Ctrl-C mid-stage leaves the committed artifact untouched (the
    hazard config_suite's keep-other-rows merge guards against)."""
    path = _out_path(out_name)
    partial = path + ".partial"
    if path not in _touched:
        _touched.add(path)
        if os.path.exists(partial):
            os.remove(partial)
    if QUICK:
        rec["quick"] = True
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(partial, "a") as f:
        f.write(json.dumps(rec) + "\n")


def finalize_outputs():
    for path in sorted(_touched):
        partial = path + ".partial"
        if os.path.exists(partial):
            os.replace(partial, path)
            log(f"finalized {os.path.basename(path)}")


def run_config(scorer, p0, data, cfg, *, n_seeds, eval_every, dataset,
               out_name, platform):
    """One sweep cell: train S replicas, emit the full curve row
    (schema: sim_learner.curve_record + suite provenance fields)."""
    from tuplewise_tpu.models.sim_learner import curve_record, train_curves

    Xp, Xn, Xp_te, Xn_te = data
    t0 = time.perf_counter()
    out = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                       n_seeds=n_seeds, eval_every=eval_every)
    wc = time.perf_counter() - t0
    rec = dict(
        curve_record(cfg, out, n_seeds),
        dataset=dataset, seed0=cfg.seed,
        n_train=[len(Xp), len(Xn)],
        n_test=[len(Xp_te), len(Xn_te)],
        m_per_worker=[len(Xp) // cfg.n_workers,
                      len(Xn) // cfg.n_workers],
        wallclock_s=round(wc, 2), platform=platform,
    )
    emit(rec, out_name)

    def fmt(v):   # null-safe: n_seeds=1 rows carry no spread estimate
        return "n/a" if v is None else f"{v:.5f}"

    log(f"{dataset} N={cfg.n_workers} n_r={rec['n_r']} "
        f"B={cfg.pairs_per_worker} "
        f"final={rec['final_auc_mean']:.5f}+-{fmt(rec['final_auc_se'])} "
        f"sd={fmt(rec['final_auc_sd'])} ({wc:.1f}s)")
    return rec


def _gauss_cells(q):
    """ONE source of truth for the gaussian sweep's data/config cell:
    the chip platform-independence stage must reproduce stage_gauss's
    cells exactly, so both read this (a divergence would surface as a
    confusing tolerance failure in the chip-vs-CPU regression gate)."""
    from tuplewise_tpu.data import make_gaussian_splits
    from tuplewise_tpu.models.pairwise_sgd import TrainConfig
    from tuplewise_tpu.models.scorers import LinearScorer

    n = 128 if q else 512
    n_te = 2000 if q else 20000
    steps = 40 if q else 500
    S = 4 if q else 48
    data = make_gaussian_splits(n, n_te, dim=10, separation=0.8, seed=0)
    scorer = LinearScorer(dim=10)
    p0 = scorer.init(0)
    base = TrainConfig(kernel="hinge", lr=0.3, steps=steps, seed=1000)
    return data, scorer, p0, base, S, steps


def stage_gauss(q, platform):
    """Gaussians, small-block regime: n_r x N sweep + pair-budget sweep."""
    data, scorer, p0, base, S, steps = _gauss_cells(q)
    nrs = (1, 5, NEVER) if q else (1, 5, 25, 125, NEVER)
    for N in ((16, 32) if q else (32, 128, 256, 16)):
        for nr in nrs:
            run_config(
                scorer, p0, data,
                dataclasses.replace(base, n_workers=N,
                                    repartition_every=nr),
                n_seeds=S, eval_every=steps // 20 or 1,
                dataset="gaussians", out_name="learning_gauss.jsonl",
                platform=platform,
            )
    # pair-budget sweep at fixed N: stochastic per-step pair sampling
    # composes with the repartition schedule [SURVEY §1.2 item 4].
    # B=None (all local pairs) is not re-run: sweep A already emitted
    # those rows at this N, and the budget figure picks them up there.
    N = 16 if q else 128
    for B in (1, 4, 16):
        for nr in ((1, NEVER) if q else (1, 25, NEVER)):
            run_config(
                scorer, p0, data,
                dataclasses.replace(base, n_workers=N,
                                    repartition_every=nr,
                                    pairs_per_worker=B),
                n_seeds=S, eval_every=steps // 20 or 1,
                dataset="gaussians", out_name="learning_gauss.jsonl",
                platform=platform,
            )


def stage_designs(q, platform):
    """Pair-budget DESIGNS on the learning side [SURVEY §1.2 item 4;
    VERDICT r3 next #6]: at N=128 the per-worker grid is 4x4=16 pairs,
    so B in {4, 8} puts the budget at 25%/50% of the grid — the regime
    where the on-device swor/bernoulli samplers (ops.device_design) cut
    per-step gradient sampling noise by the finite-population factor
    (1 - B/G). The sweep records whether that survives into the final
    test-AUC floor, with the swr rows as the control."""
    data, scorer, p0, base, S, steps = _gauss_cells(q)
    N = 16 if q else 128
    for design in ("swr", "swor", "bernoulli"):
        for B in ((4,) if q else (4, 8)):
            for nr in ((1,) if q else (1, NEVER)):
                run_config(
                    scorer, p0, data,
                    dataclasses.replace(base, n_workers=N,
                                        repartition_every=nr,
                                        pairs_per_worker=B,
                                        pair_design=design),
                    n_seeds=S, eval_every=steps // 20 or 1,
                    dataset="gaussians",
                    out_name="learning_designs.jsonl",
                    platform=platform,
                )


def stage_prod(q, platform):
    """Production-size budgeted sweep [VERDICT r4 next #5]: the O(B)
    budgeted path (device pair designs, no per-worker grid) frees the
    sim instrument from the toy-m regime, so the committed record gets
    a proper-m cell WITH error bars: n = 16384/class, S = 16 seeds,
    n_r in {1, never}, at N in {8, 64} workers (N=64 puts the
    per-worker block at 256/class — the visible-trade-off regime at
    production data size). A same-shape instrument-overlap cell then
    trains the N=8 config on BOTH instruments (vmapped sim vs real
    shard_map mesh, seed-aligned) so the at-scale sweep is backed by
    the same per-seed agreement evidence as the toy cells."""
    import numpy as np

    from tuplewise_tpu.data import make_gaussian_splits
    from tuplewise_tpu.models.pairwise_sgd import (
        TrainConfig, evaluate_auc, train_pairwise,
    )
    from tuplewise_tpu.models.scorers import LinearScorer

    n = 1024 if q else 16384
    S = 4 if q else 16
    steps = 40 if q else 500
    data = make_gaussian_splits(n, 2000 if q else 20000, dim=10,
                                separation=0.8, seed=0)
    scorer = LinearScorer(dim=10)
    p0 = scorer.init(0)
    base = TrainConfig(kernel="hinge", lr=0.3, steps=steps, seed=1000,
                       pairs_per_worker=256)
    for N in (8, 64):
        for nr in (1, NEVER):
            run_config(
                scorer, p0, data,
                dataclasses.replace(base, n_workers=N,
                                    repartition_every=nr),
                n_seeds=S, eval_every=steps // 20 or 1,
                dataset="gaussians",
                out_name="learning_prod.jsonl", platform=platform,
            )

    # instrument overlap at the SAME shape (N=8): per-seed agreement
    # between the sim sweep engine and the real mesh trainer
    import dataclasses as _dc

    from tuplewise_tpu.models.sim_learner import train_curves

    Xp, Xn, Xp_te, Xn_te = data
    S_cell = 2 if q else 8
    for nr in ((1,) if q else (1, NEVER)):
        cfg = _dc.replace(base, n_workers=8, repartition_every=nr,
                          steps=40 if q else 200)
        t0 = time.perf_counter()
        out = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                           n_seeds=S_cell, eval_every=10**9)
        sim_finals = [
            float(evaluate_auc(
                scorer,
                {k: np.asarray(v)[s] for k, v in
                 out["final_params"].items()},
                Xp_te, Xn_te))
            for s in range(S_cell)
        ]
        mesh_finals = []
        for s in range(S_cell):
            p_s, _ = train_pairwise(
                scorer, p0, Xp, Xn, _dc.replace(cfg, seed=cfg.seed + s)
            )
            mesh_finals.append(
                float(evaluate_auc(scorer, p_s, Xp_te, Xn_te))
            )
        wc = time.perf_counter() - t0
        delta = float(np.max(np.abs(
            np.asarray(sim_finals) - np.asarray(mesh_finals)
        )))
        emit({
            "cell": "instrument_overlap_prod", "n_workers": 8,
            "n_train_per_class": n,
            "pairs_per_worker": base.pairs_per_worker,
            "n_r": None if nr >= NEVER else nr, "steps": cfg.steps,
            "n_seeds": S_cell,
            "sim_final_auc": [round(v, 6) for v in sim_finals],
            "mesh_final_auc": [round(v, 6) for v in mesh_finals],
            "max_abs_delta": delta,
            "wallclock_incl_compile_s": round(wc, 2),
            "platform": platform,
        }, "learning_prod.jsonl")
        log(f"prod overlap n_r={None if nr >= NEVER else nr}: "
            f"max |sim-mesh| = {delta:.2e} over {S_cell} seeds "
            f"({wc:.1f}s)")


def stage_triplet(q, platform):
    """Degree-3 metric learning [VERDICT r3 next #9]: the triplet-hinge
    learner (models.triplet_sgd) trained through a k=2 embedding
    bottleneck, held-out triplet accuracy as the curve — config 4
    turned into a LEARNING config. Two tasks:

    * gauss-overlap: overlapping Gaussian clouds (separation 1.0,
      d=16), a nontrivial accuracy ceiling set by the class overlap;
    * mnist-surrogate: class 3 vs rest of the MNIST-embedding
      surrogate (separable by construction, meta-stamped synthetic —
      the curve shows recovery through the bottleneck).

    Repartition schedule sweep n_r in {1, 25, never}, S seeds each.

    r5 adds the NONLINEAR-embedding cell [VERDICT r4 next #9]: a
    radial task (inner shell vs outer shell, Bayes ceiling 1.0 by
    construction) trained with the linear embedding AND the MLP
    embedder through the SAME budgeted path — a linear projection
    cannot separate radii, so the cell shows the plugin discipline
    closing the Bayes-ceiling gap.
    """
    import numpy as np

    from tuplewise_tpu.data import load_mnist_embeddings, make_gaussians
    from tuplewise_tpu.models.scorers import LinearEmbed, MLPEmbed
    from tuplewise_tpu.models.triplet_sgd import (
        TripletTrainConfig, evaluate_triplet_accuracy, init_embed,
        train_triplet,
    )

    S = 2 if q else 8
    steps = 30 if q else 300
    N = 4 if q else 8

    def split(X, frac, rng):
        p = rng.permutation(len(X))
        t = int(frac * len(X))
        return X[p[:t]], X[p[t:]]

    def task_data(task, seed):
        rng = np.random.default_rng(seed)
        if task == "gauss-overlap":
            n = 240 if q else 2_000
            # overlapping clouds: the optimal metric projects onto the
            # shift direction and the class overlap caps accuracy well
            # below 1 — a nontrivial ceiling. (No rotation: isotropic
            # covariance + rotation-invariant init make a rotated task
            # distributionally identical — reviewer r4.)
            X, Y = make_gaussians(n, 3 * n, dim=16, separation=1.0,
                                  seed=seed)
        else:
            n_all = 400 if q else 4_000
            E, labels, _ = load_mnist_embeddings(n=n_all, seed=seed)
            X, Y = E[labels == 3], E[labels != 3]
        Xc_tr, Xc_te = split(np.asarray(X, np.float32), 0.75, rng)
        Xo_tr, Xo_te = split(np.asarray(Y, np.float32), 0.75, rng)
        return Xc_tr, Xo_tr, Xc_te, Xo_te

    for task in ("gauss-overlap", "mnist-surrogate"):
        for nr in ((1,) if q else (1, 25, NEVER)):
            accs, curves, acc0s = [], [], []
            t0 = time.perf_counter()
            for s in range(S):
                Xc_tr, Xo_tr, Xc_te, Xo_te = task_data(task, s)
                dim = Xc_tr.shape[1]
                p0 = init_embed(dim, 2, seed=s)
                acc0s.append(
                    evaluate_triplet_accuracy(p0, Xc_te, Xo_te)
                )
                cfg = TripletTrainConfig(
                    lr=0.1, steps=steps, n_workers=N,
                    repartition_every=nr,
                    triplets_per_worker=512 if q else 4_096,
                    seed=1_000 + s, embed_dim=2,
                )
                _, hist = train_triplet(
                    p0, Xc_tr, Xo_tr, cfg,
                    eval_every=max(steps // 10, 1),
                    eval_data=(Xc_te, Xo_te),
                )
                curves.append(hist["test_acc"])
                accs.append(float(hist["test_acc"][-1]))
            wc = time.perf_counter() - t0
            accs = np.asarray(accs)
            curve = np.mean(np.stack(curves), axis=0)
            rec = {
                "task": task, "embed_dim": 2, "n_workers": N,
                "n_r": None if nr >= NEVER else nr,
                "repartition_every": nr, "steps": steps,
                "triplets_per_worker": 512 if q else 4_096,
                "n_seeds": S,
                "acc_init_mean": round(float(np.mean(acc0s)), 6),
                "acc_curve_mean": np.round(curve, 6).tolist(),
                "final_acc_mean": round(float(accs.mean()), 6),
                "final_acc_se": round(
                    float(accs.std(ddof=1) / np.sqrt(S)), 6
                ) if S > 1 else None,
                "wallclock_s": round(wc, 2), "platform": platform,
            }
            rec["embedder"] = "linear"
            emit(rec, "learning_triplet.jsonl")
            log(f"triplet {task} n_r={rec['n_r']} "
                f"final={rec['final_acc_mean']:.5f} "
                f"(init {rec['acc_init_mean']:.5f}) ({wc:.1f}s)")

    # ---- nonlinear-embedding cell [VERDICT r4 next #9] -------------- #
    def radial_data(seed):
        """Inner shell (class) vs outer shell (others) in d=8: radii
        are disjoint, so the Bayes triplet accuracy is 1.0 — but no
        LINEAR projection separates radii, so the linear embedding
        plateaus well below the ceiling and the MLP must close it."""
        rng = np.random.default_rng(seed)
        d, n = 8, (240 if q else 2_000)

        def shell(m, r_lo, r_hi):
            v = rng.standard_normal((m, d))
            v /= np.linalg.norm(v, axis=1, keepdims=True)
            r = rng.uniform(r_lo, r_hi, size=(m, 1))
            return (v * r).astype(np.float32)

        X = shell(n, 0.5, 1.0)
        Y = shell(3 * n, 1.8, 2.6)
        Xc_tr, Xc_te = split(X, 0.75, rng)
        Xo_tr, Xo_te = split(Y, 0.75, rng)
        return Xc_tr, Xo_tr, Xc_te, Xo_te

    # the MLP needs a longer horizon: at 300 steps it is still climbing
    # through the linear plateau (~0.85); 800 steps brings it near the
    # 1.0 ceiling while linear cannot move
    r_steps = steps if q else 800
    for name in ("linear", "mlp"):
        accs, curves, acc0s = [], [], []
        t0 = time.perf_counter()
        for s in range(S):
            Xc_tr, Xo_tr, Xc_te, Xo_te = radial_data(s)
            emb = (LinearEmbed(dim=8, embed_dim=2) if name == "linear"
                   else MLPEmbed(dim=8, hidden=32, embed_dim=2))
            p0 = emb.init(seed=s)
            acc0s.append(evaluate_triplet_accuracy(
                p0, Xc_te, Xo_te, embedder=emb))
            cfg = TripletTrainConfig(
                lr=0.3, steps=r_steps, n_workers=N,
                repartition_every=1,
                triplets_per_worker=512 if q else 4_096,
                seed=1_000 + s, embed_dim=2,
            )
            _, hist = train_triplet(
                p0, Xc_tr, Xo_tr, cfg,
                eval_every=max(r_steps // 10, 1),
                eval_data=(Xc_te, Xo_te), embedder=emb,
            )
            curves.append(hist["test_acc"])
            accs.append(float(hist["test_acc"][-1]))
        wc = time.perf_counter() - t0
        accs = np.asarray(accs)
        rec = {
            "task": "radial", "embedder": name, "embed_dim": 2,
            "n_workers": N, "n_r": 1, "repartition_every": 1,
            "steps": r_steps,
            "triplets_per_worker": 512 if q else 4_096, "n_seeds": S,
            "bayes_ceiling": 1.0,
            "acc_init_mean": round(float(np.mean(acc0s)), 6),
            "acc_curve_mean": np.round(
                np.mean(np.stack(curves), axis=0), 6).tolist(),
            "final_acc_mean": round(float(accs.mean()), 6),
            "final_acc_se": round(
                float(accs.std(ddof=1) / np.sqrt(S)), 6
            ) if S > 1 else None,
            "wallclock_s": round(wc, 2), "platform": platform,
        }
        emit(rec, "learning_triplet.jsonl")
        log(f"triplet radial embedder={name} "
            f"final={rec['final_acc_mean']:.5f} "
            f"(init {rec['acc_init_mean']:.5f}) ({wc:.1f}s)")


def stage_gauss_chip(q, platform):
    """The visible-regime sweep cells re-run ON THE TPU CHIP: jax's
    threefry PRNG is backend-deterministic, so the same seeds draw the
    same partitions and the chip rows must reproduce the committed CPU
    rows to f32 rounding — platform-independence evidence for the
    whole learning suite (learning_gauss_chip.jsonl)."""
    import jax

    if jax.devices()[0].platform != "tpu":
        raise SystemExit(
            "gauss-chip must run on the TPU chip: platform is "
            f"{jax.devices()[0].platform!r} — rows stamped from a "
            "TPU-less host would make the chip-vs-CPU gate vacuous"
        )
    data, scorer, p0, base, S, steps = _gauss_cells(q)
    N = 16 if q else 256
    for nr in ((1, NEVER) if q else (1, 25, NEVER)):
        run_config(
            scorer, p0, data,
            dataclasses.replace(base, n_workers=N,
                                repartition_every=nr),
            n_seeds=S, eval_every=steps // 20 or 1,
            dataset="gaussians", out_name="learning_gauss_chip.jsonl",
            platform=platform,
        )


def stage_adult(q, platform):
    """Surrogate-Adult (real CSVs when on disk): n_r x N sweep with the
    stratified train/test split [VERDICT r2 next #2]."""
    from tuplewise_tpu.data import load_adult_splits
    from tuplewise_tpu.models.pairwise_sgd import TrainConfig, split_by_label
    from tuplewise_tpu.models.scorers import LinearScorer

    n = 600 if q else 8000
    steps = 40 if q else 400
    S = 4 if q else 48
    X, y, Xte, yte, meta = load_adult_splits(n=n, seed=0)
    Xp, Xn = split_by_label(X, y)
    Xp_te, Xn_te = split_by_label(Xte, yte)
    data = (Xp, Xn, Xp_te, Xn_te)
    log(f"adult: train pos/neg = {len(Xp)}/{len(Xn)}, "
        f"test = {len(Xp_te)}/{len(Xn_te)}, source={meta['source']}")
    scorer = LinearScorer(dim=Xp.shape[1])
    p0 = scorer.init(0)
    base = TrainConfig(kernel="hinge", lr=0.3, steps=steps, seed=2000)
    nrs = (1, 5, NEVER) if q else (1, 5, 25, 125, NEVER)
    for N in ((8,) if q else (8, 64, 180)):
        # N=180 -> m_pos ~ 8: the visible regime at the real class ratio
        for nr in nrs:
            run_config(
                scorer, p0, data,
                dataclasses.replace(base, n_workers=N,
                                    repartition_every=nr),
                n_seeds=S, eval_every=steps // 20 or 1,
                dataset="adult", out_name="learning_adult.jsonl",
                platform=platform,
            )


def _throughput_row(n_per_class, cfg, label, platform, steps_timed=30,
                    out_name="learning_throughput.jsonl"):
    """Mesh-trainer steps/s at a production size (compile excluded)."""
    import jax

    from tuplewise_tpu.data import make_gaussian_splits
    from tuplewise_tpu.models.pairwise_sgd import (
        evaluate_auc, train_pairwise,
    )
    from tuplewise_tpu.models.scorers import LinearScorer

    Xp, Xn, Xp_te, Xn_te = make_gaussian_splits(
        n_per_class, max(n_per_class // 4, 1000), dim=5, seed=0
    )
    scorer = LinearScorer(dim=5)
    p0 = scorer.init(0)
    # warm with the SAME step count: the chunk length is a STATIC jit
    # argument, so a shorter warm run compiles a different executable
    # and the timed run would recompile inside the window (this bug
    # once inflated these rows ~10x at n=1e5 — caught by the committed
    # trace digest showing 1.2 s of device time in a 22 s wall)
    timed = dataclasses.replace(cfg, steps=steps_timed)
    train_pairwise(scorer, p0, Xp, Xn, timed)
    t0 = time.perf_counter()
    params, hist = train_pairwise(scorer, p0, Xp, Xn, timed)
    wc = time.perf_counter() - t0
    pairs_per_step = (len(Xp) // cfg.n_workers) ** 2 * cfg.n_workers \
        if cfg.pairs_per_worker is None \
        else cfg.pairs_per_worker * cfg.n_workers
    from tuplewise_tpu.models.sim_learner import last_recorded_loss

    rec = {
        "label": label, "platform": platform,
        "devices": jax.device_count(),
        "n_workers": cfg.n_workers,
        "n_train_per_class": n_per_class,
        "kernel": cfg.kernel, "lr": cfg.lr,
        "repartition_every": cfg.repartition_every,
        "pairs_per_worker": cfg.pairs_per_worker,
        # loss-free steps [VERDICT r4 next #1] record NaN; loss_last is
        # the last RECORDED loss (None = never recorded past step 0 or
        # diverged — valid JSON needs no NaN literals)
        "loss_every": cfg.loss_every,
        "steps": steps_timed,
        "steps_per_s": round(steps_timed / wc, 3),
        "grad_pairs_per_s": round(pairs_per_step * steps_timed / wc, 1),
        "wallclock_s": round(wc, 3),
        "auc_test_after": evaluate_auc(scorer, params, Xp_te, Xn_te),
        "loss_last": last_recorded_loss(hist["loss"], cfg.loss_every),
    }
    emit(rec, out_name)
    log(f"throughput {label}: {rec['steps_per_s']} steps/s, "
        f"{rec['grad_pairs_per_s']:.3e} grad-pairs/s ({wc:.1f}s)")
    return rec


def stage_mesh8(q, platform):
    """True multi-worker mesh training on the 8-virtual-CPU mesh: the
    distributed path's semantics AND its wall-clock on record
    [VERDICT r2 next #7]."""
    from tuplewise_tpu.models.pairwise_sgd import TrainConfig

    n = 512 if q else 4096
    for nr in (1, 10, NEVER):
        _throughput_row(
            n,
            TrainConfig(kernel="hinge", lr=0.3, n_workers=8,
                        repartition_every=nr, seed=7),
            label=f"mesh8_cpu_nr{'inf' if nr >= NEVER else nr}",
            platform=platform,
            steps_timed=10 if q else 30,
        )

    # Instrument-overlap cell [VERDICT r3 weak #6]: ONE cell (the
    # gauss data at N=8, 200 steps — a dedicated cell, not one of the
    # committed sweep's) trained by BOTH instruments — the vmapped sim
    # trainer (the committed sweeps' engine) and the REAL shard_map
    # mesh trainer, S seeds each, same fold chains (mesh seed =
    # cfg.seed+s is sim replica s) — so the committed record shows the
    # two agreeing per seed, not just in distribution.
    import dataclasses as _dc

    import numpy as np

    from tuplewise_tpu.models.pairwise_sgd import (
        evaluate_auc, train_pairwise,
    )
    from tuplewise_tpu.models.sim_learner import train_curves

    data, scorer, p0, base, *_ = _gauss_cells(q)
    Xp, Xn, Xp_te, Xn_te = data
    S_cell = 2 if q else 8
    for nr in ((1,) if q else (1, NEVER)):
        cfg = _dc.replace(base, n_workers=8, repartition_every=nr,
                          steps=40 if q else 200)
        t0 = time.perf_counter()
        out = train_curves(scorer, p0, Xp, Xn, Xp_te, Xn_te, cfg,
                           n_seeds=S_cell, eval_every=10**9)
        sim_finals = [
            float(evaluate_auc(
                scorer,
                {k: np.asarray(v)[s] for k, v in
                 out["final_params"].items()},
                Xp_te, Xn_te))
            for s in range(S_cell)
        ]
        mesh_finals = []
        for s in range(S_cell):
            p_s, _ = train_pairwise(
                scorer, p0, Xp, Xn, _dc.replace(cfg, seed=cfg.seed + s)
            )
            mesh_finals.append(
                float(evaluate_auc(scorer, p_s, Xp_te, Xn_te))
            )
        wc = time.perf_counter() - t0
        delta = float(np.max(np.abs(
            np.asarray(sim_finals) - np.asarray(mesh_finals)
        )))
        rec = {
            "cell": "instrument_overlap", "n_workers": 8,
            "n_r": None if nr >= NEVER else nr, "steps": cfg.steps,
            "n_seeds": S_cell,
            "sim_final_auc": [round(v, 6) for v in sim_finals],
            "mesh_final_auc": [round(v, 6) for v in mesh_finals],
            "max_abs_delta": delta,
            # honest label: each mesh seed is a fresh cfg -> a fresh
            # compile, so this wall-clock is MOSTLY XLA compilation
            # (the cell exists for parity, not timing; §6.4's rows
            # carry the warmed throughput numbers)
            "wallclock_incl_compile_s": round(wc, 2),
            "platform": platform,
        }
        emit(rec, "learning_mesh_overlap.jsonl")
        log(f"overlap cell n_r={rec['n_r']}: max |sim-mesh| final-AUC "
            f"delta = {delta:.2e} over {S_cell} seeds ({wc:.1f}s)")


def stage_chip(q, platform):
    """Mesh-of-1 training on the attached TPU chip at production sizes;
    the repartition event cost is visible as the nr=1 vs nr=inf delta."""
    from tuplewise_tpu.models.pairwise_sgd import TrainConfig

    for n in ((2048,) if q else (100_000, 500_000)):
        for nr in (1, 10, NEVER):
            # le = NEVER is loss-free training [VERDICT r4 next #1]:
            # only step 0 records a loss, every later step takes the
            # grad-only kernel — same trajectory, ~1.4x the step rate
            for le in (1, NEVER):
                _throughput_row(
                    n,
                    TrainConfig(kernel="hinge", lr=0.3, n_workers=1,
                                repartition_every=nr, seed=7,
                                tile=2048, loss_every=le),
                    label=(
                        f"chip_n{n}_nr{'inf' if nr >= NEVER else nr}"
                        + ("_lossfree" if le >= NEVER else "")
                    ),
                    platform=platform,
                    steps_timed=5 if q else 20,
                    out_name="learning_throughput_chip.jsonl",
                )


def stage_trace(q, platform):
    """Profiler evidence for the trainer [VERDICT r2 next #7]: a warm
    20-step run with n_r=2 under jax.profiler, digested to text by
    scripts/trace_summary.py (results/trace_train_chip_summary.txt).
    The repartition events appear as conditional/dynamic-slice/gather
    rows against the step scan's while loop. r5: the traced config is
    LOSS-FREE (loss_every > steps) — the production recommendation —
    so the digest shows the grad-only kernel dominating the step."""
    import subprocess

    import jax

    from tuplewise_tpu.data import make_gaussian_splits
    from tuplewise_tpu.models.pairwise_sgd import TrainConfig, train_pairwise
    from tuplewise_tpu.models.scorers import LinearScorer

    n = 2048 if q else 100_000
    Xp, Xn, _, _ = make_gaussian_splits(n, 1000, dim=5, seed=0)
    scorer = LinearScorer(dim=5)
    p0 = scorer.init(0)
    cfg = TrainConfig(kernel="hinge", lr=0.3, steps=20, n_workers=1,
                      repartition_every=2, seed=7, tile=2048,
                      loss_every=NEVER)
    train_pairwise(scorer, p0, Xp, Xn, cfg)   # warm SAME chunk length
    trace_dir = _out_path("trace_train_chip")
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)  # one run per digest
    with jax.profiler.trace(trace_dir):
        t0 = time.perf_counter()
        train_pairwise(scorer, p0, Xp, Xn, cfg)
        log(f"traced 20 steps n_r=2 in {time.perf_counter() - t0:.2f}s")
    digest = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_summary.py"),
         trace_dir, "14"],
        capture_output=True, text=True, check=True,
    ).stdout
    out = _out_path("trace_train_chip_summary.txt")
    with open(out, "w") as f:
        f.write(digest)
    log(f"wrote {out}")


def stage_figs():
    from tuplewise_tpu.harness.figures import (
        plot_auc_vs_budget, plot_auc_vs_comm, plot_design_budget,
        plot_learning_curves, plot_sd_vs_comm, plot_triplet_curves,
    )

    os.makedirs(FIGS, exist_ok=True)

    def load(name):
        p = _out_path(name)
        if not os.path.exists(p):
            return []
        with open(p) as f:
            return [json.loads(x) for x in f if x.strip()]

    def fig_path(name):
        return os.path.join(FIGS, _quick_name(name))

    for dataset, fname in (("gaussians", "learning_gauss.jsonl"),
                           ("adult", "learning_adult.jsonl")):
        rows = [r for r in load(fname) if r["pairs_per_worker"] is None]
        if not rows:
            continue
        for N in sorted({r["n_workers"] for r in rows}):
            sub = [r for r in rows if r["n_workers"] == N]
            plot_learning_curves(
                sub,
                fig_path(f"learning_curves_{dataset}_N{N}.png"),
                title=f"{dataset}, N={N} workers "
                      f"(m={sub[0]['m_per_worker'][0]}/class)",
            )
        plot_auc_vs_comm(
            rows,
            fig_path(f"learning_auc_vs_comm_{dataset}.png"),
            title=f"{dataset}: final held-out AUC vs communication",
        )
        plot_sd_vs_comm(
            rows,
            fig_path(f"learning_sd_vs_comm_{dataset}.png"),
            title=f"{dataset}: partition-induced spread vs communication",
        )
    # pair-budget sweep figure: B rows + the matching all-pairs rows
    gauss = load("learning_gauss.jsonl")
    b_rows = [r for r in gauss if r["pairs_per_worker"] is not None]
    if b_rows:
        N = b_rows[0]["n_workers"]
        nrs = {r["n_r"] for r in b_rows}
        full = [r for r in gauss if r["pairs_per_worker"] is None
                and r["n_workers"] == N and r["n_r"] in nrs]
        plot_auc_vs_budget(
            b_rows + full,
            fig_path("learning_auc_vs_budget.png"),
            title=f"gaussians, N={N}: pair budget x repartition",
        )
    d_rows = load("learning_designs.jsonl")
    if d_rows:
        plot_design_budget(
            d_rows,
            fig_path("learning_design_budget.png"),
            title=f"gaussians, N={d_rows[0]['n_workers']}: pair-budget "
                  "DESIGNS (B/G = 25%, 50% of the per-worker grid)",
        )
    t_rows = load("learning_triplet.jsonl")
    if t_rows:
        plot_triplet_curves(
            t_rows,
            fig_path("learning_triplet_curves.png"),
            title="degree-3 metric learner, k=2 bottleneck",
        )
    log(f"figures written to {FIGS}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--stages",
                    default="gauss,adult,designs,prod,triplet,mesh8,figs",
                    help="comma list: gauss,adult,designs,prod,triplet,"
                         "mesh8,chip,gauss-chip,trace,figs")
    args = ap.parse_args()
    stages = set(args.stages.split(","))
    known = {"gauss", "adult", "designs", "prod", "triplet", "mesh8",
             "chip", "gauss-chip", "trace", "figs"}
    if stages - known:
        ap.error(f"unknown stages {sorted(stages - known)}")
    _cpu_stages = {"gauss", "adult", "designs", "prod", "triplet",
                   "mesh8"}
    if stages & {"chip", "gauss-chip", "trace"} and stages & _cpu_stages:
        ap.error("run --stages chip in its own invocation: the platform "
                 "(TPU vs forced-CPU) is process-global")
    global QUICK
    QUICK = args.quick
    os.makedirs(RESULTS, exist_ok=True)

    if stages & _cpu_stages:
        # sim sweeps + virtual mesh run on the forced-CPU platform (8
        # virtual devices for mesh8); same conftest dance as tests/
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    else:
        import jax

        platform = jax.devices()[0].platform

    if "gauss" in stages:
        stage_gauss(args.quick, platform)
    if "adult" in stages:
        stage_adult(args.quick, platform)
    if "designs" in stages:
        stage_designs(args.quick, platform)
    if "prod" in stages:
        stage_prod(args.quick, platform)
    if "triplet" in stages:
        stage_triplet(args.quick, platform)
    if "mesh8" in stages:
        stage_mesh8(args.quick, platform)
    if "chip" in stages:
        stage_chip(args.quick, platform)
    if "gauss-chip" in stages:
        stage_gauss_chip(args.quick, platform)
    if "trace" in stages:
        stage_trace(args.quick, platform)
    # data stages completed: atomically publish their rows BEFORE figs
    # reads them (and so a crash above leaves committed files untouched)
    finalize_outputs()
    if "figs" in stages:
        stage_figs()
    log("done")


if __name__ == "__main__":
    main()
