"""Summarize a trace into a small text table.

Four input shapes, auto-detected:

* a **directory** — a ``jax.profiler`` trace
  (``<dir>/plugins/profile/<run>/*.trace.json.gz``): device-side
  complete events digested into per-op totals so the ring hot-loop
  profile can be committed as text (RESULTS.md) and diffed across
  rounds [VERDICT r1 next #10];
* a ``*.jsonl`` **file** — the span JSONL exported by
  ``obs.tracing.Tracer.export_jsonl`` (``--trace-out x.jsonl``)
  [ISSUE 6]: top spans by SELF time (total minus child time — the
  number that says where the wall-clock actually went, not how deep
  the span nests), plus a per-stage insert-latency p99 table;
* a ``*.json`` **file** — the Chrome trace-event export
  (``--trace-out x.json``): same summary, read from the ``X`` events'
  embedded span/parent ids;
* a ``*.collapsed`` / ``*.txt`` / ``*.speedscope.json`` **file** —
  the sampling profiler's export (``obs.prof.SamplingProfiler``,
  ``--prof-out``) [ISSUE 14]: samples classified into a **host-tax
  table** (which layer of the stack the request-thread wall-clock
  burns in — serving Python, pack/mesh glue, jax dispatch, numpy,
  WAL/snapshot IO, waiting) plus the top leaf frames.

Usage: python scripts/trace_summary.py
           <dir | spans.jsonl | trace.json | prof.collapsed> [top_n]
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict


def load_events(trace_dir: str):
    pats = [
        os.path.join(trace_dir, "**", "*.trace.json.gz"),
        os.path.join(trace_dir, "**", "*.trace.json"),
    ]
    files = sorted(f for p in pats for f in glob.glob(p, recursive=True))
    if not files:
        raise FileNotFoundError(f"no trace json under {trace_dir!r}")
    events, pids = [], {}
    for f in files:
        op = gzip.open if f.endswith(".gz") else open
        with op(f, "rt") as fh:
            data = json.load(fh)
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e.get("pid")] = e.get("args", {}).get("name", "")
            elif e.get("ph") == "X":
                events.append(e)
    return events, pids


def summarize(trace_dir: str, top_n: int = 15) -> str:
    events, pids = load_events(trace_dir)
    if not events:
        raise ValueError(
            f"{trace_dir!r} has trace JSON but no complete ('X') "
            f"events — aborted or host-only profiler run?"
        )
    # keep device-side lanes (TPU/TensorCore/device XLA ops); python/
    # host lanes carry dispatch noise, not the kernel profile
    def is_device(e):
        name = pids.get(e.get("pid"), "").lower()
        return any(k in name for k in ("tpu", "device", "xla", "/tc"))

    dev = [e for e in events if is_device(e)] or events
    per_op = defaultdict(float)
    t0 = min(e["ts"] for e in dev)
    t1 = max(e["ts"] + e.get("dur", 0) for e in dev)
    for e in dev:
        per_op[e["name"]] += e.get("dur", 0.0)
    total = sum(per_op.values())
    lines = [
        f"trace: {trace_dir}",
        f"device events: {len(dev)}  span: {(t1 - t0) / 1e6:.3f}s  "
        f"summed op time: {total / 1e6:.3f}s",
        f"{'op':<58} {'total_ms':>10} {'share':>7}",
    ]
    for name, dur in sorted(per_op.items(), key=lambda kv: -kv[1])[:top_n]:
        nm = name if len(name) <= 57 else name[:54] + "..."
        share = dur / total if total else 0.0
        lines.append(f"{nm:<58} {dur / 1e3:>10.2f} {share:>6.1%}")
    return "\n".join(lines)


# --------------------------------------------------------------------- #
# span-trace summaries [ISSUE 6]                                         #
# --------------------------------------------------------------------- #

def load_spans(path: str):
    """Spans as dicts with trace_id/span_id/parent_id/name/t0_s/dur_s,
    from either the span JSONL or the Chrome trace-event export."""
    if path.endswith(".jsonl"):
        spans = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if "meta" in rec:
                    continue
                spans.append(rec)
        return spans
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    spans = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args", {})
        spans.append({
            "trace_id": args.get("trace_id"),
            "span_id": args.get("span_id"),
            "parent_id": args.get("parent_id"),
            "name": e["name"],
            "t0_s": e["ts"] / 1e6,
            "dur_s": e.get("dur", 0.0) / 1e6,
        })
    return spans


def _quantile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)


def summarize_spans(path: str, top_n: int = 15) -> str:
    spans = load_spans(path)
    if not spans:
        raise ValueError(f"{path!r} contains no spans")
    # self time: total minus the time of DIRECT children — the honest
    # "where did the wall-clock go" attribution
    child_time = defaultdict(float)
    for s in spans:
        if s.get("parent_id") is not None:
            child_time[s["parent_id"]] += s["dur_s"]
    agg = defaultdict(lambda: {"n": 0, "total": 0.0, "self": 0.0,
                               "durs": []})
    for s in spans:
        a = agg[s["name"]]
        a["n"] += 1
        a["total"] += s["dur_s"]
        a["self"] += max(0.0, s["dur_s"] - child_time.get(s["span_id"],
                                                          0.0))
        a["durs"].append(s["dur_s"])
    n_traces = len({s["trace_id"] for s in spans})
    t0 = min(s["t0_s"] for s in spans)
    t1 = max(s["t0_s"] + s["dur_s"] for s in spans)
    lines = [
        f"trace: {path}",
        f"spans: {len(spans)}  traces: {n_traces}  "
        f"span window: {t1 - t0:.3f}s",
        "",
        f"{'span (by self time)':<34} {'n':>7} {'self_ms':>10} "
        f"{'total_ms':>10} {'p99_ms':>9}",
    ]
    by_self = sorted(agg.items(), key=lambda kv: -kv[1]["self"])
    for name, a in by_self[:top_n]:
        nm = name if len(name) <= 33 else name[:30] + "..."
        lines.append(
            f"{nm:<34} {a['n']:>7} {a['self'] * 1e3:>10.2f} "
            f"{a['total'] * 1e3:>10.2f} "
            f"{_quantile(a['durs'], 0.99) * 1e3:>9.3f}")
    # per-stage insert-latency table: the insert.* children tile each
    # request's lifetime, so these p99s ARE the latency decomposition
    stages = {n: a for n, a in agg.items() if n.startswith("insert.")}
    if stages:
        lines += ["", f"{'insert stage':<24} {'n':>7} {'p50_ms':>9} "
                      f"{'p99_ms':>9} {'max_ms':>9}"]
        for name, a in sorted(stages.items(),
                              key=lambda kv: -kv[1]["total"]):
            lines.append(
                f"{name:<24} {a['n']:>7} "
                f"{_quantile(a['durs'], 0.5) * 1e3:>9.3f} "
                f"{_quantile(a['durs'], 0.99) * 1e3:>9.3f} "
                f"{max(a['durs']) * 1e3:>9.3f}")
    return "\n".join(lines)


# --------------------------------------------------------------------- #
# host-tax digest of sampling-profiler exports [ISSUE 14]                 #
# --------------------------------------------------------------------- #

# (category, substring-match over the frame's trimmed path) — first
# match wins, checked leaf-to-root so the innermost classifiable frame
# decides; order encodes specificity
_HOST_TAX_CATEGORIES = (
    ("wait_idle", ("threading.py:wait", "threading.py:_wait",
                   "queue.py:get", "queue.py:put", "selectors.py:",
                   "socket.py:", "ssl.py:")),
    ("gc_or_prof", ("obs/prof.py:", "obs/ledger.py:")),
    ("wal_snapshot_io", ("serving/recovery.py:",)),
    ("jax_dispatch", ("jax/", "jaxlib/", "jax\\", "/pjit.py:",
                      "pallas/")),
    ("mesh_glue", ("parallel/sharded_counts.py:", "parallel/mesh.py:",
                   "parallel/self_heal.py:")),
    ("serving_python", ("serving/", "estimators/")),
    ("observability", ("obs/", "utils/profiling.py:")),
    ("numpy_host", ("numpy/", "numpy\\")),
)


def classify_frame(frame: str):
    for cat, pats in _HOST_TAX_CATEGORIES:
        for p in pats:
            if p in frame:
                return cat
    return None  # unclassified — caller falls back toward the root


def classify_stack(stack) -> str:
    """Walk leaf→root; the innermost frame with a known category
    names the sample (a numpy sort called from serving code is
    numpy_host — the time is IN numpy, which is the honest leaf-time
    attribution collapsed stacks give)."""
    for frame in reversed(stack):
        cat = classify_frame(frame)
        if cat is not None:
            return cat
    return "other_host"


def load_collapsed(path: str):
    """[(stack tuple root→leaf, count)] from a collapsed-stack file or
    a speedscope "sampled" export."""
    if path.endswith(".speedscope.json") or path.endswith(".json"):
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        frames = [fr["name"] for fr in doc["shared"]["frames"]]
        out = []
        for prof in doc.get("profiles", []):
            if prof.get("type") != "sampled":
                continue
            for sample in prof.get("samples", []):
                out.append((tuple(frames[i] for i in sample), 1))
        return out
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            stack, _, n = line.rpartition(" ")
            out.append((tuple(stack.split(";")), int(n)))
    return out


def summarize_collapsed(path: str, top_n: int = 15) -> str:
    """The host-tax table: sample share per stack layer, plus the top
    leaf frames — the committed-text digest of where the host Python
    time actually burns."""
    stacks = load_collapsed(path)
    if not stacks:
        raise ValueError(f"{path!r} contains no stack samples")
    by_cat = defaultdict(int)
    by_leaf = defaultdict(int)
    total = 0
    for stack, n in stacks:
        total += n
        by_cat[classify_stack(stack)] += n
        by_leaf[stack[-1]] += n
    lines = [
        f"profile: {path}",
        f"samples: {total}  distinct stacks: {len(stacks)}",
        "",
        f"{'host-tax category':<24} {'samples':>8} {'share':>7}",
    ]
    for cat, n in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        lines.append(f"{cat:<24} {n:>8} {n / total:>6.1%}")
    lines += ["", f"{'top leaf frame':<52} {'samples':>8} {'share':>7}"]
    for leaf, n in sorted(by_leaf.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:top_n]:
        nm = leaf if len(leaf) <= 51 else leaf[:48] + "..."
        lines.append(f"{nm:<52} {n:>8} {n / total:>6.1%}")
    return "\n".join(lines)


if __name__ == "__main__":
    d = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 15
    if os.path.isdir(d):
        print(summarize(d, n))
    elif d.endswith((".collapsed", ".txt")) \
            or d.endswith(".speedscope.json"):
        print(summarize_collapsed(d, n))
    else:
        print(summarize_spans(d, n))
