"""Summarize a jax.profiler trace directory into a small text table.

The profiler writes perfetto JSON under
``<dir>/plugins/profile/<run>/*.trace.json.gz``; this digests the
device-side complete events ("ph" == "X") into per-op totals so the
ring hot-loop profile can be committed as text (RESULTS.md) and diffed
across rounds [VERDICT r1 next #10] — the raw trace is too big and too
opaque to review.

Usage: python scripts/trace_summary.py results/trace_mesh_complete [top_n]
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict


def load_events(trace_dir: str):
    pats = [
        os.path.join(trace_dir, "**", "*.trace.json.gz"),
        os.path.join(trace_dir, "**", "*.trace.json"),
    ]
    files = sorted(f for p in pats for f in glob.glob(p, recursive=True))
    if not files:
        raise FileNotFoundError(f"no trace json under {trace_dir!r}")
    events, pids = [], {}
    for f in files:
        op = gzip.open if f.endswith(".gz") else open
        with op(f, "rt") as fh:
            data = json.load(fh)
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e.get("pid")] = e.get("args", {}).get("name", "")
            elif e.get("ph") == "X":
                events.append(e)
    return events, pids


def summarize(trace_dir: str, top_n: int = 15) -> str:
    events, pids = load_events(trace_dir)
    if not events:
        raise ValueError(
            f"{trace_dir!r} has trace JSON but no complete ('X') "
            f"events — aborted or host-only profiler run?"
        )
    # keep device-side lanes (TPU/TensorCore/device XLA ops); python/
    # host lanes carry dispatch noise, not the kernel profile
    def is_device(e):
        name = pids.get(e.get("pid"), "").lower()
        return any(k in name for k in ("tpu", "device", "xla", "/tc"))

    dev = [e for e in events if is_device(e)] or events
    per_op = defaultdict(float)
    t0 = min(e["ts"] for e in dev)
    t1 = max(e["ts"] + e.get("dur", 0) for e in dev)
    for e in dev:
        per_op[e["name"]] += e.get("dur", 0.0)
    total = sum(per_op.values())
    lines = [
        f"trace: {trace_dir}",
        f"device events: {len(dev)}  span: {(t1 - t0) / 1e6:.3f}s  "
        f"summed op time: {total / 1e6:.3f}s",
        f"{'op':<58} {'total_ms':>10} {'share':>7}",
    ]
    for name, dur in sorted(per_op.items(), key=lambda kv: -kv[1])[:top_n]:
        nm = name if len(name) <= 57 else name[:54] + "..."
        share = dur / total if total else 0.0
        lines.append(f"{nm:<58} {dur / 1e3:>10.2f} {share:>6.1%}")
    return "\n".join(lines)


if __name__ == "__main__":
    d = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 15
    print(summarize(d, n))
