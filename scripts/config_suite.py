"""BASELINE.json benchmark suite — one committed number per config.

Self-baselined per SURVEY §6 (the reference published nothing): the
frozen NumPy oracle path is the baseline, the JAX/TPU paths are the
build. Emits results/configs.jsonl, one line per BASELINE config:

  1 AUC U-statistic, synthetic Gaussians, n=10k: numpy vs jax vs pallas
    parity + pairs/s
  2 bipartite ranking, pairwise hinge, Adult: AUC lift + steps/s
  3 incomplete U, n=10^6, B=10^4 (also in results/pairs_n1e6.jsonl)
  4 degree-3 triplet kernel on MNIST embeddings: numpy/jax parity + time
  5 cross-shard ring all-pairs at n=10^7 total: per-chip throughput of
    the mesh backend (mesh of 1 on this host's chip; 8-shard semantics
    are exercised on the virtual CPU mesh by tests/ and
    __graft_entry__.dryrun_multichip)

Usage: python scripts/config_suite.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
RESULTS = os.path.join(REPO, "results")


def log(msg):
    print(f"[configs] {msg}", file=sys.stderr, flush=True)


QUICK = False   # set by main(); stamped so quick rows can't pass as full


def emit(rec, out):
    if QUICK:
        rec["quick"] = True
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    out.write(json.dumps(rec) + "\n")
    out.flush()
    log(json.dumps(rec))


def timed(fn, reps=3):
    fn()  # warm / compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def config1(out, q):
    """AUC U-stat on Gaussians, n=10k total: parity + pairs/s."""
    from tuplewise_tpu.data import make_gaussians
    from tuplewise_tpu.estimators.estimator import Estimator

    n = 640 if q else 5000
    X, Y = make_gaussians(n, n, dim=1, separation=1.0, seed=0)
    s1, s2 = X[:, 0], Y[:, 0]
    vals, rates = {}, {}
    for backend in ("numpy", "jax", "cpp"):
        try:
            est = Estimator("auc", backend=backend)
        except Exception as e:
            log(f"config1: {backend} unavailable: {e!r}")
            continue
        vals[backend] = float(est.complete(s1, s2))
        rates[backend] = n * n / timed(lambda: est.complete(s1, s2))
    emit({
        "config": 1, "name": "auc_gaussians_n10k",
        "n_pos": n, "n_neg": n, "estimates": vals,
        "pairs_per_s": {k: round(v, 1) for k, v in rates.items()},
        "max_parity_delta": max(
            abs(v - vals["numpy"]) for v in vals.values()
        ),
    }, out)


def config2(out, q):
    """Pairwise hinge bipartite ranking on (surrogate) UCI Adult, with
    held-out evaluation [VERDICT r2 next #2]: train on the train split,
    report train AND test AUC."""
    from tuplewise_tpu.data import load_adult_splits
    from tuplewise_tpu.models.pairwise_sgd import (
        TrainConfig, evaluate_auc, split_by_label, train_pairwise,
    )
    from tuplewise_tpu.models.scorers import LinearScorer

    import jax

    n = 400 if q else 8000
    steps = 20 if q else 200
    X, y, Xte, yte, meta = load_adult_splits(n=n, seed=0)
    Xp, Xn = split_by_label(X, y)
    Xp_te, Xn_te = split_by_label(Xte, yte)
    scorer = LinearScorer(dim=Xp.shape[1])
    p0 = scorer.init(0)
    cfg = TrainConfig(kernel="hinge", lr=0.3, steps=steps,
                      n_workers=min(4, jax.device_count()),
                      repartition_every=10, seed=0)
    # warm with the SAME step count (chunk length is a static jit arg;
    # a different warm length would leave a recompile in the window)
    train_pairwise(scorer, p0, Xp, Xn, cfg)
    t0 = time.perf_counter()
    params, hist = train_pairwise(scorer, p0, Xp, Xn, cfg)
    dt = time.perf_counter() - t0
    auc_tr0 = evaluate_auc(scorer, p0, Xp, Xn)
    auc_tr1 = evaluate_auc(scorer, params, Xp, Xn)
    auc_te0 = evaluate_auc(scorer, p0, Xp_te, Xn_te)
    auc_te1 = evaluate_auc(scorer, params, Xp_te, Xn_te)
    fig = None
    try:  # figure is a bonus — never lose the metrics record to it
        from tuplewise_tpu.harness.figures import plot_learning_curve

        figdir = os.path.join(RESULTS, "figures")
        os.makedirs(figdir, exist_ok=True)
        fig = plot_learning_curve(
            hist, os.path.join(figdir, "learning_curve_adult.png"),
            auc_before=auc_te0, auc_after=auc_te1,
        )
    except Exception as e:
        log(f"config2: learning-curve figure failed: {e!r}")
    emit({
        "config": 2, "name": "pairwise_hinge_adult",
        "n": n, "steps": steps, "n_workers": cfg.n_workers,
        "n_test": len(Xte),
        "data_synthetic": bool(meta["synthetic"]),
        "split": meta.get("split"),
        "auc_train_before": auc_tr0, "auc_train": auc_tr1,
        "auc_test_before": auc_te0, "auc_test": auc_te1,
        "loss_first": float(hist["loss"][0]),
        "loss_last": float(hist["loss"][-1]),
        "steps_per_s": round(steps / dt, 2),
        "figure": fig,
    }, out)


def config2b(out, q):
    """Gradient throughput of the pairwise learner's hot loop.

    Measured via a self-contained jitted SGD scan: it isolates the
    gradient hot loop from trainer plumbing and from remote-compile
    jitter (train_pairwise itself now caches its compiled chunk per
    configuration and matches this rate on repeat calls). Both
    gradient paths are reported: analytic streamed g' (the trainer's
    path for hinge/logistic) vs autodiff through the checkpointed
    tiles (the fallback for kernels without diff_grad_fn)."""
    from tuplewise_tpu.data import make_gaussians
    from tuplewise_tpu.models.scorers import LinearScorer

    import jax
    import jax.numpy as jnp
    from jax import lax

    from tuplewise_tpu.ops import pair_tiles
    from tuplewise_tpu.ops.kernels import get_kernel

    n = 512 if q else 100_000   # per class
    steps = 3 if q else 10
    kernel = get_kernel("hinge")
    Xp, Xn = make_gaussians(n, n, dim=5, separation=1.0, seed=1)
    Xp, Xn = jnp.asarray(Xp, jnp.float32), jnp.asarray(Xn, jnp.float32)
    scorer = LinearScorer(dim=5)
    p0 = jax.tree.map(jnp.asarray, scorer.init(1))
    rng = np.random.default_rng(2)

    def sync(tree):
        return float(sum(np.sum(np.asarray(x))
                         for x in jax.tree.leaves(tree)))

    rates = {}
    for label, mean_fn in (
        ("analytic_gp", lambda s1, s2: pair_tiles.diff_pair_mean(
            kernel, s1, s2, 2048, 2048)),
        ("autodiff_tiles", lambda s1, s2: pair_tiles.pair_mean(
            kernel, s1, s2, tile_a=2048, tile_b=2048)),
    ):
        def loss(p):
            return mean_fn(
                scorer.apply(p, Xp, jnp), scorer.apply(p, Xn, jnp)
            )

        def step(p, _):
            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda x, gg: x - 0.1 * gg, p, g), l

        f = jax.jit(lambda p: lax.scan(step, p, None, length=steps))
        sync(f(p0))  # compile (cached: same jit object reused)
        ts = []
        for _ in range(3):
            pp = jax.tree.map(
                lambda x: x + 1e-6 * jnp.asarray(
                    rng.standard_normal(x.shape), jnp.float32), p0)
            t0 = time.perf_counter()
            sync(f(pp))
            ts.append(time.perf_counter() - t0)
        rates[label] = round(steps * n * n / min(ts), 1)
    emit({
        "config": "2b", "name": "pairwise_grad_throughput",
        "n_pos": n, "n_neg": n, "steps": steps, "tile": 2048,
        "grad_pairs_per_s": rates,
    }, out)


def config3(out, q):
    """Incomplete U at n=10^6 total, B=10^4 (headline row also lives in
    results/pairs_n1e6.jsonl with M=200 Monte-Carlo reps)."""
    from tuplewise_tpu.data import make_gaussians
    from tuplewise_tpu.estimators.estimator import Estimator

    n = 1000 if q else 500_000
    X, Y = make_gaussians(n, n, dim=1, separation=1.0, seed=0)
    s1, s2 = X[:, 0], Y[:, 0]
    est = Estimator("auc", backend="jax")
    val = float(est.incomplete(s1, s2, n_pairs=10_000, seed=0))
    dt = timed(lambda: est.incomplete(s1, s2, n_pairs=10_000, seed=0))
    emit({
        "config": 3, "name": "incomplete_n1e6_B1e4",
        "n_pos": n, "n_neg": n, "B": 10_000, "estimate": val,
        "seconds_per_estimate": round(dt, 5),
        "mc_reference": "results/pairs_n1e6.jsonl",
    }, out)


def config4(out, q):
    """Degree-3 triplet statistic on MNIST embeddings (surrogate unless
    real IDX files are in TUPLEWISE_DATA_DIR)."""
    from tuplewise_tpu.harness.triplet_experiment import (
        triplet_mnist_statistic,
    )

    n = 200 if q else 2000
    r_np = triplet_mnist_statistic(
        kernel="triplet_indicator", backend="numpy", n=n,
        n_pairs=20_000, seed=0,
    )
    t0 = time.perf_counter()
    r_jx = triplet_mnist_statistic(
        kernel="triplet_indicator", backend="jax", n=n,
        n_pairs=20_000, seed=0,
    )
    dt = time.perf_counter() - t0

    # COMPLETE degree-3 throughput through the distance factorization
    # (ops.pallas_triplets via impl="pallas") — the reproducible source
    # of RESULTS §1's triplets/s row [VERDICT r3 next #3]. Distinct
    # inputs per rep + host-read sync, the bench.py discipline.
    import numpy as np

    from tuplewise_tpu.estimators.estimator import Estimator

    rng = np.random.default_rng(0)
    est_t = Estimator("triplet_indicator", backend="jax", impl="pallas")

    def rate_at(nt, d, reps):
        """Complete-triplet throughput at one (n, d) shape — distinct
        inputs per rep + host-read sync (the bench.py discipline).
        Inputs are made DEVICE-RESIDENT before the timed window: a
        numpy input would put an [nt, d] host->device tunnel transfer
        inside the clock (8.4 MB at d=128 — it depressed the r5 d=128
        row ~35% until caught against resident-input probes)."""
        import jax.numpy as jnp

        inputs = [
            (jnp.asarray(rng.standard_normal((nt, d)).astype(np.float32)),
             jnp.asarray(rng.standard_normal((nt, d)).astype(np.float32)
                         + 0.3))
            for _ in range(reps + 1)
        ]
        for X, Y in inputs:                 # force residency
            float(jnp.sum(X) + jnp.sum(Y))
        est_t.complete(*inputs[0])          # compile outside the timer
        times = []
        # the warm pair never re-enters the timed loop: the runtime can
        # memoize an identical repeated call (bench.py discipline)
        for X, Y in inputs[1:]:
            t1 = time.perf_counter()
            est_t.complete(X, Y)            # float() inside = synced
            times.append(time.perf_counter() - t1)
        return float(nt) * (nt - 1) * nt / min(times), min(times)

    def rate_at_segmented(nt, d, seg=16384):
        """n=65536 cell: this host's axon tunnel kills single device
        programs past ~60-75 s (worker watchdog — reproduced with a
        3x-scan of the KNOWN-GOOD n=32768 program, so it is an
        execution-length limit of the tunnel, not a kernel property).
        The measurement therefore host-loops jitted sub-programs over
        (anchor, positive, negative) segments — an EXACT partition of
        the statistic (sums/counts additive over grid tiles), each
        sub-program ~20 s of device time. One compile (all sub-shapes
        identical); wall-clock spans the full loop."""
        import jax

        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pallas_triplets import (
            pallas_triplet_stats,
        )

        kt = get_kernel("triplet_indicator")
        X = rng.standard_normal((nt, d)).astype(np.float32)
        Y = (rng.standard_normal((nt, d)) + 0.3).astype(np.float32)
        import jax.numpy as jnp

        Xd, Yd = jnp.asarray(X), jnp.asarray(Y)
        ids = jnp.arange(nt, dtype=jnp.int32)
        float(jnp.sum(Xd) + jnp.sum(Yd))

        @jax.jit
        def sub(a, ia, p, ip, y):
            return pallas_triplet_stats(
                kt, a, y, ids_x=ia, positives=p, ids_p=ip,
            )

        def run_all():
            s_tot = c_tot = 0.0
            for a0 in range(0, nt, seg):
                for p0 in range(0, nt, 2 * seg):
                    for k0 in range(0, nt, 2 * seg):
                        s, c = sub(
                            Xd[a0:a0 + seg], ids[a0:a0 + seg],
                            Xd[p0:p0 + 2 * seg], ids[p0:p0 + 2 * seg],
                            Yd[k0:k0 + 2 * seg],
                        )
                        s_tot += float(s)
                        c_tot += float(c)
            return s_tot, c_tot

        # warm: one sub-program compiles the (only) shape — with
        # SWAPPED operands so it matches no timed subcall (the runtime
        # can memoize an identical repeated call), SYNCED by host read
        # (async dispatch would otherwise leave ~17 s of warm device
        # time running inside the timed window; block_until_ready is
        # unreliable through this tunnel)
        ws, wc = sub(Yd[:seg], ids[:seg], Yd[:2 * seg], ids[:2 * seg],
                     Xd[:2 * seg])
        float(ws), float(wc)
        t1 = time.perf_counter()
        s_tot, c_tot = run_all()
        dt_all = time.perf_counter() - t1
        assert abs(c_tot - float(nt) * (nt - 1) * nt) < 1e-3 * c_tot
        return float(nt) * (nt - 1) * nt / dt_all, dt_all

    # Scaling grid + roofline [VERDICT r4 next #4]: the factorized path
    # is O(n^2 d) MXU distance phase + O(n^3) scalar combine, so the
    # rate should RISE with n toward the pure pair-kernel asymptote
    # (distance fraction ~ d * pair_rate / (n * mxu_rate)) and fall
    # with d at fixed n. The committed grid measures exactly that;
    # reps shrink at the big shapes (one n=65536 rep is ~2.8e14
    # triplets — minutes of chip time; the n^3 term dominates so
    # run-to-run spread is small).
    grid = ([(256, 8, 3)] if q else [
        (4096, 16, 3), (4096, 32, 3), (4096, 128, 3),
        (16384, 16, 2), (16384, 32, 2), (16384, 128, 2),
        (32768, 16, 1), (32768, 32, 1), (32768, 128, 1),
        (65536, 32, 1),
    ])
    scale_rows = []
    for nt, d, reps in grid:
        segmented = nt >= 65536
        try:
            if segmented:
                r, dt_min = rate_at_segmented(nt, d)
            else:
                r, dt_min = rate_at(nt, d, reps)
        except Exception as e:   # one cell must not void the grid
            log(f"config4 scaling n={nt} d={d} FAILED: {e!r}")
            scale_rows.append({
                "n": nt, "dim": d, "reps": reps, "error": repr(e)[:300],
            })
            continue
        row = {
            "n": nt, "dim": d, "reps": reps,
            "triplets_per_s": round(r, 1),
            "seconds": round(dt_min, 3),
        }
        if segmented:
            # honest label: 16 host-looped sub-programs (the tunnel's
            # ~60 s execution watchdog forbids one big program here),
            # so the rate INCLUDES 16 dispatch round-trips
            row["host_segmented"] = True
        scale_rows.append(row)
        log(f"config4 scaling n={nt} d={d}: {r:.3e} triplets/s "
            f"({dt_min:.1f}s){' [segmented]' if segmented else ''}")
    from tuplewise_tpu.utils.results_io import quick_sibling

    spath = os.path.join(
        RESULTS, quick_sibling("triplet_scaling.jsonl", QUICK)
    )
    with open(spath + ".partial", "w") as f:
        for r in scale_rows:
            r["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            f.write(json.dumps(r) + "\n")
    os.replace(spath + ".partial", spath)

    ok_rows = [r for r in scale_rows if "error" not in r]
    if not ok_rows:
        # every cell failed (tunnel down / kernel regression): still
        # emit the config row so the error-annotated grid is on record
        emit({
            "config": 4, "name": "triplet_mnist", "n": n,
            "numpy": r_np, "jax": r_jx,
            "jax_seconds_total": round(dt, 3),
            "scaling_error": "all scaling cells failed; see "
                             + os.path.basename(spath),
        }, out)
        return
    big = max(ok_rows, key=lambda r: (r["n"], r["triplets_per_s"]))

    emit({
        "config": 4, "name": "triplet_mnist",
        "n": n, "numpy": r_np, "jax": r_jx,
        "jax_seconds_total": round(dt, 3),
        # headline = the LARGEST-n rate [VERDICT r4 next #4]; the full
        # grid is results/triplet_scaling.jsonl
        "complete_triplets_per_s": big["triplets_per_s"],
        "complete_throughput_shape": {"n_anchors": big["n"],
                                      "dim": big["dim"]},
        "scaling_file": "results/triplet_scaling.jsonl",
    }, out)


def config5(out, q):
    """Cross-shard ring all-pairs at n=10^7 total: the mesh backend's
    ppermute ring (mask-aware Pallas hot loop) on this host's chip."""
    import jax

    from tuplewise_tpu.backends.mesh_backend import MeshBackend
    from tuplewise_tpu.ops.kernels import get_kernel

    n = 1000 if q else 5_000_000   # per class; 2n = 10^7 total
    rng = np.random.default_rng(5)
    be = MeshBackend(get_kernel("auc"), n_workers=jax.device_count(),
                     tile_a=2048, tile_b=8192)
    pa = be._pack_complete(rng.standard_normal(n).astype(np.float32))
    pb = be._pack_complete(rng.standard_normal(n).astype(np.float32))

    no_masks = n % be.n_shards == 0   # same padding guard as .complete()

    def go():
        (a, ma, ia), (b, mb, ib) = pa, pb
        return float(be._complete(a, ma, ia, b, mb, ib,
                                  no_masks=no_masks))

    val = go()
    dt = timed(go, reps=1 if not q else 2)
    emit({
        "config": 5, "name": "ring_all_pairs_n1e7",
        "n_pos": n, "n_neg": n, "n_shards": be.n_shards,
        "impl": be.impl, "estimate": val,
        "pairs_per_s_per_chip": round(n * n / dt / be.n_shards, 1),
        "seconds": round(dt, 2),
        "multi_shard_evidence":
            "tests/test_mesh_backend.py + test_mesh_2d.py (8 virtual "
            "CPU devices) + __graft_entry__.dryrun_multichip",
    }, out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--configs", default="1,2,2b,3,4,5")
    args = ap.parse_args()
    global QUICK
    QUICK = args.quick
    os.makedirs(RESULTS, exist_ok=True)
    from tuplewise_tpu.utils.results_io import quick_sibling

    # quick runs write a sibling file: a smoke run must never replace
    # the committed full-run rows (rule shared via utils.results_io)
    path = os.path.join(RESULTS, quick_sibling("configs.jsonl", QUICK))
    wanted = set(args.configs.split(","))
    fns = {"1": config1, "2": config2, "2b": config2b, "3": config3,
           "4": config4, "5": config5}
    # a subset run replaces only ITS rows — truncating the whole file
    # here once silently destroyed the other configs' committed rows
    keep = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if str(rec.get("config")) not in wanted:
                    keep.append(line)
    # Atomic publish with per-config durability: each config's rows
    # collect in memory, then keep + everything-finished-so-far rewrites
    # a .partial sibling and os.replace()s onto the real file AFTER EVERY
    # config — a crash (even SIGKILL, which a try/except can't catch)
    # mid-config loses only that config's rows, never the kept rows or
    # earlier configs' hours of results.
    import io

    done_rows = []
    partial = path + ".partial"
    for key in sorted(wanted):
        buf = io.StringIO()
        try:
            fns[key](buf, args.quick)
        except Exception as e:  # keep the suite going; record why
            emit({"config": key, "error": repr(e)}, buf)
        done_rows.append(buf.getvalue())
        with open(partial, "w") as out:
            out.writelines(keep)
            out.writelines(done_rows)
        os.replace(partial, path)
    log(f"wrote {path}")


if __name__ == "__main__":
    main()
