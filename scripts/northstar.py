"""North-star experiment driver [BASELINE.json:2,5; VERDICT r1 next #1].

Runs the paper-shaped suite on the attached TPU chip and writes every
artifact the trade-off figures need:

  results/variance_n1e6.jsonl   complete/local at n=10^6 (M=200)
  results/rounds_n1e6.jsonl     repartitioned T in {1,2,4,8,16} (M=200)
  results/pairs_n1e6.jsonl      incomplete B in {1e3..1e7}     (M=200)
  results/variance_n1e7.jsonl   complete/local at n=10^7 (M=32)
  results/rounds_n1e7.jsonl     repartitioned T in {1,2,4,8}  (M=16)
  results/pairs_n1e7.jsonl      incomplete B in {1e3..1e7}    (M=64)
  results/mesh_n1e6.jsonl       mesh backend (ring path), mesh of 1
  results/figures/*.png         the three paper-shaped figures

"n" is the TOTAL sample size (n_pos = n_neg = n/2), matching the
paper's usage; the complete grid at n=10^7 is 2.5e13 pairs per rep.
Wall-clocks recorded by the harness are compute-only (compile excluded)
— what the variance-vs-wallclock axis needs. Chunked execution
(checkpoint_every) bounds HBM and amortizes the one warm-up chunk.

Usage: python scripts/northstar.py [--quick]   (--quick: tiny sanity run)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
RESULTS = os.path.join(REPO, "results")

from tuplewise_tpu.harness.variance import (  # noqa: E402
    VarianceConfig, run_variance_experiment, write_jsonl,
)


def log(msg):
    print(f"[northstar +{time.perf_counter() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


T0 = time.perf_counter()

QUICK = False    # set by main(); quick output never touches full files

from tuplewise_tpu.utils.results_io import (  # noqa: E402
    is_quick, quick_sibling, strip_quick,
)


def _qname(name: str) -> str:
    return quick_sibling(name, QUICK)


def _out(name: str) -> str:
    return os.path.join(RESULTS, _qname(name))


_touched = set()


def run(cfg, out, chunk=None, trace_dir=None):
    path = _out(out)
    # write_jsonl appends; truncate each output once per invocation so
    # re-running a stage (e.g. after a crash) never duplicates rows
    if path not in _touched:
        _touched.add(path)
        if os.path.exists(path):
            os.remove(path)
    r = run_variance_experiment(
        cfg, checkpoint_every=chunk, trace_dir=trace_dir
    )
    write_jsonl([r], path)
    log(f"{out}: scheme={cfg.scheme} T={cfg.n_rounds} B={cfg.n_pairs} "
        f"var={r['variance']:.3e} wc={r['wallclock_s']:.1f}s")
    return r


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--stages", type=str,
                    default="1e6,1e7,tradeoff,designs,mesh,exact,scale8,"
                            "serve,figs",
                    help="comma list of stages to run (the default runs "
                         "everything RESULTS.md commits: the production "
                         "scales, the visible-trade-off regime, the "
                         "sampling-design rows, the mesh ring, the exact "
                         "rank-AUC series, the n=10^8 scale demo, and "
                         "the serving replay)")
    args = ap.parse_args()
    global QUICK
    QUICK = args.quick
    stages = set(args.stages.split(","))
    known = {"1e6", "1e7", "tradeoff", "designs", "mesh", "exact",
             "scale8", "serve", "figs"}
    if stages - known:
        ap.error(f"unknown stages {sorted(stages - known)}; "
                 f"choose from {sorted(known)}")
    os.makedirs(RESULTS, exist_ok=True)
    os.makedirs(os.path.join(RESULTS, "figures"), exist_ok=True)

    q = args.quick
    # n here is PER CLASS: n_pos = n_neg = n/2 of the total sample size
    n6 = 1_000 if q else 500_000          # "n = 10^6"
    n7 = 2_000 if q else 5_000_000        # "n = 10^7"
    m6 = 8 if q else 200
    m7 = 4 if q else 32
    m7r = 4 if q else 16

    base6 = VarianceConfig(n_pos=n6, n_neg=n6, n_workers=8, n_reps=m6)
    base7 = VarianceConfig(n_pos=n7, n_neg=n7, n_workers=8, n_reps=m7)

    if "1e6" in stages:
        log(f"== stage n=1e6 (n_pos=n_neg={n6}, M={m6}) ==")
        run(base6, "variance_n1e6.jsonl", chunk=None if q else 8)
        run(dataclasses.replace(base6, scheme="local"),
            "variance_n1e6.jsonl", chunk=None if q else 8)
        for T in (1, 2, 4, 8, 16):
            run(dataclasses.replace(
                    base6, scheme="repartitioned", n_rounds=T),
                "rounds_n1e6.jsonl", chunk=None if q else 8)
        for B in (1_000, 10_000, 100_000, 1_000_000, 10_000_000):
            if q and B > 100_000:
                continue
            run(dataclasses.replace(base6, scheme="incomplete", n_pairs=B),
                "pairs_n1e6.jsonl", chunk=None if q else 25)

    if "1e7" in stages:
        log(f"== stage n=1e7 (n_pos=n_neg={n7}, M={m7}) ==")
        run(base7, "variance_n1e7.jsonl", chunk=None if q else 1)
        run(dataclasses.replace(base7, scheme="local"),
            "variance_n1e7.jsonl", chunk=None if q else 1)
        for T in (1, 2, 4, 8):
            run(dataclasses.replace(
                    base7, scheme="repartitioned", n_rounds=T,
                    n_reps=m7r),
                "rounds_n1e7.jsonl", chunk=None if q else 1)
        for B in (1_000, 10_000, 100_000, 1_000_000, 10_000_000):
            if q and B > 100_000:
                continue
            run(dataclasses.replace(
                    base7, scheme="incomplete", n_pairs=B,
                    n_reps=4 if q else 64),
                "pairs_n1e7.jsonl", chunk=None if q else 8)

    if "tradeoff" in stages:
        # The paper's VISIBLE trade-off regime: many workers, small
        # per-worker blocks (the local-average deficit ~ zeta_11/(n*m)
        # needs m in the tens). N sweep for the local estimator, T
        # sweeps at the two smallest block sizes, plus the closed-form
        # Hoeffding prediction for the overlay [SURVEY §1.2, §5.1].
        mt = 8 if q else 800
        baset = dataclasses.replace(base6, n_reps=mt)
        log(f"== stage tradeoff (n_pos=n_neg={n6}, M={mt}) ==")
        run(baset, "tradeoff_complete.jsonl", chunk=None if q else 8)
        n_sweep = (2, 4) if q else (8, 100, 1000, 12500, 125000, 250000)
        for N in n_sweep:
            run(dataclasses.replace(baset, scheme="local", n_workers=N),
                "tradeoff_workers.jsonl", chunk=None if q else 8)
        for N in ((4,) if q else (125000, 250000)):
            for T in (1, 2) if q else (1, 2, 4, 8, 16, 32):
                run(dataclasses.replace(
                        baset, scheme="repartitioned", n_workers=N,
                        n_rounds=T),
                    f"tradeoff_rounds_N{N}.jsonl", chunk=None if q else 25)
        # plug-in zetas on a 20k sample -> closed-form overlay curves
        from tuplewise_tpu.data import make_gaussians
        from tuplewise_tpu.estimators.variance import (
            two_sample_variance_from_zetas, two_sample_zetas,
        )

        Xz, Yz = make_gaussians(20_000, 20_000, 1, 1.0, seed=7)
        zetas = two_sample_zetas("auc", Xz[:, 0], Yz[:, 0])
        vc = two_sample_variance_from_zetas(zetas, n6, n6)

        def v_loc(N):
            return two_sample_variance_from_zetas(
                zetas, n6 // N, n6 // N) / N

        theory = {
            "zetas": list(zetas),
            "complete": vc,
            "workers": [[int(N), v_loc(N)] for N in n_sweep],
            "rounds": {
                str(N): [[T, vc + max(v_loc(N) - vc, 0.0) / T]
                         for T in (1, 2, 4, 8, 16, 32)]
                for N in ((4,) if q else (125000, 250000))
            },
        }
        with open(_out("tradeoff_theory.json"), "w") as f:
            json.dump(theory, f, indent=1)
        log("tradeoff stage done (theory overlay written)")

    if "designs" in stages:
        # Sampling designs MEASURED, not just implemented [VERDICT r3
        # next #4]. Headline scale first: B << G = n1*n2, so the
        # finite-population factor is ~1 and swor/bernoulli are
        # variance-NEUTRAL vs swr — the committed rows pin that
        # prediction (each z-checks against its own fpc closed form,
        # scripts/stat_check.py).
        log("== stage sampling designs (swor/bernoulli, measured) ==")
        for design in ("swor", "bernoulli"):
            for B in (1_000, 10_000, 100_000):
                if q and B > 10_000:
                    continue
                run(dataclasses.replace(
                        base6, scheme="incomplete", n_pairs=B,
                        design=design),
                    "designs_n1e6.jsonl", chunk=None if q else 25)
        # Where the reduction LIVES: conditional on a frozen dataset
        # (fix_data=True), Monte-Carlo over sampling randomness only.
        # The audit's closed forms are then EXACT (s^2 = U(1-U), no
        # plug-in): swor at B = G/2 halves the swr conditional
        # variance; at B = G/10 it removes 10%. Only B/G matters for
        # the factor, so n=500/class (G=250k) keeps the host-designed
        # index blocks small; chunking bounds them at [250, B].
        mC = 8 if q else 2_000
        baseC = VarianceConfig(
            n_pos=500, n_neg=500, separation=1.0, n_workers=2,
            n_reps=mC, fix_data=True,
        )
        for design in ("swr", "swor", "bernoulli"):
            for B in (25_000, 125_000):
                if q and B > 25_000:
                    continue
                run(dataclasses.replace(
                        baseC, scheme="incomplete", n_pairs=B,
                        design=design),
                    "designs_conditional.jsonl", chunk=None if q else 250)
        # Degree-3 conditional rows [VERDICT r4 next #3]: same frozen-
        # data audit, triplet grid G = n1(n1-1)n2 = 62,400 at n=40/class
        # (only B/G sets the fpc factor, so the small grid keeps the
        # host-designed index blocks at [chunk, ~B]); z-checked against
        # the EXACT s^2 = U(1-U) forms by scripts/stat_check.py.
        GT = 40 * 39 * 40
        baseT = VarianceConfig(
            kernel="triplet_indicator", n_pos=40, n_neg=40, dim=3,
            separation=1.0, n_workers=2, n_reps=mC, fix_data=True,
        )
        for design in ("swr", "swor", "bernoulli"):
            for B in (GT // 10, GT // 2):
                if q and B > GT // 10:
                    continue
                run(dataclasses.replace(
                        baseT, scheme="incomplete", n_pairs=B,
                        design=design),
                    "designs_conditional.jsonl", chunk=None if q else 250)

    if "mesh" in stages:
        # the DISTRIBUTED estimator on the real chip: mesh of 1, ring
        # hot loop (pallas impl), on-device Monte-Carlo.  Validates the
        # deliverable path end-to-end on hardware and captures the
        # profiler traces the ring engineering is judged by.
        import jax

        nw = jax.device_count()
        log(f"== stage mesh ({nw}-device mesh, platform="
            f"{jax.devices()[0].platform}) ==")
        mesh6 = dataclasses.replace(
            base6, backend="mesh", n_workers=nw,
            n_reps=8 if q else 50,
        )
        run(mesh6, "mesh_n1e6.jsonl", chunk=None if q else 4,
            trace_dir=_out("trace_mesh_complete"))
        run(dataclasses.replace(mesh6, scheme="repartitioned", n_rounds=4),
            "mesh_n1e6.jsonl", chunk=None if q else 4,
            trace_dir=_out("trace_mesh_repart"))
        run(dataclasses.replace(mesh6, scheme="local"), "mesh_n1e6.jsonl",
            chunk=None if q else 4)
        # designed incomplete THROUGH THE MESH at scale [VERDICT r4
        # next #6 evidence]: distinct tuple sets drawn on device per
        # rep (ops.device_design), sharded [N, per], cross-shard
        # regather + psum'd weighted mean — zero host syncs in the
        # rep loop; the swr row prices the design's extra cost
        for design in ("swr", "swor"):
            run(dataclasses.replace(
                    mesh6, scheme="incomplete", n_pairs=100_000,
                    design=design, n_reps=8 if q else 50),
                "mesh_n1e6.jsonl", chunk=None if q else 10)
        # HBM high-water of the mesh stage (devices that report it)
        from tuplewise_tpu.utils.profiling import device_memory_stats

        for dev, stats in device_memory_stats().items():
            log(f"memory {dev}: "
                + ", ".join(f"{k}={v}" for k, v in sorted(stats.items())
                            if "bytes" in k))

    if "exact" in stages:
        # The AUC statistic has an O(n log n) EXACT path (ops.rank_auc:
        # one sort + two searchsorteds); the frontier's complete-U
        # wall-clock prices GENERIC-kernel streaming, which overstates
        # the cost of exactness for this special case [VERDICT r2
        # next #6]. Same Monte-Carlo protocol as the 1e6/1e7 stages
        # (fresh Gaussian draws per rep, on-device), rank-AUC estimator.
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from tuplewise_tpu.ops.rank_auc import rank_auc
        from tuplewise_tpu.utils.rng import fold, root_key

        log("== stage exact (rank-AUC fast path) ==")
        for scale, n, M in (("n1e6", n6, m6), ("n1e7", n7, m7)):
            def one_rep(rep, n=n):
                key = fold(root_key(0), "mc_rep", rep)
                k1, k2 = jax.random.split(fold(key, "data"))
                s1 = jax.random.normal(k1, (n,), jnp.float32) + 1.0
                s2 = jax.random.normal(k2, (n,), jnp.float32)
                return rank_auc(s1, s2)

            runner = jax.jit(
                lambda reps, f=one_rep: lax.map(f, reps)
            )
            # warm at the SAME shape: the rep-array length is part of
            # the jit signature, so a shorter warm run would leave a
            # recompile inside the timed window
            np.asarray(runner(jnp.arange(M)))
            t0 = time.perf_counter()
            ests = np.asarray(runner(jnp.arange(M)))
            wc = time.perf_counter() - t0
            row = {
                "config": {
                    "kernel": "auc", "scheme": "complete",
                    "estimator": "rank_auc_exact", "backend": "jax",
                    "n_pos": n, "n_neg": n, "dim": 1,
                    "separation": 1.0, "n_workers": 1, "n_rounds": 1,
                    "n_pairs": 0, "partition_scheme": "swor",
                    "n_reps": M, "seed": 0,
                },
                "mean": float(ests.mean()),
                "variance": float(ests.var(ddof=1)),
                "std_error": float(ests.std(ddof=1) / np.sqrt(M)),
                "wallclock_s": wc,
                "vmapped": True,
                "n_reps": M,
            }
            path = _out(f"exact_{scale}.jsonl")
            if os.path.exists(path):
                os.remove(path)
            write_jsonl([row], path)
            log(f"exact_{scale}: var={row['variance']:.3e} "
                f"wc={wc:.3f}s for M={M} ({wc / M * 1e3:.1f} ms/rep)")

    if "scale8" in stages:
        # n = 10^8 TOTAL samples — one decade past the headline scale.
        # The complete grid is 2.5e15 pairs (~80 min/rep streamed), so
        # the tractable paths at this n are the O(n log n) exact rank
        # statistic and the incomplete family: exactly the regime the
        # paper argues for. Chunked reps bound HBM (one rep's 400 MB of
        # scores live at a time for the exact path; incomplete chunks
        # by 4).
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tuplewise_tpu.ops.rank_auc import rank_auc
        from tuplewise_tpu.utils.rng import fold, root_key

        n8 = 4_000 if q else 50_000_000     # per class; 10^8 total
        M8 = 2 if q else 8
        log(f"== stage scale8 (n_pos=n_neg={n8}, M={M8}) ==")

        # ONE rep per dispatch, looped on the host: the XLA sort at
        # n=5e7 runs ~60 s, and a single lax.map program spanning all
        # reps exceeded what the axon tunnel worker tolerates (it
        # crashed mid-program); per-rep dispatches are each bounded
        @jax.jit
        def one_rep8(rep, n=n8):
            key = fold(root_key(0), "mc_rep", rep)
            k1, k2 = jax.random.split(fold(key, "data"))
            s1 = jax.random.normal(k1, (n,), jnp.float32) + 1.0
            s2 = jax.random.normal(k2, (n,), jnp.float32)
            return rank_auc(s1, s2)

        float(one_rep8(jnp.asarray(0)))       # compile outside the timer
        ests, wc = [], 0.0
        for rep in range(M8):
            t0 = time.perf_counter()
            ests.append(float(one_rep8(jnp.asarray(rep))))
            wc += time.perf_counter() - t0
            log(f"  scale8 exact rep {rep + 1}/{M8}")
        ests = np.asarray(ests)
        row = {
            "config": {
                "kernel": "auc", "scheme": "complete",
                "estimator": "rank_auc_exact", "backend": "jax",
                "n_pos": n8, "n_neg": n8, "dim": 1,
                "separation": 1.0, "n_workers": 1, "n_rounds": 1,
                "n_pairs": 0, "partition_scheme": "swor",
                "n_reps": M8, "seed": 0,
            },
            "mean": float(ests.mean()),
            "variance": float(ests.var(ddof=1)),
            "std_error": float(ests.std(ddof=1) / np.sqrt(M8)),
            # NOT a vmapped/lax.map program: one jitted dispatch per
            # rep (see comment above) — stamp provenance honestly
            "wallclock_s": wc, "vmapped": False,
            "dispatch": "per_rep_jit", "n_reps": M8,
        }
        path = _out("exact_n1e8.jsonl")
        if os.path.exists(path):
            os.remove(path)
        write_jsonl([row], path)
        log(f"exact_n1e8: var={row['variance']:.3e} wc={wc:.1f}s "
            f"({wc / M8 * 1e3:.0f} ms/rep)")

        base8 = VarianceConfig(n_pos=n8, n_neg=n8, n_workers=8,
                               n_reps=M8)
        for B in (100_000, 10_000_000, 100_000_000):
            if q and B > 100_000:
                continue
            run(dataclasses.replace(
                    base8, scheme="incomplete", n_pairs=B),
                "pairs_n1e8.jsonl", chunk=None if q else 4)

    if "serve" in stages:
        # Online serving replay [ISSUE 1]: the micro-batched request
        # path over the streaming estimators. One row per configuration
        # cell: events/s, latency percentiles, batch fill, and the
        # exact-vs-oracle parity guardrail, to results/serving.jsonl.
        # Budget sweep exposes the ONLINE variance-vs-budget knob;
        # max_batch 1 row prices the coalescing the engine exists for.
        import jax

        from tuplewise_tpu.serving import ServingConfig
        from tuplewise_tpu.serving.replay import make_stream, replay

        nS = 2_000 if q else 300_000
        log(f"== stage serve (replay, n_events={nS}) ==")
        sS, lS = make_stream(nS, pos_frac=0.5, separation=1.0, seed=0)
        # run identity [ISSUE 7 satellite]: one id per northstar
        # invocation (replay stamps the config digest per cell), so
        # scripts/perf_gate.py can join history without guessing
        import uuid

        run_id = uuid.uuid4().hex[:12]
        path = _out("serving.jsonl")
        if os.path.exists(path):
            os.remove(path)
        # submission is a bounded closed loop (max_inflight): latency
        # percentiles then price per-event cost + pause spikes, not
        # queue backlog — the regime the bg-compaction p99 win [ISSUE 2]
        # is defined in. The sync-compaction cell is the on-thread
        # baseline that win is measured against.
        cells = [
            {"max_batch": 256, "budget": 64},        # sync compaction
            {"max_batch": 256, "budget": 64, "bg_compact": True},
            {"max_batch": 256, "budget": 4, "bg_compact": True},
            {"max_batch": 256, "budget": 64, "window": nS // 4,
             "bg_compact": True},
            {"max_batch": 1, "budget": 64},          # unbatched baseline
        ]
        if jax.device_count() >= 4:
            # mesh-sharded index (per-shard searchsorted + psum'd win
            # counts) — needs >= 4 devices (TPU pod slice, or the
            # 8-virtual-device CPU test config). Two cells [ISSUE 5]:
            # delta compaction (the default) vs the host-merge
            # full-re-placement engine — the rows' bytes_h2d /
            # bytes_per_compaction fields record the transfer win.
            cells.insert(2, {"max_batch": 256, "budget": 64,
                             "bg_compact": True, "mesh_shards": 4})
            cells.insert(3, {"max_batch": 256, "budget": 64,
                             "bg_compact": True, "mesh_shards": 4,
                             "delta_fraction": 0.0})
        p99s = {}
        for cell in cells:
            # low-latency regime (small flush window, 64 in flight):
            # the percentiles price per-event cost + pause spikes
            cfg = ServingConfig(policy="block", flush_timeout_s=0.0005,
                                compact_every=1024, **cell)
            # the unbatched baseline prices COALESCING (its rate is
            # length-stable); a shorter stream bounds its wall time
            nCell = min(nS, 50_000) if cell.get("max_batch") == 1 else nS
            rec = replay(sS[:nCell], lS[:nCell], config=cfg, warmup=not q,
                         max_inflight=64, run_id=run_id)
            rec["stage"] = "serve"
            rec["max_inflight"] = 64
            write_jsonl([rec], path)
            if cell.get("max_batch") != 1 and "window" not in cell \
                    and cell.get("budget") == 64 \
                    and "mesh_shards" not in cell:
                p99s[bool(cell.get("bg_compact"))] = \
                    rec["insert_latency_p99_ms"]
            log(f"serve {cell}: {rec['events_per_s']:.0f} ev/s "
                f"insert p99={rec['insert_latency_p99_ms']:.1f}ms "
                f"pause p99={rec['compaction_pause_p99_ms']} "
                f"fill={rec['mean_batch_fill']:.2f} "
                f"auc_err={rec.get('auc_abs_err')}")
        if True in p99s and False in p99s and p99s[True]:
            log(f"serve: bg-compaction p99 insert win = "
                f"{p99s[False] / p99s[True]:.1f}x "
                f"(sync {p99s[False]:.1f}ms -> bg {p99s[True]:.1f}ms)")

    if "figs" in stages:
        log("== stage figures ==")
        from tuplewise_tpu.harness.figures import (
            plot_frontier, plot_variance_vs_pairs,
            plot_variance_vs_rounds, plot_variance_vs_wallclock,
            plot_variance_vs_workers,
        )

        def load(name):
            p = _out(name)
            if not os.path.exists(p):
                return []
            with open(p) as f:
                return [json.loads(x) for x in f if x.strip()]

        figs = os.path.join(RESULTS, "figures")

        def fig(name):
            return os.path.join(figs, _qname(name))
        for scale in ("n1e6", "n1e7"):
            rounds = load(f"rounds_{scale}.jsonl")
            var = load(f"variance_{scale}.jsonl")
            pairs = load(f"pairs_{scale}.jsonl")
            exact = load(f"exact_{scale}.jsonl")
            comp = next(
                (r for r in var if r["config"]["scheme"] == "complete"),
                None,
            )
            if rounds:
                plot_variance_vs_rounds(
                    rounds, fig(f"var_vs_rounds_{scale}.png"),
                    baseline=comp,
                )
                plot_variance_vs_wallclock(
                    rounds + ([comp] if comp else []),
                    fig(f"var_vs_wallclock_{scale}.png"),
                )
            if pairs:
                plot_variance_vs_pairs(
                    pairs, fig(f"var_vs_pairs_{scale}.png"),
                )
            if var or rounds or pairs:
                plot_frontier(
                    {
                        "complete $U_n$ (generic streaming)":
                            [comp] if comp else [],
                        "local average": [
                            r for r in var
                            if r["config"]["scheme"] == "local"
                        ],
                        "repartitioned T=1..": rounds,
                        "incomplete B sweep": pairs,
                        # the AUC special case has an O(n log n) exact
                        # path — without this point the figure reads as
                        # "exactness costs 47 s", which is only true of
                        # generic kernels [VERDICT r2 next #6]
                        "exact rank-AUC ($O(n\\log n)$)": exact,
                    },
                    fig(f"frontier_{scale}.png"),
                )
        # trade-off-regime figures with the closed-form overlay
        tthe = {}
        tpath = _out("tradeoff_theory.json")
        if os.path.exists(tpath):
            with open(tpath) as f:
                tthe = json.load(f)
        tcomp = load("tradeoff_complete.jsonl")
        tcomp = tcomp[0] if tcomp else None
        workers = load("tradeoff_workers.jsonl")
        if workers:
            plot_variance_vs_workers(
                workers, fig("var_vs_workers.png"),
                baseline=tcomp, theory=tthe.get("workers"),
            )
        for name in sorted(os.listdir(RESULTS)):
            if not name.startswith("tradeoff_rounds_N"):
                continue
            # quick-suffixed inputs pair with quick-suffixed figures;
            # a quick figs run never reads (or overwrites) full data
            if is_quick(name) != QUICK:
                continue
            base = strip_quick(name)
            N = base[len("tradeoff_rounds_N"):-len(".jsonl")]
            plot_variance_vs_rounds(
                # load() re-applies the quick suffix to base names
                load(base),
                fig(f"var_vs_rounds_N{N}.png"),
                baseline=tcomp,
                theory=(tthe.get("rounds") or {}).get(N),
            )
        log("figures written to results/figures/")

    log("done")


if __name__ == "__main__":
    main()
