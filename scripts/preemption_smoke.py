"""CI preemption smoke [ISSUE 4 satellite].

The batch-path acceptance cycle, end to end through the real CLI:

1. run a short pairwise-SGD job uninterrupted and record its
   params digest;
2. rerun it with a chaos schedule that SIGKILLs the process right
   after its 2nd checkpoint lands (real preemption: the process dies
   mid-epoch, uncatchably);
3. rerun with ``--resume`` and assert the final params digest (and
   AUC) are bit-identical to the uninterrupted run;
4. same cycle for the mesh Monte-Carlo sweep (``variance
   --backend mesh``), asserting mean/variance parity.

Appends the row (stage "preemption_smoke") to a JSONL the workflow
uploads as an artifact. Exits nonzero on a missed kill, a missing
checkpoint, or any parity breach.

Usage: python scripts/preemption_smoke.py [--out results/preemption_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

_KILL_SPEC = json.dumps({"faults": [
    {"point": "checkpoint", "on_call": 2, "action": "sigkill"}]})


def _cli(args, expect_kill=False):
    p = subprocess.run(
        [sys.executable, "-m", "tuplewise_tpu.harness.cli"] + args,
        capture_output=True, text=True, env=dict(os.environ), cwd=REPO,
        timeout=300)
    if expect_kill:
        assert p.returncode == -signal.SIGKILL, (
            f"expected SIGKILL death, got rc={p.returncode}:\n"
            f"{p.stderr[-2000:]}")
        return None
    assert p.returncode == 0, p.stderr[-2000:]
    return json.loads(p.stdout.strip().splitlines()[-1])


def _cycle(name, args, ck, fields):
    """straight -> killed -> resumed; returns the parity record."""
    ref = _cli(list(args))
    _cli(args + ["--checkpoint", ck, "--checkpoint-every", "2",
                 "--chaos-spec", _KILL_SPEC], expect_kill=True)
    assert os.path.exists(ck), f"{name}: no checkpoint survived the kill"
    res = _cli(args + ["--checkpoint", ck, "--checkpoint-every", "2",
                       "--resume"])
    rec = {"resumed_from": res["recovery"]["resumed_from"]}
    assert rec["resumed_from"] > 0, f"{name}: resume started from 0"
    for f in fields:
        assert res[f] == ref[f], (
            f"{name}: {f} diverged after SIGKILL+resume: "
            f"{res[f]!r} != {ref[f]!r}")
        rec[f] = res[f]
    print(f"[preemption_smoke] {name}: bit-identical after "
          f"SIGKILL@step{rec['resumed_from']} + --resume",
          file=sys.stderr)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "preemption_smoke.jsonl"))
    args = ap.parse_args(argv)

    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        row = {"stage": "preemption_smoke", "ok": True}
        row["pairwise_sgd"] = _cycle(
            "pairwise_sgd",
            ["train", "--dataset", "gaussians", "--n", "256",
             "--steps", "8", "--n-workers", "2"],
            os.path.join(tmp, "sgd.npz"),
            ["params_sha256", "auc_test", "loss_last"])
        row["mesh_mc"] = _cycle(
            "mesh_mc",
            ["variance", "--backend", "mesh", "--scheme", "local",
             "--n-pos", "128", "--n-neg", "128", "--n-workers", "2",
             "--n-reps", "6", "--seed", "3"],
            os.path.join(tmp, "mc.npz"),
            ["mean", "variance"])

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "a", encoding="utf-8") as f:
        f.write(json.dumps(row) + "\n")
    print(f"[preemption_smoke] OK -> {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
