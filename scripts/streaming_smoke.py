"""CI streaming-latency smoke [ISSUE 2 satellite].

A fast end-to-end check of the serving path as CI sees it: replay a
small stream through the micro-batch engine with background compaction
on, assert the latency-percentile fields are present and the exact
estimate matches the batch oracle, and append the row (stage
"ci_smoke") to a serving JSONL the workflow uploads as an artifact.

Usage: python scripts/streaming_smoke.py [--n-events 4000]
                                         [--out results/serving_smoke.jsonl]
Exits nonzero on any missing field or parity breach.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REQUIRED_FIELDS = (
    "events_per_s",
    "insert_latency_p50_ms",
    "insert_latency_p95_ms",
    "insert_latency_p99_ms",
    "compaction_pause_p99_ms",
    "compactions",
    "auc_abs_err",
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-events", type=int, default=4_000)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "serving_smoke.jsonl"))
    args = ap.parse_args(argv)

    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(args.n_events, pos_frac=0.5,
                                 separation=1.0, seed=0)
    cfg = ServingConfig(policy="block", flush_timeout_s=0.002,
                        compact_every=256, bg_compact=True)
    rec = replay(scores, labels, config=cfg, max_inflight=256)
    rec["stage"] = "ci_smoke"

    failures = [f for f in REQUIRED_FIELDS if rec.get(f) is None]
    if failures:
        print(f"SMOKE FAIL: missing/None fields {failures}",
              file=sys.stderr)
        return 1
    if rec["compactions"] < 1:
        print("SMOKE FAIL: stream never crossed a compaction",
              file=sys.stderr)
        return 1
    # exact-index parity vs the batch oracle: the guardrail the whole
    # index design exists for — a streaming-vs-batch mismatch fails CI
    if rec["auc_abs_err"] > 1e-6:
        print(f"SMOKE FAIL: auc_abs_err={rec['auc_abs_err']}",
              file=sys.stderr)
        return 1
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write(json.dumps(rec) + "\n")
    print(
        f"streaming smoke OK: {rec['events_per_s']:.0f} ev/s, insert "
        f"p99={rec['insert_latency_p99_ms']:.2f}ms, "
        f"{rec['compactions']} compactions, "
        f"auc_abs_err={rec['auc_abs_err']:.1e} -> {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
