"""CI streaming-latency smoke [ISSUE 2 satellite; sharded delta leg
ISSUE 5].

A fast end-to-end check of the serving path as CI sees it: replay a
small stream through the micro-batch engine with background compaction
on, assert the latency-percentile fields are present and the exact
estimate matches the batch oracle, and append the row (stage
"ci_smoke") to a serving JSONL the workflow uploads as an artifact.

With ``--mesh-shards`` the smoke exercises the SHARDED index's delta
compaction instead: the same stream replays in delta mode and in the
PR 2 host-merge mode, and the run fails unless (1) both modes' exact
AUC is bit-identical (and a directly-driven delta index matches the
single-host index's wins2 exactly), and (2) the delta mode shipped
strictly fewer host→device bytes per minor compaction — the byte
saving the tier exists for.

Usage: python scripts/streaming_smoke.py [--n-events 4000]
                                         [--mesh-shards 2]
                                         [--delta-fraction 0.25]
                                         [--out results/serving_smoke.jsonl]
Exits nonzero on any missing field, parity breach, or byte regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REQUIRED_FIELDS = (
    "events_per_s",
    "insert_latency_p50_ms",
    "insert_latency_p95_ms",
    "insert_latency_p99_ms",
    "compaction_pause_p99_ms",
    "compactions",
    "auc_abs_err",
)


def _check_common(rec) -> int:
    failures = [f for f in REQUIRED_FIELDS if rec.get(f) is None]
    if failures:
        print(f"SMOKE FAIL: missing/None fields {failures}",
              file=sys.stderr)
        return 1
    if rec["compactions"] < 1:
        print("SMOKE FAIL: stream never crossed a compaction",
              file=sys.stderr)
        return 1
    # exact-index parity vs the batch oracle: the guardrail the whole
    # index design exists for — a streaming-vs-batch mismatch fails CI
    if rec["auc_abs_err"] > 1e-6:
        print(f"SMOKE FAIL: auc_abs_err={rec['auc_abs_err']}",
              file=sys.stderr)
        return 1
    return 0


def _write(rec, out_path: str) -> None:
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        f.write(json.dumps(rec) + "\n")


def _sharded_delta_leg(args) -> int:
    """[ISSUE 5 satellite] delta-compaction smoke on a small mesh.

    The per-minor byte margin needs the base to dwarf a delta chunk:
    below ~6k events the host path's re-placed block is still only a
    bucket or two, so the leg enforces a floor on the stream length.
    """
    import numpy as np

    from tuplewise_tpu.serving import ExactAucIndex, ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    n_events = max(args.n_events, 6_000)
    scores, labels = make_stream(n_events, pos_frac=0.5,
                                 separation=1.0, seed=0)
    recs = {}
    for mode, frac in (("delta", args.delta_fraction),
                       ("host_merge", 0.0)):
        cfg = ServingConfig(policy="block", flush_timeout_s=0.002,
                            compact_every=256, bg_compact=True,
                            mesh_shards=args.mesh_shards,
                            delta_fraction=frac,
                            max_delta_runs=args.max_delta_runs)
        recs[mode] = replay(scores, labels, config=cfg,
                            max_inflight=256)
        recs[mode]["stage"] = f"ci_smoke_sharded_{mode}"
        rc = _check_common(recs[mode])
        if rc:
            return rc
    delta, host = recs["delta"], recs["host_merge"]
    # parity bit: the two compaction engines must agree to the BIT on
    # the exact statistic over the same stream
    if delta["auc_exact"] != host["auc_exact"]:
        print(f"SMOKE FAIL: delta vs host-merge AUC mismatch "
              f"{delta['auc_exact']} != {host['auc_exact']}",
              file=sys.stderr)
        return 1
    # ... and a directly-driven delta index must match the SINGLE-HOST
    # index's integer win count exactly (windowed, so tombstones +
    # deltas + a major merge are all exercised). With --count-kernel a
    # THIRD index rides the same stream through the Pallas-fused count
    # path (interpret mode on CPU) and must match bit-for-bit at every
    # step [ISSUE 10 satellite].
    sc32 = scores.astype(np.float32)
    w = max(256, n_events // 3)
    sharded = ExactAucIndex(engine="jax", compact_every=128, window=w,
                            shards=args.mesh_shards,
                            delta_fraction=args.delta_fraction,
                            max_delta_runs=args.max_delta_runs)
    single = ExactAucIndex(engine="jax", compact_every=128, window=w)
    kernel = None
    if args.count_kernel:
        kernel = ExactAucIndex(engine="jax", compact_every=128,
                               window=w, shards=args.mesh_shards,
                               delta_fraction=args.delta_fraction,
                               max_delta_runs=args.max_delta_runs,
                               count_kernel=True)
    for i in range(0, len(sc32), 173):
        j = min(i + 173, len(sc32))
        sharded.insert_batch(sc32[i:j], labels[i:j])
        single.insert_batch(sc32[i:j], labels[i:j])
        if sharded._wins2 != single._wins2:
            print(f"SMOKE FAIL: wins2 diverged at event {j}",
                  file=sys.stderr)
            return 1
        if kernel is not None:
            kernel.insert_batch(sc32[i:j], labels[i:j])
            if kernel._wins2 != single._wins2:
                print(f"SMOKE FAIL: count-kernel wins2 diverged at "
                      f"event {j}", file=sys.stderr)
                return 1
    if kernel is not None:
        ksnap = kernel.metrics.snapshot()
        calls = ksnap["count_kernel_calls_total"]["value"]
        fallbacks = ksnap["count_kernel_fallbacks_total"]["value"]
        kernel.close()
        if calls < 1 or fallbacks:
            print(f"SMOKE FAIL: count kernel calls={calls} "
                  f"fallbacks={fallbacks} (expected active kernel, "
                  f"zero fallbacks)", file=sys.stderr)
            return 1
        delta["count_kernel"] = {"calls": int(calls),
                                 "fallbacks": int(fallbacks),
                                 "parity": "bit-identical"}
        print(f"count-kernel leg OK: {calls} fused dispatches, "
              f"0 fallbacks, wins2 bit-identical", file=sys.stderr)
    # the byte saving the tier exists for [ISSUE 5]
    if not delta["bytes_h2d"]:
        print("SMOKE FAIL: delta mode recorded zero bytes_h2d",
              file=sys.stderr)
        return 1
    if not (delta["bytes_per_compaction"]
            and host["bytes_per_compaction"]
            and delta["bytes_per_compaction"]
            < host["bytes_per_compaction"]):
        print(f"SMOKE FAIL: no byte saving per minor compaction "
              f"(delta {delta['bytes_per_compaction']} vs host "
              f"{host['bytes_per_compaction']})", file=sys.stderr)
        return 1
    _write(delta, args.out)
    print(
        f"sharded delta smoke OK (S={args.mesh_shards}): "
        f"{delta['bytes_per_compaction']:.0f} B/minor vs host "
        f"{host['bytes_per_compaction']:.0f} B "
        f"({host['bytes_per_compaction'] / delta['bytes_per_compaction']:.0f}x), "
        f"major_merges={delta['major_merges']}, "
        f"auc_abs_err={delta['auc_abs_err']:.1e} -> {args.out}",
        file=sys.stderr,
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-events", type=int, default=4_000)
    ap.add_argument("--mesh-shards", type=int, default=None,
                    help="run the sharded delta-compaction leg on an "
                         "N-device mesh instead of the plain smoke")
    ap.add_argument("--delta-fraction", type=float, default=0.25)
    ap.add_argument("--max-delta-runs", type=int, default=64)
    ap.add_argument("--count-kernel", action="store_true",
                    help="also drive the Pallas-fused count path "
                         "(interpret mode on CPU) and assert "
                         "bit-identical wins2 vs the XLA path "
                         "[ISSUE 10]")
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "serving_smoke.jsonl"))
    args = ap.parse_args(argv)

    if args.mesh_shards:
        return _sharded_delta_leg(args)

    from tuplewise_tpu.serving import ServingConfig
    from tuplewise_tpu.serving.replay import make_stream, replay

    scores, labels = make_stream(args.n_events, pos_frac=0.5,
                                 separation=1.0, seed=0)
    cfg = ServingConfig(policy="block", flush_timeout_s=0.002,
                        compact_every=256, bg_compact=True)
    rec = replay(scores, labels, config=cfg, max_inflight=256)
    rec["stage"] = "ci_smoke"

    rc = _check_common(rec)
    if rc:
        return rc
    if args.count_kernel:
        # kernel leg [ISSUE 10]: same stream through the engine with
        # the fused count path on — the exact statistic must be
        # bit-identical (integer counts)
        import dataclasses

        krec = replay(scores, labels,
                      config=dataclasses.replace(cfg,
                                                 count_kernel=True),
                      max_inflight=256)
        if krec["auc_exact"] != rec["auc_exact"]:
            print(f"SMOKE FAIL: count-kernel AUC mismatch "
                  f"{krec['auc_exact']} != {rec['auc_exact']}",
                  file=sys.stderr)
            return 1
        rec["count_kernel"] = {"parity": "bit-identical"}
        print("count-kernel leg OK: engine AUC bit-identical",
              file=sys.stderr)
    _write(rec, args.out)
    print(
        f"streaming smoke OK: {rec['events_per_s']:.0f} ev/s, insert "
        f"p99={rec['insert_latency_p99_ms']:.2f}ms, "
        f"{rec['compactions']} compactions, "
        f"auc_abs_err={rec['auc_abs_err']:.1e} -> {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
