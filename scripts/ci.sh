#!/usr/bin/env bash
# Tier-1 verify — the exact command ROADMAP.md pins as the regression
# gate, runnable locally or in CI. Forces the CPU platform; conftest.py
# adds --xla_force_host_platform_device_count=8 so the mesh/ring paths
# run on 8 virtual devices with no TPU attached.
#
# Usage: scripts/ci.sh
set -o pipefail
cd "$(dirname "$0")/.."

# Static invariant checks [ISSUE 12, dataflow tier ISSUE 13,
# host-cost/lifecycle tier ISSUE 15] — FIRST, because they need no
# jax and fail in seconds: lock-order/thread discipline, traced-code
# purity, telemetry cross-reference, compile-ladder discipline
# (flow-sensitive), config/CLI/doc drift, guard-inference race
# detection, integer-exactness + int32 overflow certification
# (diffed against the committed analysis/exactness_bounds.toml
# envelope), host-cost certification of the request path (per-root
# cost counters diffed against analysis/hotpath_budget.toml — growth
# fails naming root/site/budget line, shrinkage ratchets the budget
# down), exception-flow/future-lifecycle + error-taxonomy analysis,
# import cycles. The gate also asserts the epoch-keyed parse cache
# hits on a second in-job corpus load. Findings are suppressible only
# via the committed tuplewise_tpu/analysis/waivers.toml (bounded
# per-waiver counts = the ratchet); the JSON report lands at
# results/analysis_report.json, the SARIF twin (inline PR
# annotations) and the hotpath certificate artifact next to it.
timeout -k 10 180 python scripts/analysis_gate.py \
    --sarif results/analysis_report.sarif
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Streaming latency smoke [ISSUE 2]: replay a small stream through the
# serving engine (background compaction on), assert the insert-latency
# percentile fields are present and the exact index matches the batch
# oracle; writes results/serving_smoke.jsonl for the CI artifact.
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python scripts/streaming_smoke.py
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Sharded delta-compaction smoke [ISSUE 5; --count-kernel leg
# ISSUE 10]: the same replay on a 2-device mesh, delta mode vs the
# host-merge engine — asserts bit-identical AUC between the two
# engines (and vs the single-host index's integer wins), plus a strict
# host->device byte saving per minor compaction. --count-kernel drives
# a THIRD index through the Pallas-fused count path (interpret mode on
# CPU) and asserts bit-identical wins2 at every step with zero kernel
# fallbacks; writes results/serving_smoke_sharded.jsonl.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/streaming_smoke.py --mesh-shards 2 \
    --delta-fraction 0.25 --n-events 6000 --count-kernel \
    --out results/serving_smoke_sharded.jsonl
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Multi-tenant fleet smoke [ISSUE 8, whale leg ISSUE 9]: T=32 tenants
# over 2 mesh shards through the MultiTenantEngine — per-tenant
# wins2/AUC bit-identical to 32 independent single-tenant indexes,
# ONE jitted batched count per coalesced micro-batch, a healthy
# per-tenant (label-wildcard) SLO verdict with one series per tenant,
# typed quota shedding, PLUS the whale leg: one tenant at ~20x the
# median promotes (fleet_whale_promotions fired), parity holds through
# the promotion, and dirty-row placement ships strictly less than the
# full pack per re-place. --count-kernel [ISSUE 10] re-runs the
# fleet-vs-independents parity through the Pallas tenant-axis count
# kernel (interpret mode) asserting bit-identical wins2/AUC and zero
# fallbacks; writes results/multitenant_smoke.jsonl for the CI
# artifact.
timeout -k 10 360 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/multitenant_smoke.py --count-kernel
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Controller smoke [ISSUE 11]: a Zipf flash crowd at T=32/S=2 served
# twice — the SLO-driven FleetController keeps the controlled fleet's
# verdict healthy (typed per-tenant throttling BEFORE the breach, zero
# hard rejects, per-tenant wins2 bit-identical to independents through
# every actuation) while the uncontrolled twin breaches; `tuplewise
# doctor` must then attribute 100% of the actuations to the signal
# that caused them. Writes results/controller_smoke.jsonl.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/controller_smoke.py
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Chaos smoke [ISSUE 3]: a seeded fault schedule (shard death +
# compactor crash + batcher crash + poison events) through replay;
# asserts every recovery counter fired and the final AUC is
# bit-identical to the fault-free run on the same admitted events;
# writes results/chaos_smoke.jsonl for the CI artifact.
timeout -k 10 240 env JAX_PLATFORMS=cpu \
    python scripts/chaos_smoke.py
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Preemption smoke [ISSUE 4]: SIGKILL a short SGD run and a mesh
# Monte-Carlo sweep right after a checkpoint lands (chaos 'sigkill'
# action), resume each with --resume, and assert the final
# params/estimates are bit-identical to the uninterrupted runs;
# writes results/preemption_smoke.jsonl for the CI artifact.
timeout -k 10 420 env JAX_PLATFORMS=cpu \
    python scripts/preemption_smoke.py
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Observability smoke [ISSUE 6; profiler leg ISSUE 14]: a traced
# chaos replay must produce a schema-valid Chrome/perfetto trace whose
# per-stage spans sum to the measured insert latency (>= 95% per
# trace), a metrics.jsonl with >= 2 periodic registry snapshots, a
# flight-recorder dump in which every injected fault / compaction /
# heal appears exactly once with a correlating trace id, PLUS the
# host-tax leg: the wave ledger's bucket sums tile the measured
# insert latency EXACTLY (coverage == 1.0), >= 1 tail exemplar lands
# under the injected 60ms batcher delay, and the sampling profiler's
# speedscope + collapsed exports are schema-valid and digestible into
# the host-tax table; all files land under results/ for the CI
# artifact.
timeout -k 10 240 env JAX_PLATFORMS=cpu \
    python scripts/obs_smoke.py
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Doctor [ISSUE 7]: post-hoc diagnosis over the obs_smoke artifacts.
# The chaos run must diagnose as non-degraded (every injected fault
# correlated with recovery evidence => verdict "recovered", exit 0;
# "degraded" exits 2) and the last stdout line must be one
# machine-parseable JSON verdict; report + verdict land under
# results/ for the CI artifact.
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python -m tuplewise_tpu.harness.cli doctor \
    --metrics results/metrics.jsonl \
    --flight results/obs_flight.jsonl \
    --spans results/obs_spans.jsonl \
    --quiet --out results/doctor_report.json \
    | tee results/doctor_verdict.jsonl
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ]; then exit "$rc"; fi
python - <<'PYEOF'
import json
line = open("results/doctor_verdict.jsonl").read().strip().splitlines()[-1]
v = json.loads(line)
assert v["healthy"], v
assert v["faults"] == v["faults_resolved"] >= 4, v
print("doctor verdict OK:", v)
PYEOF
rc=$?
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Perf gate [ISSUE 7, fail since ISSUE 8, multi-stage since ISSUE 9]:
# the newest row of EACH gated stage (bench_streaming, multi_tenant,
# fleet_incremental — the last adds bytes-per-pack-re-place so the
# dirty-row saving can never quietly regress — and serving_kernel
# [ISSUE 10], whose kernel_calls_per_batch witness must hold at
# exactly 1.0) in the committed results/serving.jsonl vs its
# comparable history, with noise bands; any stage breach fails CI.
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python scripts/perf_gate.py --mode fail
exit $?
