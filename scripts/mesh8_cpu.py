"""Distributed-semantics evidence on an 8-worker virtual mesh.

The TPU host has one chip, so the hardware runs in RESULTS.md are
mesh-of-1. This script runs the REAL mesh backend — shard_map, ppermute
ring, on-device repartitioning, psum — over 8 virtual CPU devices and
Monte-Carlos each scheme, so the committed JSONL shows the N=8
distributed estimators producing the same statistics the closed forms
predict (unbiased means, ordered variances), not just passing unit
tests. Run:

    python scripts/mesh8_cpu.py          # writes results/mesh8_cpu.jsonl
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tuplewise_tpu.harness.variance import (  # noqa: E402
    VarianceConfig, run_variance_experiment, write_jsonl,
)


def main():
    assert jax.device_count() >= 8, jax.devices()
    out = os.path.join(REPO, "results", "mesh8_cpu.jsonl")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    if os.path.exists(out):
        os.remove(out)
    base = VarianceConfig(
        backend="mesh", n_workers=8, n_pos=8192, n_neg=8192, n_reps=100,
    )
    runs = [base, dataclasses.replace(base, scheme="local")]
    runs += [
        dataclasses.replace(base, scheme="repartitioned", n_rounds=T)
        for T in (1, 4, 16)
    ]
    runs += [
        dataclasses.replace(base, scheme="incomplete", n_pairs=B)
        for B in (1_000, 100_000)
    ]
    t0 = time.perf_counter()
    for cfg in runs:
        r = run_variance_experiment(cfg, checkpoint_every=25)
        r["devices"] = str(jax.devices()[0])
        write_jsonl([r], out)
        print(json.dumps({
            "scheme": cfg.scheme, "T": cfg.n_rounds, "B": cfg.n_pairs,
            "mean": round(r["mean"], 6),
            "variance": r["variance"],
        }), flush=True)
    print(f"# wrote {out} in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
