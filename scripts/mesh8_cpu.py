"""Distributed-semantics evidence on an 8-worker virtual mesh.

The TPU host has one chip, so the hardware runs in RESULTS.md are
mesh-of-1. This script runs the REAL mesh backend — shard_map, ppermute
ring, on-device repartitioning, psum — over 8 virtual CPU devices and
Monte-Carlos each scheme, so the committed JSONL shows the N=8
distributed estimators producing the same statistics the closed forms
predict (unbiased means, ordered variances), not just passing unit
tests. A second section (r3) runs the 2-D (dcn=2 x ici=4) HIERARCHICAL
double ring and the non-diff kernel kinds (scatter one-sample with
global-id exclusion; degree-3 triplet double ring) through the
mesh-native MC runner, so the multi-host layout and the full
kernel-kind matrix have committed statistics too. Run:

    python scripts/mesh8_cpu.py     # results/mesh8_cpu.jsonl + mesh8_2d_cpu.jsonl
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tuplewise_tpu.harness.variance import (  # noqa: E402
    VarianceConfig, run_variance_experiment, write_jsonl,
)


def main():
    assert jax.device_count() >= 8, jax.devices()
    out = os.path.join(REPO, "results", "mesh8_cpu.jsonl")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    if os.path.exists(out):
        os.remove(out)
    base = VarianceConfig(
        backend="mesh", n_workers=8, n_pos=8192, n_neg=8192, n_reps=100,
    )
    runs = [base, dataclasses.replace(base, scheme="local")]
    runs += [
        dataclasses.replace(base, scheme="repartitioned", n_rounds=T)
        for T in (1, 4, 16)
    ]
    runs += [
        dataclasses.replace(base, scheme="incomplete", n_pairs=B)
        for B in (1_000, 100_000)
    ]
    t0 = time.perf_counter()
    for cfg in runs:
        r = run_variance_experiment(cfg, checkpoint_every=25)
        r["devices"] = str(jax.devices()[0])
        write_jsonl([r], out)
        print(json.dumps({
            "scheme": cfg.scheme, "T": cfg.n_rounds, "B": cfg.n_pairs,
            "mean": round(r["mean"], 6),
            "variance": r["variance"],
        }), flush=True)
    print(f"# wrote {out} in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    main_2d_and_kernels()


def main_2d_and_kernels():
    """2-D hierarchical ring + non-diff kernel kinds, mesh-native MC."""
    import jax.numpy as jnp
    import numpy as np

    from tuplewise_tpu.harness.mesh_mc import make_mesh_mc_runner
    from tuplewise_tpu.parallel.mesh import make_mesh_2d

    out = os.path.join(REPO, "results", "mesh8_2d_cpu.jsonl")
    if os.path.exists(out):
        os.remove(out)
    mesh2d = make_mesh_2d(2, 4)
    t0 = time.perf_counter()
    rows = [
        # 2-D dcn x ici double ring, every scheme, incl. a ragged size
        ("2d", VarianceConfig(backend="mesh", n_workers=8, n_pos=8192,
                              n_neg=8192, n_reps=100)),
        ("2d", VarianceConfig(backend="mesh", n_workers=8, n_pos=8192,
                              n_neg=8192, n_reps=100, scheme="local")),
        ("2d", VarianceConfig(backend="mesh", n_workers=8, n_pos=8192,
                              n_neg=8192, n_reps=100,
                              scheme="repartitioned", n_rounds=4)),
        ("2d", VarianceConfig(backend="mesh", n_workers=8, n_pos=8197,
                              n_neg=8187, n_reps=100)),
        # kernel-kind matrix on the 1-D mesh: scatter (one-sample,
        # population E h = dim) and degree-3 triplet (double ring)
        ("1d", VarianceConfig(kernel="scatter", backend="mesh",
                              n_workers=8, n_pos=4096, n_neg=4096,
                              n_reps=100)),
        ("1d", VarianceConfig(kernel="triplet_indicator", backend="mesh",
                              n_workers=8, n_pos=96, n_neg=96, dim=3,
                              n_reps=100)),
    ]
    for topo, cfg in rows:
        runner = make_mesh_mc_runner(
            cfg, mesh=mesh2d if topo == "2d" else None
        )
        assert runner is not None, cfg
        ests = np.asarray(runner(jnp.arange(cfg.n_reps)))
        r = {
            "config": cfg.to_json(),
            "mesh": "dcn2 x ici4" if topo == "2d" else "w8",
            "mean": float(ests.mean()),
            "variance": float(ests.var(ddof=1)),
            "std_error": float(ests.std(ddof=1) / np.sqrt(cfg.n_reps)),
            "vmapped": True,
            "n_reps": cfg.n_reps,
        }
        write_jsonl([r], out)
        print(json.dumps({
            "mesh": r["mesh"], "kernel": cfg.kernel,
            "scheme": cfg.scheme, "n": [cfg.n_pos, cfg.n_neg],
            "mean": round(r["mean"], 6), "variance": r["variance"],
        }), flush=True)
    print(f"# wrote {out} in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
