"""Perf-regression sentinel over the serving trajectory [ISSUE 7
tentpole].

``results/serving.jsonl`` is append-only round-over-round bookkeeping:
every PR's ``bench.py --streaming`` lands a ``bench_streaming`` row,
and until now NOTHING read them back — a 30% throughput regression
would merge silently as one more row. This gate compares the NEWEST
row against the history of comparable rows with noise bands:

    center = median(history)
    band   = max(tolerance_frac * center, mad_k * 1.4826 * MAD)

(the MAD term widens the band when the history itself is noisy — CPU
CI runners are — while ``tolerance_frac`` keeps a floor so two
identical historic rows don't produce a zero-width band). A breach is

    events_per_s          below  center - band      (throughput), or
    insert_latency_p99_ms above  center + band      (tail latency).

Rows are joined on the ``config_digest`` stamped by ``bench.py``
[ISSUE 7 satellite]; legacy rows without a digest join on the config
fields that determine comparability (n_events / bg_compact /
max_inflight / budget / max_batch), so pre-digest history still
counts.

Modes (the warn-then-fail CI rollout):

* ``--mode warn`` — report breaches, always exit 0
* ``--mode fail`` — exit 1 on breach (the ci.sh leg since ISSUE 8)

The ISSUE 7→8 warn soak recalibrated the default band: rows in the
committed history come from DIFFERENT container instances, and
back-to-back runs of the identical commit on one box spread ~±20%
in events/s and p99 (measured during the ISSUE 8 flip: 13.9–17.3k
ev/s, 4.5–7.1ms p99 for the same code). A 15% floor flagged that
cross-machine noise as regression, so the default ``tolerance_frac``
is now 0.25 — wide enough for host variance, still far below the
"30% silent regression" failure mode the gate exists to catch;
``--tolerance-frac 0.15`` restores the tight band for same-host
comparisons.

Since ISSUE 9 the gate bands MULTIPLE stages per run: the default
``--stage`` list covers ``bench_streaming``, ``multi_tenant`` (T=256
cell throughput + insert p99), and ``fleet_incremental`` (throughput,
insert p99, and host→device bytes per pack re-place — the dirty-row
regression the incremental fleet path must never quietly lose). Each
stage gates against its own comparable history with its own metric
spec; the combined verdict fails when ANY stage breaches.

Always writes the verdict row (stage ``perf_gate``, per-stage
verdicts under ``stages``) to ``--out`` for the CI artifact, and
prints it as one stdout JSON line.

Usage: python scripts/perf_gate.py [--history results/serving.jsonl]
                                   [--mode warn|fail]
                                   [--stage bench_streaming,...]
                                   [--out results/perf_gate.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from statistics import median

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# metric -> direction ("min" = lower is better)
_GATED = (("events_per_s", "max", "value"),
          ("insert_latency_p99_ms", "min", "insert_latency_p99_ms"))

# per-stage metric specs [ISSUE 9 satellite]: the gate now bands the
# multi_tenant and fleet_incremental trajectories too (before, only
# bench_streaming rows were read back — a fleet regression would merge
# as one more row). Value fields are dotted paths into the row.
_STAGE_METRICS = {
    "bench_streaming": _GATED,
    "multi_tenant": (
        ("events_per_s_T256", "max", "cells.256.events_per_s"),
        ("insert_p99_ms_T256", "min", "cells.256.insert_p99_ms"),
    ),
    "fleet_incremental": (
        ("events_per_s", "max", "events_per_s"),
        ("insert_latency_p99_ms", "min", "insert_latency_p99_ms"),
        ("bytes_per_replace", "min", "bytes_per_replace"),
    ),
    # Pallas-fused counts [ISSUE 10]: kernel-mode throughput/p99 band
    # against their own history (interpret-mode numbers on CPU CI —
    # the emulator regressing IS a regression worth hearing about),
    # and the one-dispatch-per-micro-batch witness must stay exactly
    # 1.0 (any drift means the fusion quietly split)
    "serving_kernel": (
        ("events_per_s", "max", "events_per_s"),
        ("insert_latency_p99_ms", "min", "insert_latency_p99_ms"),
        ("kernel_calls_per_batch", "min", "kernel_calls_per_batch"),
    ),
    # host-tax budget [ISSUE 14]: the ledger row bench.py --streaming
    # stamps per run. Host fraction creeping UP, steady-state compile
    # events per 1k batches UP, or the GC pause tail UP are quiet
    # request-path regressions the throughput band can miss entirely
    # (a 5% host-fraction climb hides inside the 25% events/s band).
    "host_tax": (
        ("host_fraction", "min", "host_fraction"),
        ("compile_events_per_1k", "min",
         "compile_events_per_1k_batches"),
        ("gc_pause_p99_ms", "min", "gc_pause_p99_ms"),
    ),
}
_DEFAULT_STAGES = ("bench_streaming,multi_tenant,fleet_incremental,"
                   "serving_kernel,host_tax")

# the config fields that make two bench_streaming rows comparable when
# no config_digest is stamped (pre-ISSUE-7 history)
_LEGACY_KEY = ("n_events", "bg_compact", "max_inflight", "max_batch")


def load_rows(path: str, stage: str):
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("stage") == stage:
                rows.append(row)
    return rows


def _legacy_key(row: dict):
    return tuple(row.get(k) for k in _LEGACY_KEY)


def comparable_history(rows, newest):
    """History rows comparable to the newest one: same config_digest
    when both sides carry one, else same legacy config fields."""
    digest = newest.get("config_digest")
    out = []
    for r in rows[:-1]:
        if digest and r.get("config_digest"):
            if r["config_digest"] == digest:
                out.append(r)
        elif _legacy_key(r) == _legacy_key(newest):
            out.append(r)
    return out


def _get_path(row: dict, path: str):
    """Resolve a dotted path ("cells.256.events_per_s") into a row."""
    cur = row
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _value(row: dict, metric: str, value_field: str):
    # bench_streaming's events_per_s lives under "value" (metric field
    # says events/sec); everything else resolves by (dotted) path
    if value_field == "value":
        v = row.get("value")
        if v is None:
            v = row.get("events_per_s")
        return v
    return _get_path(row, value_field)


def _mad(xs, center):
    return median([abs(x - center) for x in xs])


def gate(rows, tolerance_frac: float, mad_k: float,
         min_history: int, metrics=_GATED) -> dict:
    newest = rows[-1]
    hist = comparable_history(rows, newest)
    verdict = {
        "stage": "perf_gate",
        "run_id": newest.get("run_id"),
        "config_digest": newest.get("config_digest"),
        "n_history": len(hist),
        "min_history": min_history,
        "tolerance_frac": tolerance_frac,
        "mad_k": mad_k,
        "checks": [],
        "ok": True,
    }
    if len(hist) < min_history:
        verdict["note"] = (
            f"insufficient comparable history ({len(hist)} < "
            f"{min_history}) — gate passes vacuously")
        return verdict
    for metric, direction, field in metrics:
        new = _value(newest, metric, field)
        xs = [v for v in (_value(r, metric, field) for r in hist)
              if v is not None]
        if new is None or len(xs) < min_history:
            verdict["checks"].append({
                "metric": metric, "ok": True,
                "note": "metric missing from newest row or history"})
            continue
        center = median(xs)
        band = max(tolerance_frac * abs(center),
                   mad_k * 1.4826 * _mad(xs, center))
        if direction == "max":
            breach = (center - new) > band
            limit = center - band
        else:
            breach = (new - center) > band
            limit = center + band
        verdict["checks"].append({
            "metric": metric, "direction": direction, "new": new,
            "median": center, "band": band, "limit": limit,
            "n": len(xs), "ok": not breach,
        })
        if breach:
            verdict["ok"] = False
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--history", type=str,
                    default=os.path.join(REPO, "results",
                                         "serving.jsonl"))
    ap.add_argument("--stage", "--stages", dest="stages", type=str,
                    default=_DEFAULT_STAGES,
                    help="comma-separated stages to gate (each with "
                         "its own metric spec; unknown stages use the "
                         "bench_streaming spec)")
    ap.add_argument("--mode", choices=["warn", "fail"], default="warn")
    ap.add_argument("--min-history", type=int, default=2)
    ap.add_argument("--tolerance-frac", type=float, default=0.25,
                    help="relative band floor (0.25 = 25%% of the "
                         "history median — calibrated to measured "
                         "cross-container run noise; use 0.15 for "
                         "same-host comparisons)")
    ap.add_argument("--mad-k", type=float, default=4.0,
                    help="band widens to k robust-sigmas (1.4826*MAD) "
                         "when the history itself is noisy")
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "results",
                                         "perf_gate.jsonl"))
    args = ap.parse_args(argv)

    if not os.path.exists(args.history):
        print(f"PERF GATE: no history file {args.history!r} — "
              "nothing to gate", file=sys.stderr)
        return 0
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    stage_verdicts = {}
    for stage in stages:
        rows = load_rows(args.history, stage)
        if not rows:
            print(f"PERF GATE: no {stage!r} rows in {args.history!r}",
                  file=sys.stderr)
            continue
        v = gate(rows, args.tolerance_frac, args.mad_k,
                 args.min_history,
                 metrics=_STAGE_METRICS.get(stage, _GATED))
        v["gated_stage"] = stage
        stage_verdicts[stage] = v
    if not stage_verdicts:
        print(f"PERF GATE: no gateable rows in {args.history!r}",
              file=sys.stderr)
        return 0

    verdict = {
        "stage": "perf_gate",
        "mode": args.mode,
        "ok": all(v["ok"] for v in stage_verdicts.values()),
        "stages": stage_verdicts,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(json.dumps(verdict) + "\n")
    print(json.dumps(verdict))
    if not verdict["ok"]:
        bad = [f"{s}:{c['metric']}"
               for s, v in stage_verdicts.items()
               for c in v["checks"] if not c["ok"]]
        msg = (f"PERF GATE {'FAIL' if args.mode == 'fail' else 'WARN'}:"
               f" regression in {bad} (bands in {args.out})")
        print(msg, file=sys.stderr)
        if args.mode == "fail":
            return 1
    else:
        n_checks = sum(len(v["checks"])
                       for v in stage_verdicts.values())
        print(f"PERF GATE OK: {n_checks} checks across "
              f"{len(stage_verdicts)} stages", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
