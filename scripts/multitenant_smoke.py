#!/usr/bin/env python
"""Multi-tenant fleet smoke [ISSUE 8]: T=32 tenants over 2 mesh
shards, driven end-to-end through the ``MultiTenantEngine``.

Asserts the properties the fleet exists for:

1. **Independence parity** — every tenant's wins2/AUC from the fleet
   index is BIT-IDENTICAL to a dedicated single-tenant
   ``ExactAucIndex`` fed the same events (T=32, S=2, coalesced
   multi-tenant batches).
2. **One jitted count per coalesced batch** — ``fleet_count_calls``
   equals the number of micro-batches, not events or tenants
   (the tenant-axis packing witness).
3. **Per-tenant SLO verdict** — a label-wildcard objective
   (``insert_latency_s{tenant=*}``) evaluated live yields a healthy
   verdict with one series per tenant, and the per-tenant breakdown
   survives into the record.
4. **Admission control** — a quota-busting flood is shed typed
   (``TenantRejectedError``) without touching other tenants' results.
5. **Whale promotion + dirty-row placement** [ISSUE 9] — one tenant at
   ~20x the median crosses ``whale_threshold`` and promotes to its own
   delta-tiered index (``fleet_whale_promotions`` fired), per-tenant
   parity holds through the promotion, and every geometry-stable pack
   re-place ships strictly less than the full ``[S, T_bucket, cap]``
   block (``bytes_h2d_saved`` > 0, partial re-places > 0).

Writes ``results/multitenant_smoke.jsonl`` for the CI artifact.
Run via scripts/ci.sh (needs the 8-virtual-device XLA flags).
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tuplewise_tpu.serving import (  # noqa: E402
    ExactAucIndex, ServingConfig, TenancyConfig, TenantFleetIndex,
    TenantRejectedError, make_tenant_stream, replay_fleet,
)

T = 32
SHARDS = 2
N_EVENTS = 4000
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "results", "multitenant_smoke.jsonl")


def fleet_vs_independent(count_kernel=False):
    """Direct index parity: the fleet vs T dedicated engines. With
    ``count_kernel`` the fleet counts run through the Pallas tenant-
    axis kernel (interpret mode on CPU) [ISSUE 10]."""
    scores, labels, tenants = make_tenant_stream(
        N_EVENTS, T, skew=1.0, seed=7)
    fleet = TenantFleetIndex(window=256, compact_every=64,
                             shards=SHARDS, count_kernel=count_kernel)
    singles = {}
    # coalesced multi-tenant batches: chunk the stream, group by tenant
    chunk = 97
    for i in range(0, N_EVENTS, chunk):
        sl = slice(i, min(i + chunk, N_EVENTS))
        items = []
        for tid in np.unique(tenants[sl]):
            m = tenants[sl] == tid
            items.append((str(tid), scores[sl][m], labels[sl][m]))
            if tid not in singles:
                singles[tid] = ExactAucIndex(window=256,
                                             compact_every=64,
                                             engine="jax")
        fleet.apply_inserts(items)
        for tid, s, l in items:
            singles[tid].insert_batch(s, l)
    mismatches = []
    for tid, idx in singles.items():
        if fleet.wins2(str(tid)) != idx._wins2 \
                or fleet.auc(str(tid)) != idx.auc():
            mismatches.append(str(tid))
    assert not mismatches, f"fleet/independent mismatch: {mismatches}"
    out = {"tenants": len(singles),
           "count_calls": fleet.state()["count_calls"],
           "parity": "bit-identical"}
    if count_kernel:
        snap = fleet.metrics.snapshot()
        calls = snap["count_kernel_calls_total"]["value"]
        fallbacks = snap["count_kernel_fallbacks_total"]["value"]
        assert calls >= 1, "count kernel never dispatched"
        assert fallbacks == 0, f"count kernel fell back {fallbacks}x"
        out["kernel_calls"] = int(calls)
        out["kernel_fallbacks"] = int(fallbacks)
    return out


def engine_leg():
    """Engine-level run with live per-tenant SLO + one-call witness."""
    scores, labels, tenants = make_tenant_stream(
        N_EVENTS, T, skew=1.0, seed=11)
    slo = {"objectives": [
        {"name": "tenant_insert_p99", "type": "latency",
         "metric": "insert_latency_s{tenant=*}",
         "quantile": "p99", "threshold_ms": 10_000},
        {"name": "no_tenant_rejects", "type": "counter_max",
         "metric": "tenant_rejected_total", "max": 0},
    ]}
    rec = replay_fleet(
        scores, labels, tenants,
        config=ServingConfig(window=512, compact_every=128,
                             max_batch=256, policy="block",
                             flush_timeout_s=0.001,
                             mesh_shards=SHARDS),
        tenancy=TenancyConfig(max_tenants=64, tenant_quota=4096),
        chunk=2, max_inflight=128, slo_spec=slo)
    assert rec["events_applied"] == N_EVENTS, rec["events_applied"]
    err = rec["tenant_auc_max_abs_err"]
    assert err < 1e-6, f"per-tenant oracle parity broke: {err}"
    calls, batches = rec["fleet_count_calls"], rec["batches"]
    assert 0 < calls <= batches, (calls, batches)
    assert rec["slo"]["healthy"], rec["slo"]
    series = rec["slo"]["objectives"]["tenant_insert_p99"]["last"][
        "series"]
    assert len(series) == T, (len(series), T)
    return {
        "events_per_s": round(rec["events_per_s"], 1),
        "insert_p99_ms": rec["insert_latency_p99_ms"],
        "tenant_insert_p99_max_ms": rec["tenant_insert_p99_max_ms"],
        "fleet_count_calls": calls,
        "batches": batches,
        "tenant_auc_max_abs_err": err,
        "slo_healthy": rec["slo"]["healthy"],
        "slo_series": len(series),
        "tenancy_report": rec["report"].get("tenancy"),
    }


def admission_leg():
    """Quota shedding is typed and tenant-attributed."""
    from tuplewise_tpu.serving import MultiTenantEngine

    rejected = None
    with MultiTenantEngine(
            ServingConfig(max_batch=16, flush_timeout_s=0.5),
            TenancyConfig(max_tenants=4, tenant_quota=2)) as eng:
        futs = []
        try:
            for i in range(64):
                futs.append(eng.insert("flood", float(i), i % 2))
        except TenantRejectedError as e:
            rejected = e.tenant
        ok = eng.insert("calm", 1.0, 1)
        assert ok.result(10.0) == 1
        for f in futs:
            f.result(10.0)
    assert rejected == "flood", rejected
    return {"rejected_tenant": rejected}


def whale_leg():
    """[ISSUE 9] One tenant at ~20x the median: promotion fires, the
    fleet stays bit-identical to independents through it, and the
    dirty-row path strictly beats the full-pack ship per re-place."""
    rng = np.random.default_rng(21)
    T_SMALL, PER_ROUND, ROUNDS = 15, 4, 20
    whale_per_round = PER_ROUND * 20          # 20x the median tenant
    fleet = TenantFleetIndex(compact_every=64, shards=SHARDS,
                             whale_threshold=400)
    singles = {}

    def batch(tid, k):
        labels = rng.random(k) < 0.5
        scores = rng.standard_normal(k) + 0.8 * labels
        if tid not in singles:
            singles[tid] = ExactAucIndex(compact_every=64,
                                         engine="jax")
        singles[tid].insert_batch(scores, labels)
        return (tid, scores, labels)

    for _ in range(ROUNDS):
        items = [batch("whale", whale_per_round)]
        items += [batch(f"s{k}", PER_ROUND) for k in range(T_SMALL)]
        fleet.apply_inserts(items)
    m = fleet.metrics.snapshot()
    promotions = m["fleet_whale_promotions"]["value"]
    assert promotions >= 1, "whale never promoted"
    assert fleet.is_whale("whale")
    mismatches = [t for t in singles
                  if fleet.wins2(t) != singles[t]._wins2
                  or fleet.auc(t) != singles[t].auc()]
    assert not mismatches, f"parity broke through promotion: " \
                           f"{mismatches}"
    # strict per-re-place byte saving: geometry-stable re-places ship
    # only dirty rows, so partial re-places dominate and every one of
    # them credits saved bytes
    replaces = m["pack_replaces_total"]["value"]
    full = m["pack_full_replaces_total"]["value"]
    saved = m["bytes_h2d_saved"]["value"]
    assert replaces - full > 0, (replaces, full)
    assert saved > 0, "dirty-row placement saved nothing"
    shipped = m["bytes_h2d"]["value"]
    assert shipped < shipped + saved, "vacuous"
    frac = shipped / (shipped + saved)
    assert frac < 0.5, f"re-places shipped {frac:.0%} of full cost"
    fleet.close()
    return {"promotions": int(promotions),
            "tenants": len(singles),
            "pack_replaces": int(replaces),
            "pack_partial_replaces": int(replaces - full),
            "bytes_h2d": int(shipped), "bytes_h2d_saved": int(saved),
            "shipped_fraction_of_full": round(frac, 4),
            "parity": "bit-identical"}


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--count-kernel", action="store_true",
                    help="add the Pallas-fused counts leg [ISSUE 10]: "
                         "re-run the fleet-vs-independents parity with "
                         "count_kernel=True (interpret mode on CPU) "
                         "and assert bit-identical wins2/AUC + zero "
                         "kernel fallbacks")
    args = ap.parse_args(argv)

    rec = {"stage": "multitenant_smoke", "tenants": T,
           "mesh_shards": SHARDS, "n_events": N_EVENTS}
    rec["independent_parity"] = fleet_vs_independent()
    print(f"[multitenant_smoke] index parity OK "
          f"({rec['independent_parity']})", file=sys.stderr)
    if args.count_kernel:
        rec["count_kernel"] = fleet_vs_independent(count_kernel=True)
        print(f"[multitenant_smoke] count-kernel leg OK "
              f"({rec['count_kernel']})", file=sys.stderr)
    rec["engine"] = engine_leg()
    print(f"[multitenant_smoke] engine leg OK ({rec['engine']})",
          file=sys.stderr)
    rec["admission"] = admission_leg()
    print(f"[multitenant_smoke] admission OK ({rec['admission']})",
          file=sys.stderr)
    rec["whale"] = whale_leg()
    print(f"[multitenant_smoke] whale leg OK ({rec['whale']})",
          file=sys.stderr)
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
