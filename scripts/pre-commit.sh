#!/usr/bin/env bash
# Fast pre-commit loop for the static invariant checkers [ISSUE 15]:
# `tuplewise check --diff HEAD` restricts findings to the files you
# changed plus everything that (transitively) imports them — the
# reverse-dependency closure from the module graph — so the loop runs
# in a couple of seconds instead of re-judging the whole repo.
#
# Install as a git hook:
#   ln -sf ../../scripts/pre-commit.sh .git/hooks/pre-commit
#
# The full unscoped run (waiver staleness, certificate diffs, SARIF)
# still happens in CI: scripts/analysis_gate.py is the first ci.sh
# leg. This hook is the tight loop, not the gate.
set -o pipefail
cd "$(dirname "$0")/.."
exec python -m tuplewise_tpu.harness.cli check --diff HEAD "$@"
