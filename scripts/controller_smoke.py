#!/usr/bin/env python
"""SLO-driven control plane smoke [ISSUE 11]: a Zipf flash crowd at
T=32 over 2 mesh shards, served twice from the same schedule —

* **controlled** — a ``FleetController`` rides the live SLO monitor
  (real ``MetricsFlusher`` observer wiring, the exact ``serve
  --controller-spec`` path): it must throttle the flooding tenant
  typed (``TenantThrottledError`` + retry hint) BEFORE the breach, so
  the run ends with the SLO verdict **healthy**, ZERO hard rejects
  for in-quota tenants, and per-tenant wins2 bit-identical to
  independent single-tenant indexes over the admitted events;
* **uncontrolled twin** — the same schedule with no controller must
  **breach** (queue saturation and/or hard-reject flood), proving the
  scenario actually needs defending.

Then ``tuplewise doctor`` runs over the controlled run's artifacts
(metrics.jsonl + flight.jsonl) and must attribute **100 % of the
actuations** to the signal that caused them (cause → action → effect
correlation) with a non-degraded verdict.

Writes ``results/controller_smoke.jsonl`` for the CI artifact.
Run via scripts/ci.sh.
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import dataclasses  # noqa: E402

import numpy as np  # noqa: E402

from tuplewise_tpu.obs.metrics_export import MetricsFlusher  # noqa: E402
from tuplewise_tpu.obs.slo import SloMonitor  # noqa: E402
from tuplewise_tpu.serving import (  # noqa: E402
    BackpressureError, ExactAucIndex, FleetController,
    MultiTenantEngine, ServingConfig, TenancyConfig,
    TenantThrottledError,
)

T = 32
SHARDS = 2
QUEUE = 128
BURST = 256
ROUNDS = 4
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "results", "controller_smoke.jsonl")

SLO_SPEC = {"objectives": [
    {"name": "queue_sat", "type": "saturation",
     "metric": "queue_depth_live", "capacity": "queue_size",
     "max_fraction": 0.8},
    {"name": "no_hard_rejects", "type": "counter_max",
     "metric": "rejected_total", "max": 0},
]}

# throttle_s is deliberately long: on a loaded CI box the submitting
# thread can stall for seconds mid-burst, and a short throttle that
# expires inside such a stall would let the flood through between two
# checkpoints. Reversibility is preserved — the calm release clears
# throttles as soon as pressure subsides. The budgets are sized for
# the run: the flusher evaluates every 20 ms on top of the burst
# checkpoints, and with the 20 ms cooldown a sustained-pressure run
# spends ~50 shed steps/s — a budget that exhausted mid-scenario
# would (by design!) let the tail of the flood through, which is
# exactly the "budget bounds the blast radius" semantics, but not
# what this smoke is pinning.
CTL_SPEC = {"knobs": ["shed", "flush"], "cooldown_s": 0.02,
            "up_ticks": 1, "down_ticks": 8, "throttle_s": 5.0,
            "shed_budget": 2048, "flush_budget": 64}


def run(controlled, artifact_dir=None):
    rng = np.random.default_rng(31)
    cfg = ServingConfig(queue_size=QUEUE, policy="reject",
                        flush_timeout_s=0.001, max_batch=32,
                        mesh_shards=SHARDS)
    admitted = {}
    metrics_path = (os.path.join(artifact_dir, "metrics.jsonl")
                    if artifact_dir else None)
    with MultiTenantEngine(cfg, TenancyConfig(
            max_tenants=T + 8, tenant_quota=8192)) as eng:
        mon = SloMonitor(SLO_SPEC, registry=eng.metrics,
                         flight=eng.flight,
                         context=dataclasses.asdict(cfg))
        if controlled:
            FleetController(eng, CTL_SPEC).attach(mon)
        flusher = MetricsFlusher(
            eng.metrics, metrics_path, every_s=0.02,
            meta={"stage": "controller_smoke"}, config=cfg,
            observers=[mon.observe_row]).start()
        shed = rejected = 0
        for r in range(ROUNDS):
            # steady state: every tenant a small resolved batch
            futs = []
            for k in range(1, T):
                s = rng.standard_normal(8)
                l = rng.random(8) < 0.5
                futs.append((f"t{k}", s, l,
                             eng.insert(f"t{k}", s, l)))
                if len(futs) >= 32:
                    for tid, s_, l_, f in futs:
                        f.result(30.0)
                        admitted.setdefault(tid, []).append((s_, l_))
                    futs = []
            for tid, s_, l_, f in futs:
                f.result(30.0)
                admitted.setdefault(tid, []).append((s_, l_))
            # the wedge: one big polite insert occupies the batcher
            ws = rng.standard_normal(100_000)
            wl = rng.random(100_000) < 0.5
            wedge = eng.insert(f"t{T - 1}", ws, wl)
            admitted.setdefault(f"t{T - 1}", []).append((ws, wl))
            # the flash crowd: t0 floods while the batcher is busy.
            # The flusher keeps writing rows (the doctor artifacts),
            # and the monitor is ALSO pumped at burst checkpoints so
            # the control decision does not hinge on a 20 ms timer
            # landing inside the warn window — the same deterministic
            # pumping the tier-1 scenario suite uses.
            for i in range(BURST):
                s = rng.standard_normal(1)
                l = rng.random(1) < 0.5
                try:
                    eng.insert("t0", s, l)
                    admitted.setdefault("t0", []).append((s, l))
                except TenantThrottledError:
                    shed += 1
                except BackpressureError:
                    rejected += 1
                if (i + 1) % 20 == 0:
                    mon.observe(eng.metrics.snapshot(),
                                time.perf_counter())
                    time.sleep(0.005)
            wedge.result(120.0)
            eng.flush(timeout=120.0)
            time.sleep(0.1)
        flusher.stop()
        slo = mon.report()
        m = eng.metrics.snapshot()
        wins = {t: eng.fleet.wins2(t) for t in eng.fleet.tenants()}
        flight = eng.flight
        acts = flight.events("actuation")
        if artifact_dir:
            flight.dump_to(os.path.join(artifact_dir, "flight.jsonl"))
    oracle = {}
    for tid, batches in admitted.items():
        idx = ExactAucIndex(engine="jax")
        idx.insert_batch(np.concatenate([s for s, _ in batches]),
                         np.concatenate([l for _, l in batches]))
        oracle[tid] = idx._wins2
    return {
        "slo_healthy": slo["healthy"],
        "slo": slo,
        "shed": shed,
        "rejected": rejected,
        "rejected_total": m["rejected_total"]["value"],
        "tenant_rejected_total": m["tenant_rejected_total"]["value"],
        "tenant_throttled_total": m["tenant_throttled_total"]["value"],
        "actuations": len(acts),
        "actuation_signals_nonnull": sum(1 for a in acts
                                         if a.get("signal")),
        "parity": wins == oracle,
        "wins_mismatch": sorted(t for t in wins
                                if wins[t] != oracle.get(t))[:5],
    }


def main() -> int:
    rec = {"stage": "controller_smoke", "tenants": T,
           "mesh_shards": SHARDS, "queue_size": QUEUE, "burst": BURST}

    with tempfile.TemporaryDirectory() as art:
        c = run(controlled=True, artifact_dir=art)
        rec["controlled"] = {k: v for k, v in c.items() if k != "slo"}
        print(f"[controller_smoke] controlled: healthy="
              f"{c['slo_healthy']} throttled="
              f"{c['tenant_throttled_total']} rejects="
              f"{c['rejected_total']} actuations={c['actuations']}",
              file=sys.stderr)
        assert c["slo_healthy"], \
            f"controlled fleet breached its SLO: {c['slo']}"
        assert c["rejected_total"] == 0, \
            "controlled fleet hard-rejected in-quota traffic"
        assert c["tenant_rejected_total"] == 0
        assert c["tenant_throttled_total"] > 0, \
            "controller never shed — the scenario did not exercise it"
        assert c["actuations"] > 0
        assert c["actuation_signals_nonnull"] == c["actuations"], \
            "actuation without a triggering signal"
        assert c["parity"], \
            f"wins2 diverged from independents: {c['wins_mismatch']}"

        # doctor attribution over the controlled run's artifacts
        from tuplewise_tpu.obs.doctor import diagnose

        report = diagnose(run_dir=art, slo_spec=SLO_SPEC,
                          context={"queue_size": QUEUE})
        acts = report.get("actuations") or {}
        rec["doctor"] = {"verdict": report["verdict"],
                         "actuations": acts.get("total", 0),
                         "attributed": acts.get("attributed", 0)}
        print(f"[controller_smoke] doctor: {rec['doctor']}",
              file=sys.stderr)
        assert acts.get("total", 0) == c["actuations"], \
            (acts, c["actuations"])
        assert acts["attributed"] == acts["total"], \
            f"doctor could not attribute every actuation: {acts}"
        assert not report["verdict"].startswith("degraded"), \
            report["verdict"]

    u = run(controlled=False)
    rec["uncontrolled"] = {k: v for k, v in u.items() if k != "slo"}
    print(f"[controller_smoke] uncontrolled twin: healthy="
          f"{u['slo_healthy']} rejects={u['rejected_total']}",
          file=sys.stderr)
    assert not u["slo_healthy"], \
        "uncontrolled twin did not breach — the scenario is vacuous"
    assert u["parity"], "parity must hold even while breaching"

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
