"""Statistical verification of every committed result row.

Reads all results/*.jsonl variance-harness rows and checks, per row:

  * mean vs the population AUC (z = (mean - pop) / SE(mean)), and
  * variance vs its Hoeffding closed form
    (z = (var - pred) / SE(var), SE(var) ~ var * sqrt(2/(M-1)) for
    near-Gaussian estimator distributions),

with plug-in zetas from a 20k-per-class sample (`estimators/variance`).
Writes results/stat_check.txt and exits nonzero if any |z| > 4 — a
one-file audit that the committed experiments obey the theory, and a
regression gate future rounds can run after touching any estimator.

Usage: python scripts/stat_check.py
"""

from __future__ import annotations

import glob
import json
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tuplewise_tpu.data import make_gaussians, true_gaussian_auc  # noqa: E402
from tuplewise_tpu.estimators.variance import (  # noqa: E402
    conditional_incomplete_variance,
    incomplete_variance_from_zetas,
    local_variance_from_zetas,
    repartitioned_variance_from_zetas,
    two_sample_variance_from_zetas,
    two_sample_zetas,
)

Z_LIMIT = 4.0
_ZETAS = {}
_FIXED = {}


def host_platform() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "host"


def fixed_row_targets(cfg: dict, row: dict):
    """(population mean or None, conditional variance, regenerated?)
    for a fix_data=True incomplete row.

    When the row's recorded generation ``platform`` matches this host,
    the frozen dataset is reconstructed bit-identically
    (harness.variance.fixed_dataset), the complete U computed exactly
    (O(n log n) midranks for AUC; the full triplet reduction for
    degree 3 [VERDICT r4 next #3]), and the conditional design form
    follows from s^2 = U(1-U) — NO plug-in anywhere, the strongest
    audit in this file. Grid size G is n1*n2 for pairs and n1(n1-1)n2
    for triplets.

    jax.random's f32 normal synthesis is PLATFORM-dependent, so a row
    committed on another platform (or one predating the stamp) cannot
    be regenerated faithfully: those rows are audited AS-IS — the
    design closed form still follows from the row's own complete-U
    (u = the row mean; the O(SE) error in u moves the prediction far
    below the variance z-score's own noise floor), while the mean has
    no independent target and its z is skipped.

    Returns None when the row isn't auditable either way."""
    if cfg.get("scheme") != "incomplete" or cfg.get("backend") != "jax":
        return None
    is_pair = cfg.get("kernel") == "auc" and cfg.get("dim") == 1
    is_triplet = cfg.get("kernel") == "triplet_indicator"
    if not (is_pair or is_triplet):
        return None
    n1, n2 = cfg["n_pos"], cfg["n_neg"]
    grid = n1 * (n1 - 1) * n2 if is_triplet else n1 * n2
    regen = row.get("platform") == host_platform()
    if regen:
        key = (cfg["kernel"], cfg["seed"], n1, n2, cfg.get("dim"),
               cfg["separation"])
        if key not in _FIXED:
            from tuplewise_tpu.harness.variance import (
                VarianceConfig, fixed_dataset,
            )

            A, B = fixed_dataset(VarianceConfig(**cfg))
            if is_pair:
                from tuplewise_tpu.models.metrics import auc_score

                _FIXED[key] = auc_score(A, B)
            else:
                from tuplewise_tpu.estimators.estimator import Estimator

                _FIXED[key] = Estimator(
                    cfg["kernel"], backend="numpy"
                ).complete(A, B)
        u = _FIXED[key]
    else:
        u = row["mean"]
    pred = conditional_incomplete_variance(
        u * (1.0 - u), grid,
        n_pairs=cfg["n_pairs"], design=cfg.get("design", "swr"),
    )
    return (u if regen else None), pred, regen


def zetas(kernel: str, separation: float):
    key = (kernel, separation)
    if key not in _ZETAS:
        X, Y = make_gaussians(20_000, 20_000, 1, separation, seed=7)
        _ZETAS[key] = two_sample_zetas(kernel, X[:, 0], Y[:, 0])
    return _ZETAS[key]


def predicted_variance(cfg: dict) -> float | None:
    """Closed-form Var for a harness row, or None if no formula applies
    (feature kernels, non-Gaussian data paths)."""
    if cfg["kernel"] != "auc" or cfg["dim"] != 1:
        return None
    z = zetas(cfg["kernel"], cfg["separation"])
    n1, n2, N = cfg["n_pos"], cfg["n_neg"], cfg["n_workers"]
    if cfg["scheme"] == "complete":
        return two_sample_variance_from_zetas(z, n1, n2)
    if cfg["scheme"] == "local":
        return local_variance_from_zetas(z, n1, n2, n_workers=N)
    if cfg["scheme"] == "repartitioned":
        return repartitioned_variance_from_zetas(
            z, n1, n2, n_workers=N, n_rounds=cfg["n_rounds"]
        )
    if cfg["scheme"] == "incomplete":
        return incomplete_variance_from_zetas(
            z, n1, n2, n_pairs=cfg["n_pairs"],
            design=cfg.get("design", "swr"),
        )
    return None


def main(out: str | None = None) -> int:
    rows, worst = [], 0.0
    from tuplewise_tpu.utils.results_io import is_quick

    # *_quick.jsonl smoke-run siblings never enter the committed audit
    paths = sorted(
        p for p in glob.glob(os.path.join(REPO, "results", "*.jsonl"))
        if not is_quick(os.path.basename(p))
    )
    for path in paths:
        name = os.path.basename(path)
        with open(path) as fh:
            lines = fh.readlines()
        for line in lines:
            r = json.loads(line)
            cfg, M = r.get("config"), r.get("n_reps")
            # only harness rows qualify: a dict config with the
            # variance-experiment schema (summary files like
            # configs.jsonl carry scalar 'config' ids)
            if (not isinstance(cfg, dict) or not M or M < 8
                    or "scheme" not in cfg or "separation" not in cfg):
                continue
            aud_pair = cfg.get("kernel") == "auc" and cfg.get("dim") == 1
            # fix_data triplet rows audit against their own EXACT
            # conditional forms (fixed_row_targets) [VERDICT r4 next #3]
            aud_tri = (cfg.get("kernel") == "triplet_indicator"
                       and cfg.get("fix_data"))
            if not (aud_pair or aud_tri):
                # only the 1-D AUC family has the Φ(sep/√2) population
                # mean and zeta closed forms; scatter/triplet mesh rows
                # are validated by their own tests, not this audit
                continue
            as_is = False
            if cfg.get("fix_data"):
                targets = fixed_row_targets(cfg, r)
                if targets is None:
                    continue  # conditional rows outside the exact audit
                pop, pred, regen = targets
                as_is = not regen
            else:
                pop = true_gaussian_auc(cfg["separation"])
                try:
                    pred = predicted_variance(cfg)
                except (ValueError, ZeroDivisionError):
                    # legal harness rows the closed forms reject (e.g.
                    # per-worker class size < 2 for the zeta formulas):
                    # audit the mean, skip the variance z-score
                    # (ADVICE r2)
                    pred = None
            # as-is rows (cross-platform artifacts) have no independent
            # mean target: only the variance-vs-design-form z applies
            z_mean = (
                (r["mean"] - pop) / math.sqrt(r["variance"] / M)
                if pop is not None else float("nan")
            )
            # `is not None`, never truthiness: a pred of exactly 0.0 is
            # a real closed form (zero-variance limit), only the
            # z-score is undefined for it
            has_pred = pred is not None
            z_var = (
                (r["variance"] - pred)
                / (pred * math.sqrt(2.0 / (M - 1)))
                if has_pred and pred > 0.0 else float("nan")
            )
            worst = max(worst,
                        abs(z_mean) if math.isfinite(z_mean) else 0.0,
                        abs(z_var) if math.isfinite(z_var) else 0.0)
            rows.append(
                f"{name:<28} {cfg['scheme']:>13} N={cfg['n_workers']:<7}"
                f"T={cfg['n_rounds']:<3} B={cfg['n_pairs']:<9}"
                f"d={cfg.get('design', 'swr'):<9}"
                + ("[as-is]" if as_is
                   else "[cond] " if cfg.get("fix_data") else "       ")
                + f"n={cfg['n_pos']:<8} M={M:<4}"
                + (f" mean={r['mean']:.6f} z_mean={z_mean:+5.2f}"
                   if math.isfinite(z_mean)
                   else f" mean={r['mean']:.6f} (no mean target)")
                + (f" var={r['variance']:.3e} pred={pred:.3e}"
                   f" z_var={z_var:+5.2f}" if has_pred
                   else " (no closed form)")
            )
    ok = worst <= Z_LIMIT
    header = (
        f"Statistical audit of committed results ({len(rows)} rows): "
        f"worst |z| = {worst:.2f} (limit {Z_LIMIT}) -> "
        f"{'PASS' if ok else 'FAIL'}\n"
        "z_mean: estimator mean vs population AUC; z_var: Monte-Carlo "
        "variance vs Hoeffding closed form (plug-in zetas, 20k sample).\n"
    )
    report = header + "\n".join(rows) + "\n"
    out = out or os.path.join(REPO, "results", "stat_check.txt")
    with open(out, "w") as f:
        f.write(report)
    print(report)
    print(f"# wrote {out}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
