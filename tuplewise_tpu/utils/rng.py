"""PRNG key discipline [SURVEY §7 "Hard parts": PRNG discipline].

Every source of randomness in the JAX paths derives from a root key via
named `fold_in` chains — per-shard, per-Monte-Carlo-rep, per-repartition-
round — so shards never reuse keys and every run is reproducible from one
integer seed. (NumPy and JAX RNGs cannot match bit-for-bit; parity tests
are exact for complete-U paths and statistical for sampled paths.)

``audit_keys()`` is the assertion-level key-discipline check of
[SURVEY §5.3]: inside the scope, every host-side ``fold`` chain
(purpose + concrete indices) is recorded and a repeated chain — the
key-reuse bug class the discipline exists to prevent — raises
immediately. Folds with traced (in-jit) indices can't be observed
per-value and are skipped; the audit covers the host orchestration
layer, where the distinct-per-shard/rep/round structure is decided.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading

import jax


_PURPOSES = {}
_AUDIT = threading.local()


def _purpose_id(purpose: str) -> int:
    """Stable small int for a purpose string (cached)."""
    if purpose not in _PURPOSES:
        h = hashlib.sha256(purpose.encode()).digest()
        _PURPOSES[purpose] = int.from_bytes(h[:4], "big")
    return _PURPOSES[purpose]


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def fold(key: jax.Array, purpose: str, *indices: int) -> jax.Array:
    """Derive a sub-key: fold in a purpose tag then each index in turn.

    Usage: ``fold(key, "repartition", t)``, ``fold(key, "mc_rep", m)``.
    Indices may be tracers (e.g. a lax.scan counter).
    """
    _record_fold(key, purpose, indices)
    key = jax.random.fold_in(key, _purpose_id(purpose))
    for ix in indices:
        key = jax.random.fold_in(key, ix)
    return key


# --------------------------------------------------------------------- #
# key-discipline audit [SURVEY §5.3]                                    #
# --------------------------------------------------------------------- #

def _concrete(x) -> bool:
    """True when x is an observable host value (not a jit tracer)."""
    import jax.core

    return not isinstance(x, jax.core.Tracer)


def _record_fold(key, purpose, indices) -> None:
    seen = getattr(_AUDIT, "seen", None)
    if seen is None:
        return
    if not (_concrete(key) and all(_concrete(i) for i in indices)):
        return  # in-jit folds: per-value observation impossible
    import numpy as np

    chain = (
        np.asarray(jax.random.key_data(key)).tobytes(),
        purpose,
        tuple(int(i) for i in indices),
    )
    if chain in seen:
        raise AssertionError(
            f"PRNG key-discipline violation: fold chain "
            f"purpose={purpose!r} indices={chain[2]} derived twice from "
            "the same parent key — two consumers would draw identical "
            "randomness. Give each consumer a distinct purpose or index."
        )
    seen.add(chain)


# --------------------------------------------------------------------- #
# mutable host-RNG state capture [ISSUE 4]                               #
# --------------------------------------------------------------------- #
# The JAX paths need no state capture — every key folds from absolute
# indices, so a resumed run re-derives its randomness. Host-side
# mutable generators (serving reservoirs, backoff jitter) DO carry
# state; these two helpers are the one place that knows how to
# round-trip it exactly (the bit_generator state dict is plain ints/
# strings, so it survives the JSON config block of a checkpoint).

def capture_np_rng(gen) -> dict:
    """JSON-safe snapshot of a ``numpy.random.Generator``'s full state."""
    return gen.bit_generator.state


def restore_np_rng(gen, state: dict) -> None:
    """Restore a state captured by :func:`capture_np_rng` — the
    generator continues the original stream bit-for-bit."""
    gen.bit_generator.state = state


@contextlib.contextmanager
def audit_keys():
    """``with audit_keys(): ...`` — raise on any repeated host-side fold
    chain inside the scope (the assertion-level check of SURVEY §5.3)."""
    prev = getattr(_AUDIT, "seen", None)
    _AUDIT.seen = set() if prev is None else prev
    try:
        yield
    finally:
        _AUDIT.seen = prev
