"""PRNG key discipline [SURVEY §7 "Hard parts": PRNG discipline].

Every source of randomness in the JAX paths derives from a root key via
named `fold_in` chains — per-shard, per-Monte-Carlo-rep, per-repartition-
round — so shards never reuse keys and every run is reproducible from one
integer seed. (NumPy and JAX RNGs cannot match bit-for-bit; parity tests
are exact for complete-U paths and statistical for sampled paths.)
"""

from __future__ import annotations

import hashlib

import jax


_PURPOSES = {}


def _purpose_id(purpose: str) -> int:
    """Stable small int for a purpose string (cached)."""
    if purpose not in _PURPOSES:
        h = hashlib.sha256(purpose.encode()).digest()
        _PURPOSES[purpose] = int.from_bytes(h[:4], "big")
    return _PURPOSES[purpose]


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def fold(key: jax.Array, purpose: str, *indices: int) -> jax.Array:
    """Derive a sub-key: fold in a purpose tag then each index in turn.

    Usage: ``fold(key, "repartition", t)``, ``fold(key, "mc_rep", m)``.
    Indices may be tracers (e.g. a lax.scan counter).
    """
    key = jax.random.fold_in(key, _purpose_id(purpose))
    for ix in indices:
        key = jax.random.fold_in(key, ix)
    return key
