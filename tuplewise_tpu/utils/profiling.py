"""Tracing / profiling / observability helpers [SURVEY §5.2, §5.6].

The reference has none of this (printed numbers + matplotlib); the build
standardizes a few small tools:

* ``timer()``        — wall-clock context manager; the harness reports
                       its numbers alongside every variance result
                       (wall-clock is half the headline metric [B:2]).
* ``trace(logdir)``  — ``jax.profiler`` trace scope (XLA host/device
                       timeline, viewable in TensorBoard/Perfetto);
                       no-op when logdir is None, so callers can thread
                       a CLI flag straight through.
* ``device_memory_stats()`` — per-device HBM usage snapshot where the
                       backend exposes it (TPU does; CPU returns {}).
* ``Counter`` / ``Gauge`` / ``Histogram`` / ``MetricsRegistry`` — the
                       serving layer's service metrics (request counts,
                       queue depth, batch fill, latency percentiles).
                       Plain thread-safe host objects, no exporter
                       dependency; ``snapshot()`` renders everything to
                       one JSON-able dict for the CLI / replay reports
                       and the ``obs.MetricsFlusher`` JSONL stream.
                       Metrics accept optional ``labels`` [ISSUE 6]: a
                       small immutable tag dict rendered into the
                       registry key (``name{k=v}``) and carried in the
                       snapshot, so per-shard / per-tenant series stay
                       distinct without a label-indexed store.
"""

from __future__ import annotations

import bisect
import contextlib
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence


@contextlib.contextmanager
def timer() -> Iterator[dict]:
    """``with timer() as t: ...`` then ``t["seconds"]``."""
    out = {"seconds": None}
    t0 = time.perf_counter()
    try:
        yield out
    finally:
        out["seconds"] = time.perf_counter() - t0


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """jax.profiler trace scope; inert when ``logdir`` is None.

    The trace captures XLA compilation, host callbacks, and device
    compute for everything executed inside the scope.
    """
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(str(logdir)):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-span inside an active trace (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


# --------------------------------------------------------------------- #
# service metrics (serving layer)                                        #
# --------------------------------------------------------------------- #

def labeled_name(name: str, labels: Optional[dict]) -> str:
    """Registry key for a (name, labels) pair: ``name{k=v,k2=v2}`` with
    keys sorted — one canonical key per label set."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_labeled_name(key: str):
    """Inverse of :func:`labeled_name`: ``name{k=v,k2=v2}`` back to
    ``(name, labels-or-None)`` [ISSUE 7 satellite]. Consumers of the
    flusher's JSONL (the SLO engine, ``tuplewise doctor``, the future
    multi-tenant SLO surface) group per-label series by base name, so
    the round trip is pinned by test.

    Label VALUES may contain ``{``/``}``/``,``/``=`` only if rendered
    unambiguously; the registry renders str(value), so keep label
    values simple (ints, short tags) — the same contract Prometheus
    labels carry."""
    i = key.find("{")
    if i < 0 or not key.endswith("}"):
        return key, None
    name, inner = key[:i], key[i + 1:-1]
    labels = {}
    for part in inner.split(","):
        k, sep, v = part.partition("=")
        if not sep:
            raise ValueError(f"malformed label in metric key {key!r}")
        labels[k] = v
    return name, labels


class Counter:
    """Monotonic counter: ``c.inc()`` / ``c.inc(5)``; ``c.value``.

    Thread-safe — the micro-batcher increments from its worker thread
    while request threads read snapshots.
    """

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError(f"Counter {self.name}: negative inc {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        out = {"type": "counter", "value": self.value}
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


class Gauge:
    """Point-in-time value: ``g.set(v)`` / ``g.add(dv)``; ``g.value``.

    The live-state complement of Counter [ISSUE 6]: queue depth,
    inflight requests, delta-run size, tombstone occupancy, mesh width
    — values that go DOWN as well as up, where the current reading (not
    the total) is the signal. Thread-safe.
    """

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += float(delta)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        out = {"type": "gauge", "value": self.value}
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


# Default buckets span the serving latency range: 10 us .. ~100 s.
_DEFAULT_BUCKETS = tuple(
    b * s for s in (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)
    for b in (1.0, 2.5, 5.0)
)

# Byte-sized histograms (transfer accounting [ISSUE 5]): powers of 4
# from 256 B to 16 GiB — compaction transfers span KBs (delta runs) to
# GBs (full base re-placements at 10^8).
BYTE_BUCKETS = tuple(256 * 4 ** i for i in range(13))


class Histogram:
    """Fixed-bucket histogram with exact-sample percentile estimates.

    Bucket counts give the Prometheus-style cumulative view
    (``snapshot()``); ``quantile(q)`` interpolates within the retained
    sample window (last ``max_samples`` observations) so p50/p99 stay
    exact for short replay runs while memory stays bounded for long
    services. Thread-safe.
    """

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None,
                 max_samples: int = 65536,
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self.buckets: List[float] = sorted(buckets or _DEFAULT_BUCKETS)
        if max_samples < 1:
            raise ValueError("max_samples must be >= 1")
        self._max_samples = max_samples
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._samples: List[float] = []   # ring buffer of recent values
        self._ring_pos = 0

    def observe(self, value: float) -> None:
        self.observe_n(value, 1)

    def observe_n(self, value: float, n: int) -> None:
        """Record ``value`` with multiplicity ``n`` under ONE lock
        acquisition — the insert-latency stage attribution [ISSUE 6]
        bills a shared per-batch stage duration to every request in the
        batch without n separate observe calls on the hot batcher
        thread. Quantiles and sums weigh the value n times, exactly as
        n ``observe`` calls would."""
        if n < 1:
            if n == 0:
                return
            raise ValueError(f"Histogram {self.name}: negative n {n}")
        value = float(value)
        with self._lock:
            self._bucket_counts[bisect.bisect_left(self.buckets, value)] += n
            self._count += n
            self._sum += value * n
            self._min = value if self._min is None else min(self._min, value)
            self._max = value if self._max is None else max(self._max, value)
            for _ in range(min(n, self._max_samples)):
                if len(self._samples) < self._max_samples:
                    self._samples.append(value)
                else:
                    self._samples[self._ring_pos] = value
                    self._ring_pos = (self._ring_pos + 1) % self._max_samples

    def observe_weighted(self, value: float, n: int) -> None:
        """Record ``value`` with multiplicity ``n`` in the count/sum/
        bucket views but only ONCE in the quantile sample window.

        The wave ledger [ISSUE 14] bills one shared per-wave bucket
        value to every request in the wave: ``observe_n`` would copy
        the value n times into the sample ring (hundreds of list ops
        per wave — measured at ~3-4% of serving throughput), while
        sums/counts are all the tiling invariant needs exact. With
        this method quantiles read PER-WAVE (each wave one sample),
        which is the distribution the host-tax p99 table wants
        anyway; ``sum`` stays exactly ``value * n``.
        """
        if n < 1:
            if n == 0:
                return
            raise ValueError(
                f"Histogram {self.name}: negative n {n}")
        value = float(value)
        with self._lock:
            self._bucket_counts[
                bisect.bisect_left(self.buckets, value)] += n
            self._count += n
            self._sum += value * n
            self._min = value if self._min is None \
                else min(self._min, value)
            self._max = value if self._max is None \
                else max(self._max, value)
            if len(self._samples) < self._max_samples:
                self._samples.append(value)
            else:
                self._samples[self._ring_pos] = value
                self._ring_pos = (self._ring_pos + 1) % self._max_samples

    def observe_many(self, values: Sequence[float]) -> None:
        """Record each value once, under ONE lock acquisition — the
        per-request queue-wait billing of a whole wave [ISSUE 14]
        costs one lock instead of batch-size locks."""
        if not values:
            return
        values = [float(v) for v in values]
        lo, hi, total = min(values), max(values), sum(values)
        with self._lock:
            bc = self._bucket_counts
            bk = self.buckets
            samples = self._samples
            cap = self._max_samples
            for v in values:
                bc[bisect.bisect_left(bk, v)] += 1
                if len(samples) < cap:
                    samples.append(v)
                else:
                    samples[self._ring_pos] = v
                    self._ring_pos = (self._ring_pos + 1) % cap
            self._sum += total
            self._min = lo if self._min is None else min(self._min, lo)
            self._max = hi if self._max is None else max(self._max, hi)
            self._count += len(values)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def mean(self) -> Optional[float]:
        with self._lock:
            return self._sum / self._count if self._count else None

    def quantile(self, q: float) -> Optional[float]:
        """Linear-interpolated quantile over the retained sample window."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        with self._lock:
            xs = sorted(self._samples)
        if not xs:
            return None
        pos = q * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._bucket_counts)
            count, total = self._count, self._sum
            vmin, vmax = self._min, self._max
        out = {
            "type": "histogram",
            "count": count,
            "sum": total,
            "min": vmin,
            "max": vmax,
            "mean": total / count if count else None,
            **({"labels": dict(self.labels)} if self.labels else {}),
            "buckets": {
                ("+inf" if i == len(self.buckets) else repr(self.buckets[i])):
                    c
                for i, c in enumerate(counts) if c
            },
        }
        for q, label in ((0.5, "p50"), (0.9, "p90"), (0.95, "p95"),
                         (0.99, "p99")):
            out[label] = self.quantile(q)
        return out


class MetricsRegistry:
    """Named Counter/Histogram factory + one-call JSON snapshot.

    ``counter(name)`` / ``histogram(name)`` create-or-return, so call
    sites never coordinate registration order. The serving layer keeps
    one registry per engine instance (no process-global state to leak
    between tests).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get(name, Counter, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get(name, Gauge, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  max_samples: int = 65536,
                  labels: Optional[dict] = None) -> Histogram:
        return self._get(name, Histogram, help, labels,
                         buckets=buckets, max_samples=max_samples)

    def _get(self, name, cls, help, labels=None, **kwargs):
        """THE create-or-return path — every metric type goes through
        this one lock-held lookup, so two call sites (or two threads)
        registering the same (name, labels) always share one object
        and a type mismatch raises instead of forking twin series
        [ISSUE 12 satellite]."""
        key = labeled_name(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels=labels, **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key!r} already registered as "
                    f"{type(m).__name__}")
            return m

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items}


def device_memory_stats() -> dict:
    """{device_str: memory_stats dict} for devices that report it."""
    import jax

    out = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(d)] = dict(stats)
    return out
