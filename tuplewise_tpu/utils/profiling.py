"""Tracing / profiling / observability helpers [SURVEY §5.2, §5.6].

The reference has none of this (printed numbers + matplotlib); the build
standardizes three small tools:

* ``timer()``        — wall-clock context manager; the harness reports
                       its numbers alongside every variance result
                       (wall-clock is half the headline metric [B:2]).
* ``trace(logdir)``  — ``jax.profiler`` trace scope (XLA host/device
                       timeline, viewable in TensorBoard/Perfetto);
                       no-op when logdir is None, so callers can thread
                       a CLI flag straight through.
* ``device_memory_stats()`` — per-device HBM usage snapshot where the
                       backend exposes it (TPU does; CPU returns {}).
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def timer() -> Iterator[dict]:
    """``with timer() as t: ...`` then ``t["seconds"]``."""
    out = {"seconds": None}
    t0 = time.perf_counter()
    try:
        yield out
    finally:
        out["seconds"] = time.perf_counter() - t0


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """jax.profiler trace scope; inert when ``logdir`` is None.

    The trace captures XLA compilation, host callbacks, and device
    compute for everything executed inside the scope.
    """
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(str(logdir)):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-span inside an active trace (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> dict:
    """{device_str: memory_stats dict} for devices that report it."""
    import jax

    out = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(d)] = dict(stats)
    return out
