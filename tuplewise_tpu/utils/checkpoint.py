"""Checkpoint/resume for the learner and the Monte-Carlo harness
[SURVEY §5.5].

Single-file ``.npz`` checkpoints, written atomically (tmp + rename):

* ``step``          — how far the run has progressed (SGD steps or
                      Monte-Carlo reps);
* ``param/<name>``  — model parameter arrays (learner);
* ``extra/<name>``  — partial result arrays (loss curves, estimates);
* ``config``        — the run config as a JSON string; on resume the
                      stored config must match the requested one (the
                      progress dimension — steps/reps — excluded), so a
                      checkpoint can never silently continue a different
                      experiment.

Resume is EXACT for both consumers because every source of randomness is
keyed by absolute step/rep index via utils.rng.fold (never by "time since
start"): a run chunked at any boundary reproduces the unchunked run
bit-for-bit. tests/test_checkpoint.py asserts this equivalence.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional

import numpy as np


def save_checkpoint(
    path: str,
    *,
    step: int,
    params: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
    config: Optional[dict] = None,
) -> None:
    """Atomically write a checkpoint (tmp file + os.replace)."""
    blob: Dict[str, Any] = {"step": np.asarray(int(step))}
    for name, arr in (params or {}).items():
        blob[f"param/{name}"] = np.asarray(arr)
    for name, arr in (extra or {}).items():
        blob[f"extra/{name}"] = np.asarray(arr)
    if config is not None:
        blob["config"] = np.asarray(json.dumps(config, sort_keys=True))
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **blob)
            # fsync BEFORE the rename: os.replace makes the new name
            # atomic against a crashed writer, but without the data
            # fsync a machine crash can leave the (renamed) file with
            # torn contents — the serving snapshots [ISSUE 3] rely on
            # rename-implies-complete.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)       # persist the rename itself
            finally:
                os.close(dfd)
        except OSError:
            pass  # directory fsync unsupported on this platform
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_checkpoint(path: str) -> Optional[dict]:
    """Load a checkpoint, or None if ``path`` doesn't exist.

    Returns {"step": int, "params": {...}, "extra": {...}, "config": dict|None}.
    """
    if not os.path.exists(path):
        return None
    with np.load(path, allow_pickle=False) as blob:
        out = {"step": int(blob["step"]), "params": {}, "extra": {},
               "config": None}
        for key in blob.files:
            if key.startswith("param/"):
                out["params"][key[len("param/"):]] = blob[key]
            elif key.startswith("extra/"):
                out["extra"][key[len("extra/"):]] = blob[key]
            elif key == "config":
                out["config"] = json.loads(str(blob[key]))
    return out


def resume_progress(
    path: Optional[str],
    config: dict,
    *,
    progress_key: str,
    requested: int,
):
    """Shared resume preamble for chunked runs (trainer + harness).

    Returns (start, checkpoint-or-None). Validates the stored config
    against ``config`` (ignoring ``progress_key``, the resumable
    dimension) and refuses checkpoints whose progress exceeds the
    request — progress cannot be rewound without producing results
    mislabeled as a shorter run.
    """
    ck = load_checkpoint(path) if path else None
    if ck is None:
        return 0, None
    check_config(ck["config"], config, ignore=(progress_key,))
    start = ck["step"]
    if start > requested:
        raise ValueError(
            f"checkpoint at {progress_key}={start} is past the requested "
            f"{progress_key}={requested}; delete {path!r} to start fresh"
        )
    return start, ck


def iter_chunks(start: int, total: int, every: Optional[int]):
    """Yield (offset, length) chunk bounds covering [start, total).

    ``every`` of None/0 means one chunk; negative values are rejected
    (both consumers share this guard so they cannot diverge)."""
    if not every:
        every = max(total - start, 1)
    if every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {every}")
    m = start
    while m < total:
        c = min(every, total - m)
        yield m, c
        m += c


def prepare_resume(path: Optional[str], resume: bool) -> None:
    """CLI ``--resume`` discipline [ISSUE 4]: without ``--resume`` an
    existing checkpoint file is removed (a fresh run), so a stale file
    from an earlier experiment can never silently turn a new run into a
    continuation. With ``--resume`` the file is left for
    :func:`resume_progress` (which still validates the stored config).
    Library callers keep auto-resume semantics by not calling this."""
    if path and not resume and os.path.exists(path):
        os.unlink(path)


def params_digest(params: Dict[str, Any]) -> str:
    """Order-independent SHA-256 of a params dict — the cheap
    bit-identity witness the preemption smoke and resume tests compare
    across processes (equal digests <=> equal bytes in every array)."""
    import hashlib

    h = hashlib.sha256()
    for name in sorted(params):
        arr = np.ascontiguousarray(np.asarray(params[name]))
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def check_config(
    stored: Optional[dict], requested: dict, *, ignore: tuple = ()
) -> None:
    """Raise if a checkpoint's config doesn't match the requested run
    (modulo ``ignore`` — the progress dimensions like steps/n_reps)."""
    if stored is None:
        return
    a = {k: v for k, v in stored.items() if k not in ignore}
    b = {k: v for k, v in requested.items() if k not in ignore}
    if a != b:
        diff = {
            k: (a.get(k), b.get(k))
            for k in sorted(set(a) | set(b))
            if a.get(k) != b.get(k)
        }
        raise ValueError(
            f"checkpoint config mismatch (stored vs requested): {diff}; "
            "refusing to resume a different experiment — delete the "
            "checkpoint file to start fresh"
        )
