"""Results-file naming shared by the experiment suites.

The rule (one copy, three consumers — northstar, learning_suite,
config_suite): --quick smoke runs write to ``*_quick`` sibling files so
they can NEVER truncate or replace committed full-run artifacts, and
the audit (scripts/stat_check.py) ignores the siblings entirely.
"""

from __future__ import annotations

import os

QUICK_SUFFIX = "_quick"


def quick_sibling(name: str, quick: bool) -> str:
    """``name`` unchanged for full runs; ``stem_quick.ext`` for quick."""
    if not quick:
        return name
    stem, ext = os.path.splitext(name)
    return f"{stem}{QUICK_SUFFIX}{ext}"


def strip_quick(name: str) -> str:
    """Base name of a possibly-quick-suffixed results file."""
    stem, ext = os.path.splitext(name)
    if stem.endswith(QUICK_SUFFIX):
        stem = stem[: -len(QUICK_SUFFIX)]
    return stem + ext


def is_quick(name: str) -> bool:
    return os.path.splitext(name)[0].endswith(QUICK_SUFFIX)
