"""JAX version compatibility shims.

The codebase targets the current public API (``jax.shard_map`` with
``check_vma=``). Older jax releases (< 0.5) ship the same primitive as
``jax.experimental.shard_map.shard_map`` with the replication check
spelled ``check_rep=``. ``ensure_shard_map()`` installs a forwarding
alias so every call site can use the modern spelling unconditionally;
it is invoked once from the package ``__init__``.
"""

from __future__ import annotations

import functools


def ensure_lax_axis_size() -> None:
    """Older jax has no ``lax.axis_size``; ``core.axis_frame(name)``
    returns the same static mesh-axis size there."""
    try:
        import jax
    except ImportError:
        return
    if hasattr(jax.lax, "axis_size"):
        return
    import jax.core as core

    def _axis_size(axis_name):
        if isinstance(axis_name, (tuple, list)):
            n = 1
            for name in axis_name:
                n *= core.axis_frame(name)
            return n
        return core.axis_frame(axis_name)

    jax.lax.axis_size = _axis_size


def sharded_take(x, idx, sharding):
    """``x[idx]`` with the gather output placed per ``sharding``.

    Newer jax spells this ``x.at[idx].get(out_sharding=...)``; older
    releases reject the kwarg, where a ``with_sharding_constraint`` on
    the plain gather pins the same placement.
    """
    import jax

    try:
        return x.at[idx].get(out_sharding=sharding)
    except TypeError:
        return jax.lax.with_sharding_constraint(x[idx], sharding)


def ensure_shard_map() -> None:
    try:
        import jax
        from jax.experimental.shard_map import shard_map as _exp_shard_map
    except ImportError:
        # host-only install (numpy/cpp backends) or a jax too old to
        # have even the experimental module — nothing to shim
        return
    if hasattr(jax, "shard_map"):
        return

    @functools.wraps(_exp_shard_map)
    def _shard_map_compat(f, *args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _exp_shard_map(f, *args, **kwargs)

    jax.shard_map = _shard_map_compat
