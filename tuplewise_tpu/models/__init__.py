from tuplewise_tpu.models.scorers import LinearScorer, MLPScorer, init_scorer

__all__ = ["LinearScorer", "MLPScorer", "init_scorer"]
