"""L5 — distributed pairwise SGD (AUC maximization / bipartite ranking).

The paper's learning path [SURVEY §1.3, §4.4]: minimize the pairwise
surrogate risk

    L(theta) = mean_{i,j} l( s_theta(x_i) - s_theta(y_j) )

with synchronous distributed SGD: each worker differentiates the loss
over ITS OWN pairs (all local pairs, or B sampled ones), gradients are
`lax.pmean`'d over the mesh, parameters update identically everywhere,
and the data is re-partitioned every ``repartition_every`` steps — the
communication/repartition trade-off of the title, now on the learning
side. BASELINE config 2 ("Bipartite ranking / pairwise hinge on Adult").

TPU mapping:
* full-pair local losses differentiate through the CHECKPOINTED tiled
  reduction (ops.pair_tiles), so backprop re-streams tiles instead of
  storing the pair grid [SURVEY §7 "Hard parts"];
* the whole training run is ONE jitted `lax.scan` over steps; the
  repartition event is a `lax.cond` regather of worker blocks from the
  sharded global arrays (XLA's all-to-all — executed only on refresh
  steps);
* a NumPy oracle trainer (analytic pairwise gradient, blockwise) pins
  the semantics for parity tests, mirroring Estimator's backend split.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tuplewise_tpu.utils.compat import sharded_take
from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.ops import pair_tiles
from tuplewise_tpu.ops.kernels import get_kernel
from tuplewise_tpu.parallel.mesh import make_mesh
from tuplewise_tpu.utils.rng import fold, root_key


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Pairwise-SGD hyperparameters [SURVEY §4.4, §5.9]."""

    kernel: str = "logistic"          # surrogate: "logistic" | "hinge"
    lr: float = 0.1
    steps: int = 100
    n_workers: int = 1
    repartition_every: int = 10       # n_r: communication budget knob
    pairs_per_worker: Optional[int] = None  # None = all local pairs
    # per-worker pair-budget design [SURVEY §1.2 item 4; VERDICT r3
    # next #6]: "swr" | "swor" | "bernoulli", drawn ON DEVICE per step
    # (ops.device_design — sort-based distinct sampling inside the
    # jitted scan, where the host samplers of the estimation side
    # cannot reach)
    pair_design: str = "swr"
    scheme: str = "swor"
    seed: int = 0
    tile: int = 512
    # record the surrogate loss every k steps [VERDICT r4 next #1]: on
    # non-recorded steps the full-pair path dispatches the GRAD-ONLY
    # Pallas kernel (one g'-pass; the fused loss+grad kernel's g-body
    # costs ~35% of a step for a value the scan would discard) and the
    # history carries NaN there. Gradients are identical either way —
    # loss_every changes what is RECORDED, never the trajectory. A
    # value >= steps records only step 0 ("loss-free" training); the
    # budgeted path (pairs_per_worker) computes its loss as a free
    # byproduct of the gradient, so only the NaN masking applies there.
    loss_every: int = 1


# --------------------------------------------------------------------- #
# mesh trainer                                                          #
# --------------------------------------------------------------------- #

@functools.lru_cache(maxsize=8)
def _compiled_trainer(scorer, cfg, mesh, n1, n2):
    """Compiled chunk program for (scorer, cfg-sans-steps, mesh, sizes).

    train_pairwise used to rebuild these closures (and thus recompile)
    on every call; caching here makes repeated training runs — sweeps,
    resumed sessions, the benchmark suite — pay one compile per
    configuration. Data enters as arguments, so the cache holds no
    array references; jit itself retraces per feature-dim/shape."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tuplewise_tpu.parallel.device_partition import draw_blocks as _draw

    kernel = get_kernel(cfg.kernel)
    N = int(np.prod(mesh.devices.shape))
    axes = tuple(mesh.axis_names)
    shard_blocks = NamedSharding(mesh, P(axes))
    m1, m2 = n1 // N, n2 // N
    root = root_key(cfg.seed)

    def draw_blocks(key, n, m):
        return _draw(key, n, N, cfg.scheme, m=m)

    def sgd_body(params, a, b, key, record):
        """One worker's step: local pair gradient, pmean, update.
        a, b: [1, m, d] local blocks; record: scalar bool — whether
        this step's loss is recorded (cfg.loss_every boundary)."""

        def loss_fn(p, loss_free=False):
            s1 = scorer.apply(p, a[0], jnp)
            s2 = scorer.apply(p, b[0], jnp)
            if cfg.pairs_per_worker is None:
                if loss_free:
                    # grad-only pass: NaN value, identical gradient
                    return pair_tiles.diff_pair_mean_loss_free(
                        kernel, s1, s2, cfg.tile, cfg.tile
                    )
                # analytic streamed g' backward when the surrogate
                # declares one (hinge/logistic do): ~100x the
                # autodiff-through-tiles gradient at n=10^5
                return pair_tiles.pair_mean_for_grad(
                    kernel, s1, s2, tile_a=cfg.tile, tile_b=cfg.tile
                )
            from tuplewise_tpu.parallel.device_partition import (
                linear_shard_index,
            )

            from tuplewise_tpu.ops.device_design import (
                draw_pair_design_device,
            )

            kk = fold(key, "pair_sample", linear_shard_index(axes))
            i, j, w = draw_pair_design_device(
                kk, m1, m2, cfg.pairs_per_worker, cfg.pair_design
            )
            vals = kernel.diff(s1[i] - s2[j], jnp)
            # max(., 1): an exact small-G bernoulli draw can realize an
            # EMPTY design — a zero-weight step, not NaN
            return jnp.sum(vals * w) / jnp.maximum(jnp.sum(w), 1.0)

        if cfg.pairs_per_worker is None and cfg.loss_every != 1:
            # both branches traced once; each step executes ONE grid
            # pass — fused loss+grad on recorded steps, g'-only between
            loss, grads = lax.cond(
                record,
                lambda p: jax.value_and_grad(loss_fn)(p),
                lambda p: jax.value_and_grad(
                    lambda q: loss_fn(q, loss_free=True)
                )(p),
                params,
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params)
            if cfg.loss_every != 1:
                # budgeted path: the loss is a free byproduct of the
                # gradient — only the recording mask applies
                loss = jnp.where(record, loss, jnp.nan)
        grads = jax.tree.map(lambda g: lax.pmean(g, axes), grads)
        loss = lax.pmean(loss, axes)
        new_params = jax.tree.map(
            lambda p, g: p - cfg.lr * g, params, grads
        )
        return new_params, loss

    sgd_smap = jax.shard_map(
        sgd_body,
        mesh=mesh,
        in_specs=(P(), P(axes), P(axes), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def step_fn(carry, t, t0, Xp, Xn):
        params, Ab, Bb = carry
        kt = fold(root, "step", t)

        def refresh(_):
            kr = fold(root, "repartition", t)
            k1, k2 = jax.random.split(kr)
            i1 = draw_blocks(k1, n1, m1)
            i2 = draw_blocks(k2, n2, m2)
            return (
                sharded_take(Xp, i1, shard_blocks),
                sharded_take(Xn, i2, shard_blocks),
            )

        # the chunk's first blocks (incl. a boundary-aligned t0) are
        # drawn by chunk_fn with the same key, so only refresh on LATER
        # boundaries — one startup regather per chunk, not two
        Ab, Bb = lax.cond(
            (t % cfg.repartition_every == 0) & (t > t0),
            refresh, lambda _: (Ab, Bb), None,
        )
        params, loss = sgd_smap(
            params, Ab, Bb, kt, t % cfg.loss_every == 0
        )
        return (params, Ab, Bb), loss

    def chunk_fn(params, Xp, Xn, t0, chunk_len):
        """Steps [t0, t0 + chunk_len). Blocks are regathered as of the
        most recent repartition boundary r0 = t0 - t0 % n_r with the key
        folded from r0, so any chunking reproduces the unchunked run."""
        r0 = t0 - t0 % cfg.repartition_every
        kr = fold(root, "repartition", r0)
        k1, k2 = jax.random.split(kr)
        Ab = sharded_take(Xp, draw_blocks(k1, n1, m1), shard_blocks)
        Bb = sharded_take(Xn, draw_blocks(k2, n2, m2), shard_blocks)
        (params, _, _), losses = lax.scan(
            functools.partial(step_fn, t0=t0, Xp=Xp, Xn=Xn),
            (params, Ab, Bb), t0 + jnp.arange(chunk_len)
        )
        return params, losses

    return jax.jit(chunk_fn, static_argnums=4)


def train_pairwise(
    scorer,
    params,
    X_pos: np.ndarray,
    X_neg: np.ndarray,
    cfg: TrainConfig,
    mesh=None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
    chaos=None,
    heal_retries: int = 2,
    retry_backoff_s: float = 0.05,
    tracer=None,
    metrics=None,
):
    """Distributed pairwise SGD over a device mesh.

    Returns (params, history) where history["loss"] is the per-step
    psum-averaged surrogate loss (NaN on steps cfg.loss_every skips —
    the trajectory is unchanged, only the recording). Runs on any mesh
    size >= 1 (a 1-chip mesh reproduces serial SGD over the full pair
    set).

    Checkpoint/resume [SURVEY §5.5]: with ``checkpoint_path``, training
    runs in scan chunks of ``checkpoint_every`` steps (default: one
    chunk) and saves params + loss history after each; an existing
    checkpoint resumes from its saved step. Resume is EXACT: every key
    is folded from the absolute step index, so a chunked run reproduces
    the unchunked run bit-for-bit (cfg.steps may differ across resumes;
    every other config field must match) — including across a SIGKILL:
    the trajectory is a function of (step, seed) only, never of where
    the last process died.

    Elastic re-sharding [ISSUE 4]: a chunk that fails (device death
    surfaces as the dispatch raising) runs the shared heal-and-retry
    protocol (``parallel.self_heal.MeshHealer``): probe, rebuild the
    mesh AT THE SAME logical width over the surviving device pool
    (``jax.devices()`` spares backfill lost slots — n_workers is part
    of the experiment's semantics, so the width must not drift),
    re-place the data blocks and params, rebuild the compiled chunk,
    retry with bounded jittered backoff (at most ``heal_retries``
    times). The resumed trajectory is bit-identical because every key
    folds from absolute step indices — physical placement never enters
    the math. When spares run out (``HealExhaustedError``) the job is
    left to checkpoint/resume on a healthy pool. ``chaos``: a
    ``testing.chaos.FaultInjector`` fired at the ``train_step`` hook
    (before each chunk) and ``checkpoint`` hook (after each save —
    where the ``sigkill`` action models real preemption).

    ``tracer`` [ISSUE 6]: an ``obs.tracing.Tracer`` — each scan chunk
    becomes a ``train.chunk`` span and each checkpoint save a
    ``train.checkpoint`` span (one trace per training run), so a slow
    run's timeline shows where the wall-clock went. ``metrics``: a
    ``MetricsRegistry`` that receives live gauges (``train_step``,
    ``train_loss_last``), a ``train_chunk_s`` histogram, and the
    healer's recovery counters — what ``tuplewise train
    --metrics-out`` streams through the ``obs.MetricsFlusher``.
    """
    kernel = get_kernel(cfg.kernel)
    if kernel.kind != "diff":
        raise ValueError(
            f"learner needs a score-difference surrogate kernel, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    if kernel.name == "auc":
        raise ValueError(
            "the AUC indicator has zero gradient almost everywhere; train "
            "with a surrogate ('logistic' or 'hinge') and evaluate with "
            "evaluate_auc"
        )
    if (cfg.loss_every != 1 and cfg.pairs_per_worker is None
            and kernel.diff_grad_fn is None):
        # lax.cond traces BOTH branches, and the loss-free branch has no
        # autodiff fallback (grad-only needs the analytic g'): fail here
        # with the reason, not deep inside the jitted scan
        raise ValueError(
            f"loss_every={cfg.loss_every} needs an analytic gradient "
            f"(kernel {kernel.name!r} has no diff_grad_fn); use "
            "loss_every=1 or a kernel with diff_grad_fn"
        )
    mesh = mesh if mesh is not None else make_mesh(cfg.n_workers)
    N = int(np.prod(mesh.devices.shape))
    # all mesh axes together form the worker axis (1-D or 2-D dcn x ici
    # meshes alike) — same generalization as MeshBackend
    axes = tuple(mesh.axis_names)
    shard_blocks = NamedSharding(mesh, P(axes))
    replicated = NamedSharding(mesh, P())

    from tuplewise_tpu.parallel.device_partition import draw_blocks as _draw
    from tuplewise_tpu.parallel.device_partition import pad_put

    n1, n2 = len(X_pos), len(X_neg)
    m1, m2 = n1 // N, n2 // N
    if min(m1, m2) < 1:
        raise ValueError(f"n=({n1},{n2}) too small for {N} workers")

    Xp, Xn = pad_put(X_pos, mesh), pad_put(X_neg, mesh)
    params = jax.device_put(
        jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params),
        replicated,
    )

    # compiled-chunk cache: key excludes steps (chunk length is an
    # argument) so sweeps over step counts reuse the same executable
    run_chunk = _compiled_trainer(
        scorer, dataclasses.replace(cfg, steps=0), mesh, n1, n2
    )

    # ---- checkpoint/resume plumbing [SURVEY §5.5] -------------------- #
    from tuplewise_tpu.utils.checkpoint import (
        iter_chunks, resume_progress, save_checkpoint,
    )

    start, ck = resume_progress(
        checkpoint_path, dataclasses.asdict(cfg),
        progress_key="steps", requested=cfg.steps,
    )
    loss_parts = []
    if ck is not None:
        loss_parts = [ck["extra"]["loss"]]
        params = jax.device_put(
            {k: jnp.asarray(v, jnp.float32)
             for k, v in ck["params"].items()},
            replicated,
        )
        if start == cfg.steps:
            return (
                jax.tree.map(np.asarray, params),
                {"loss": np.concatenate(loss_parts),
                 "recovery": {"resumed_from": int(start),
                              "reshard_events": 0, "retries_total": 0,
                              "mesh_workers": N}},
            )

    # ---- elastic heal-and-retry around each chunk [ISSUE 4] ---------- #
    from tuplewise_tpu.parallel.self_heal import Backoff, MeshHealer

    healer = None
    if heal_retries:
        healer = MeshHealer(
            mesh, fixed_width=N, pool=list(jax.devices()), chaos=chaos,
            backoff=Backoff(base_s=retry_backoff_s, seed=cfg.seed),
            metrics=metrics, tracer=tracer)

    # live training gauges [ISSUE 6]: what --metrics-out streams
    g_step = g_loss = h_chunk = None
    if metrics is not None:
        g_step = metrics.gauge("train_step")
        g_loss = metrics.gauge("train_loss_last")
        h_chunk = metrics.histogram("train_chunk_s")
        metrics.gauge("mesh_width").set(N)

    def on_heal(h):
        # adopt the healed mesh and re-place EVERYTHING on it: data
        # blocks, replicated params (host round-trip — the old mesh's
        # buffers may be torn), and the compiled chunk program
        nonlocal mesh, replicated, shard_blocks, Xp, Xn, params, run_chunk
        mesh = h.mesh
        replicated = NamedSharding(mesh, P())
        shard_blocks = NamedSharding(mesh, P(tuple(mesh.axis_names)))
        Xp, Xn = pad_put(X_pos, mesh), pad_put(X_neg, mesh)
        params = jax.device_put(jax.tree.map(np.asarray, params),
                                replicated)
        run_chunk = _compiled_trainer(
            scorer, dataclasses.replace(cfg, steps=0), mesh, n1, n2)

    from tuplewise_tpu.obs.tracing import maybe_span

    run_span = None
    if tracer is not None:
        run_span = tracer.start("train.run", parent=None,
                                steps=cfg.steps, n_workers=N)
    for t, chunk in iter_chunks(start, cfg.steps, checkpoint_every):
        def attempt(t=t, chunk=chunk):
            if chaos is not None:
                chaos.fire("train_step")
            return run_chunk(params, Xp, Xn, jnp.asarray(t, jnp.int32),
                             chunk)

        t_chunk0 = time.perf_counter()
        with maybe_span(tracer, "train.chunk", parent=run_span,
                        step=t, steps=chunk):
            if healer is not None:
                params, losses = healer.run(attempt,
                                            retries=heal_retries,
                                            on_heal=on_heal)
            else:
                params, losses = attempt()
        loss_parts.append(np.asarray(losses))
        if metrics is not None:
            h_chunk.observe(time.perf_counter() - t_chunk0)
            g_step.set(t + chunk)
            last = float(np.asarray(losses)[-1]) if len(losses) else None
            if last is not None and np.isfinite(last):
                g_loss.set(last)
        if checkpoint_path:
            with maybe_span(tracer, "train.checkpoint",
                            parent=run_span, step=t + chunk):
                save_checkpoint(
                    checkpoint_path,
                    step=t + chunk,
                    params=jax.tree.map(np.asarray, params),
                    extra={"loss": np.concatenate(loss_parts)},
                    config=dataclasses.asdict(cfg),
                )
            if chaos is not None:
                # deterministic preemption point: the checkpoint above
                # is durable, so a 'sigkill' scheduled here dies with
                # exactly t + chunk steps recoverable
                chaos.fire("checkpoint")
    if tracer is not None:
        tracer.finish(run_span)
    history = {"loss": np.concatenate(loss_parts)}
    if healer is not None:
        history["recovery"] = {
            "resumed_from": int(start),
            "reshard_events": healer.reshard_events,
            "retries_total": healer.retries_total,
            "mesh_workers": healer.n_workers,
        }
    return jax.tree.map(np.asarray, params), history


# --------------------------------------------------------------------- #
# NumPy oracle trainer (parity reference)                               #
# --------------------------------------------------------------------- #

_SURROGATE_DERIV = {
    # d/dd of the surrogate l(d)
    "logistic": lambda d: -1.0 / (1.0 + np.exp(d)),   # -sigmoid(-d)
    "hinge": lambda d: np.where(d < 1.0, -1.0, 0.0),
}


def train_pairwise_numpy(
    scorer,
    params,
    X_pos: np.ndarray,
    X_neg: np.ndarray,
    cfg: TrainConfig,
):
    """Serial oracle: same schedule, analytic full-pair gradients for a
    LINEAR scorer (the paper's model), blockwise over the pair grid."""
    assert cfg.kernel in _SURROGATE_DERIV, cfg.kernel
    assert cfg.pairs_per_worker is None, "oracle trainer uses all pairs"
    deriv = _SURROGATE_DERIV[cfg.kernel]
    kernel = get_kernel(cfg.kernel)
    from tuplewise_tpu.parallel.partition import partition_two_sample

    params = {k: np.asarray(v, np.float64) for k, v in params.items()}
    rng = np.random.default_rng(cfg.seed)
    N = cfg.n_workers
    losses = []
    parts = None  # drawn by the t=0 refresh below
    for t in range(cfg.steps):
        if t % cfg.repartition_every == 0:
            parts = partition_two_sample(
                len(X_pos), len(X_neg), N, rng, cfg.scheme
            )
        g_w = np.zeros_like(params["w"])
        g_b = 0.0  # pairwise loss of s(x)-s(y) has zero bias gradient
        loss_acc = 0.0
        for w_idx in range(N):
            A = X_pos[parts[0][w_idx]]
            Bm = X_neg[parts[1][w_idx]]
            s1 = A @ params["w"] + params["b"]
            s2 = Bm @ params["w"] + params["b"]
            d = s1[:, None] - s2[None, :]
            lp = deriv(d)
            cnt = d.size
            loss_acc += float(np.mean(kernel.diff(d, np)))
            # dL/dw = mean_ij l'(d_ij) (x_i - y_j)
            g_w += (lp.sum(axis=1) @ A + (-lp.sum(axis=0)) @ Bm) / cnt
        params["w"] = params["w"] - cfg.lr * (g_w / N)
        params["b"] = params["b"] - cfg.lr * g_b
        losses.append(loss_acc / N)
    return params, {"loss": np.asarray(losses)}


# --------------------------------------------------------------------- #
# helpers                                                               #
# --------------------------------------------------------------------- #

def split_by_label(X: np.ndarray, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(positives, negatives) feature blocks from a labeled set."""
    y = np.asarray(y)
    return np.asarray(X)[y == 1], np.asarray(X)[y == 0]


def evaluate_auc(scorer, params, X_pos, X_neg) -> float:
    """Rank-based AUC of the scorer on the GIVEN sample [SURVEY §3
    'Evaluation']. It is a test AUC only when called with held-out data
    (see :mod:`tuplewise_tpu.data.splits`); callers report train and
    test AUC separately."""
    params = jax.tree.map(np.asarray, params)
    s1 = scorer.apply(params, np.asarray(X_pos), np)
    s2 = scorer.apply(params, np.asarray(X_neg), np)
    return auc_score(s1, s2)
