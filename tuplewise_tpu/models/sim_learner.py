"""Simulated-N distributed pairwise SGD — the learning-side trade-off
instrument [SURVEY §1.3, §4.4; VERDICT r2 next #1].

The paper's learning experiments sweep worker counts far beyond any
physical device count (the trade-off becomes visible when per-worker
blocks are SMALL, i.e. N large). This module runs the SAME distributed
semantics as models.pairwise_sgd's mesh trainer — identical partition
fold chains, identical draw_blocks, identical per-step schedule — but
maps workers onto a `jax.vmap` axis on ONE chip instead of a device
mesh, so N is limited by memory, not hardware. A second vmap axis runs
Monte-Carlo seeds in the same compiled program: learning curves arrive
averaged, with error bars, in one scan.

Equivalence to the mesh trainer is a TESTED property, not an intent:
with the same TrainConfig and seed, the simulated trainer reproduces
the mesh trainer's parameter trajectory to float tolerance
(tests/test_sim_learner.py) — the key chains match because both fold
(root, "repartition", t) / (root, "step", t) / (kt, "pair_sample", w)
through utils.rng.fold and share parallel.device_partition.draw_blocks.

Scope: full-local-pair or sampled-pair losses on diff kernels, direct
[m1, m2] per-worker pair grids (memory N * m1 * m2 per seed — the
small-block regime this instrument exists for; production-scale blocks
belong to the mesh trainer's streamed tiles).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tuplewise_tpu.ops import pair_tiles
from tuplewise_tpu.ops.kernels import get_kernel
from tuplewise_tpu.ops.rank_auc import rank_auc
from tuplewise_tpu.parallel.device_partition import draw_blocks
from tuplewise_tpu.utils.rng import fold, root_key

# TrainConfig.repartition_every sentinel for "never repartition";
# curve_record maps it to n_r = null in emitted rows
NEVER = 1 << 30


def last_recorded_loss(loss, loss_every: int) -> float | None:
    """Mean loss at the last step cfg.loss_every RECORDED — the ONE
    copy of the summary rule shared by curve_record, the CLI, and the
    throughput rows. Looks at the recording PATTERN (t % loss_every
    == 0), not at finiteness: a masked step is skipped, but a recorded
    step that diverged to NaN/inf returns None instead of silently
    falling back to an earlier finite value (None in place of a number
    is the divergence flag; a NaN literal would be invalid JSON)."""
    loss = np.atleast_2d(np.asarray(loss))
    steps = loss.shape[-1]
    if steps == 0:
        return None
    k = max(int(loss_every), 1)
    last = ((steps - 1) // k) * k
    v = float(loss[..., last].mean())
    return v if np.isfinite(v) else None


def curve_record(cfg, out, n_seeds: int) -> dict:
    """Summary row for one :func:`train_curves` cell — the ONE copy of
    the row schema shared by scripts/learning_suite.py and the CLI
    ``learning`` subcommand (n_r null-mapping, comm_events accounting,
    rounding, and the seed-spread statistics).

    With n_seeds < 2 the spread fields are null (a sample SD over one
    replica is undefined — emitting NaN would produce invalid JSON).
    """
    auc = out["test_auc"]                        # [S, K]
    fin = auc[:, -1]
    if n_seeds >= 2:
        auc_se = np.round(
            auc.std(axis=0, ddof=1) / np.sqrt(n_seeds), 7
        ).tolist()
        final_se = float(fin.std(ddof=1) / np.sqrt(n_seeds))
        final_sd = float(fin.std(ddof=1))
    else:
        auc_se = [None] * auc.shape[1]
        final_se = final_sd = None
    return {
        "kernel": cfg.kernel, "lr": cfg.lr, "steps": cfg.steps,
        "n_workers": cfg.n_workers,
        "n_r": (None if cfg.repartition_every >= NEVER
                else cfg.repartition_every),
        "repartition_every": cfg.repartition_every,
        "pairs_per_worker": cfg.pairs_per_worker,
        "pair_design": cfg.pair_design,
        "n_seeds": n_seeds,
        # 1 initial partition + one event per later boundary
        "comm_events": 1 + (cfg.steps - 1) // cfg.repartition_every,
        "eval_steps": out["steps"].tolist(),
        "auc_mean": np.round(auc.mean(axis=0), 6).tolist(),
        "auc_se": auc_se,
        "final_auc_mean": float(fin.mean()),
        "final_auc_se": final_se,
        "final_auc_sd": final_sd,
        # last RECORDED loss (None = never recorded or diverged; a NaN
        # here would be the invalid-JSON case the docstring forbids)
        "loss_final_mean": last_recorded_loss(
            out["loss"], cfg.loss_every
        ),
    }


@functools.lru_cache(maxsize=32)
def _compiled_sim_trainer(scorer, cfg, n1, n2):
    """Jitted chunk program vmapped over (seeds, workers).

    Signature: run(params_batch, Xp, Xn, roots, t0, chunk_len) ->
    (params_batch, losses [S, chunk]); params_batch has a leading seed
    axis, roots is a [S] key array. Cache key excludes steps/seed (both
    are runtime inputs), mirroring pairwise_sgd._compiled_trainer."""
    kernel = get_kernel(cfg.kernel)
    N = cfg.n_workers
    m1, m2 = n1 // N, n2 // N

    def local_loss(p, a, b, kk):
        """One worker's loss on its [m1, d] / [m2, d] blocks."""
        s1 = scorer.apply(p, a, jnp)
        s2 = scorer.apply(p, b, jnp)
        if cfg.pairs_per_worker is None:
            d = s1[:, None] - s2[None, :]
            return jnp.mean(kernel.diff(d, jnp))
        from tuplewise_tpu.ops.device_design import (
            draw_pair_design_device,
        )

        i, j, w = draw_pair_design_device(
            kk, m1, m2, cfg.pairs_per_worker, cfg.pair_design
        )
        vals = kernel.diff(s1[i] - s2[j], jnp)
        # max(., 1): an exact small-G bernoulli draw can realize an
        # EMPTY design — a zero-weight step, not NaN
        return jnp.sum(vals * w) / jnp.maximum(jnp.sum(w), 1.0)

    def draw_both(kr):
        k1, k2 = jax.random.split(kr)
        return (
            draw_blocks(k1, n1, N, cfg.scheme, m=m1),
            draw_blocks(k2, n2, N, cfg.scheme, m=m2),
        )

    def step(carry, t, t0, Xp, Xn, root):
        params, Ab, Bb = carry

        def refresh(_):
            i1, i2 = draw_both(fold(root, "repartition", t))
            return Xp[i1], Xn[i2]

        # first blocks (incl. a boundary-aligned t0) come from chunk_fn
        # with the same key — refresh only on LATER boundaries, exactly
        # as the mesh trainer does
        Ab, Bb = lax.cond(
            (t % cfg.repartition_every == 0) & (t > t0),
            refresh, lambda _: (Ab, Bb), None,
        )
        kt = fold(root, "step", t)
        keys = jax.vmap(lambda w: fold(kt, "pair_sample", w))(
            jnp.arange(N)
        )
        losses, grads = jax.vmap(
            jax.value_and_grad(local_loss), in_axes=(None, 0, 0, 0)
        )(params, Ab, Bb, keys)
        g = jax.tree.map(lambda x: jnp.mean(x, axis=0), grads)
        params = jax.tree.map(lambda p, gg: p - cfg.lr * gg, params, g)
        loss = jnp.mean(losses)
        if cfg.loss_every != 1:
            # history parity with the mesh trainer's loss_every
            # semantics: the dense-grid loss is free here, but the
            # RECORD must match (NaN off the cfg.loss_every boundary)
            loss = jnp.where(t % cfg.loss_every == 0, loss, jnp.nan)
        return (params, Ab, Bb), loss

    def chunk_one_seed(params, Xp, Xn, root, t0, chunk_len):
        # regather blocks as of the latest repartition boundary, with
        # the key folded from that boundary's absolute index: chunked
        # runs reproduce the unchunked trajectory bit-for-bit
        r0 = t0 - t0 % cfg.repartition_every
        i1, i2 = draw_both(fold(root, "repartition", r0))
        (params, _, _), losses = lax.scan(
            functools.partial(step, t0=t0, Xp=Xp, Xn=Xn, root=root),
            (params, Xp[i1], Xn[i2]),
            t0 + jnp.arange(chunk_len),
        )
        return params, losses

    run = jax.vmap(chunk_one_seed, in_axes=(0, None, None, 0, None, None))
    return jax.jit(run, static_argnums=5)


@functools.lru_cache(maxsize=8)
def _compiled_auc_eval(scorer):
    @jax.jit
    def ev(params_batch, Xp_te, Xn_te):
        def one(p):
            return rank_auc(
                scorer.apply(p, Xp_te, jnp), scorer.apply(p, Xn_te, jnp)
            )

        return jax.vmap(one)(params_batch)

    return ev


def train_curves(
    scorer,
    params0,
    X_pos: np.ndarray,
    X_neg: np.ndarray,
    X_pos_test: np.ndarray,
    X_neg_test: np.ndarray,
    cfg,
    *,
    n_seeds: int = 8,
    eval_every: int = 25,
):
    """Monte-Carlo learning curves of simulated-N distributed SGD.

    Trains ``n_seeds`` independent replicas (seeds cfg.seed ..
    cfg.seed + n_seeds - 1 govern partition/sampling randomness; the
    init is SHARED so the spread isolates the partition effect),
    evaluating held-out rank AUC every ``eval_every`` steps.

    Returns a dict: ``steps`` [K], ``test_auc`` [S, K] (K includes the
    step-0 init point), ``loss`` [S, steps], ``final_params`` pytree
    with leading seed axis.
    """
    n1, n2 = len(X_pos), len(X_neg)
    N = cfg.n_workers
    if n1 // N < 1 or n2 // N < 1:
        raise ValueError(f"n=({n1},{n2}) too small for {N} workers")
    run = _compiled_sim_trainer(
        scorer, dataclasses.replace(cfg, steps=0, seed=0), n1, n2
    )
    ev = _compiled_auc_eval(scorer)

    S = n_seeds
    params = jax.tree.map(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x, jnp.float32), (S,) + np.shape(x)
        ),
        params0,
    )
    roots = jax.vmap(root_key)(cfg.seed + jnp.arange(S))
    Xp = jnp.asarray(X_pos, jnp.float32)
    Xn = jnp.asarray(X_neg, jnp.float32)
    Xp_te = jnp.asarray(X_pos_test, jnp.float32)
    Xn_te = jnp.asarray(X_neg_test, jnp.float32)

    steps_axis = [0]
    aucs = [np.asarray(ev(params, Xp_te, Xn_te))]
    loss_parts = []
    t = 0
    while t < cfg.steps:
        chunk = min(eval_every, cfg.steps - t)
        params, losses = run(
            params, Xp, Xn, roots, jnp.asarray(t, jnp.int32), chunk
        )
        loss_parts.append(np.asarray(losses))
        t += chunk
        steps_axis.append(t)
        aucs.append(np.asarray(ev(params, Xp_te, Xn_te)))
    return {
        "steps": np.asarray(steps_axis),
        "test_auc": np.stack(aucs, axis=1),        # [S, K]
        "loss": np.concatenate(loss_parts, axis=1),  # [S, steps]
        "final_params": params,
    }
