"""Degree-3 metric-learning SGD — the triplet-loss learner
[VERDICT r3 next #9; SURVEY §1.1 general-degree learning].

The estimation side's config 4 measures the per-class degree-(2,1)
triplet statistic U_c = mean_{i != j in c, k not in c} h(x_i, x_j, y_k)
on FIXED embeddings; this module LEARNS the embedding: a linear map
W in R^{d x k} trained with the triplet-hinge surrogate

    l(a, p, n) = max(0, margin + ||Wa - Wp||^2 - ||Wa - Wn||^2)

by the same distributed schedule as the pairwise learner
(models.pairwise_sgd): each worker holds a block of anchors/positives
(the target class) and a block of negatives, differentiates the mean
surrogate over B sampled local triplets per step, gradients are
lax.pmean'd, and blocks regather every ``repartition_every`` steps
(lax.cond all-to-all inside one jitted scan). Held-out quality is the
triplet ACCURACY — exactly config 4's indicator statistic on embedded
test data, evaluated by this library's own degree-3 estimator (the
Pallas distance factorization on TPU).

Per-step sampling is the budgeted incomplete path (O(B k d) per
worker); full-triplet gradients through the checkpointed triple tile
scan are possible (triplet_stats is differentiable) but cost an
O(m^3) recompute per step — the budget regime is the framework's own
recommendation at production block sizes [SURVEY §1.2 item 4].
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tuplewise_tpu.utils.compat import sharded_take
from tuplewise_tpu.ops.kernels import get_kernel
from tuplewise_tpu.parallel.mesh import make_mesh
from tuplewise_tpu.utils.rng import fold, root_key


@dataclasses.dataclass(frozen=True)
class TripletTrainConfig:
    """Triplet-SGD hyperparameters [SURVEY §5.9 config discipline]."""

    kernel: str = "triplet_hinge"     # differentiable surrogate
    embed_dim: int = 8                # k: embedding width
    lr: float = 0.05
    steps: int = 100
    n_workers: int = 1
    repartition_every: int = 10
    triplets_per_worker: int = 4096   # B per worker per step
    # per-worker triplet-budget design, drawn ON DEVICE per step
    # (ops.device_design.draw_triplet_design_device — 3-key sort
    # dedup; "swr" reproduces the legacy draws bit-for-bit)
    triplet_design: str = "swr"
    scheme: str = "swor"
    seed: int = 0


def init_embed(dim: int, embed_dim: int, seed: int = 0) -> dict:
    """Linear embedding parameters W [d, k], scaled ~ orthonormal."""
    rng = np.random.default_rng(seed)
    return {"W": rng.standard_normal((dim, embed_dim)) / np.sqrt(dim)}


def _default_embedder(params):
    """Back-compat: a bare {"W": [d, k]} params dict means the linear
    embedding the r4 API trained (models.scorers.LinearEmbed)."""
    from tuplewise_tpu.models.scorers import LinearEmbed

    if "W" not in params:
        raise ValueError(
            "params carry no linear 'W' — pass the matching embedder= "
            "(models.scorers.MLPEmbed etc.) explicitly"
        )
    d, k = np.shape(params["W"])
    return LinearEmbed(dim=int(d), embed_dim=int(k))


@functools.lru_cache(maxsize=8)
def _compiled_triplet_trainer(embedder, cfg, mesh, n1, n2):
    """Compiled chunk program (same caching/chunking contract as
    pairwise_sgd._compiled_trainer: keys fold from absolute step
    indices, so chunked runs reproduce unchunked bit-for-bit).
    ``embedder`` is any frozen-dataclass plugin with
    ``apply(params, X, xp)`` — the scorer discipline of the pairwise
    learner applied to embeddings [VERDICT r4 next #9]."""
    from tuplewise_tpu.parallel.device_partition import draw_blocks as _draw

    kernel = get_kernel(cfg.kernel)
    N = int(np.prod(mesh.devices.shape))
    axes = tuple(mesh.axis_names)
    shard_blocks = NamedSharding(mesh, P(axes))
    m1, m2 = n1 // N, n2 // N
    root = root_key(cfg.seed)
    B = cfg.triplets_per_worker

    def sgd_body(params, a, b, key):
        """One worker's step on its [1, m, d] blocks."""
        from tuplewise_tpu.parallel.device_partition import (
            linear_shard_index,
        )

        kk = fold(key, "triplet_sample", linear_shard_index(axes))

        def loss_fn(p):
            from tuplewise_tpu.ops.device_design import (
                draw_triplet_design_device,
            )

            ea = embedder.apply(p, a[0], jnp)
            eb = embedder.apply(p, b[0], jnp)
            i, j, n, w = draw_triplet_design_device(
                kk, m1, m2, B, cfg.triplet_design
            )
            vals = kernel.triplet_values(ea[i], ea[j], eb[n], jnp)
            # max(., 1): an exact small-G bernoulli draw can realize an
            # EMPTY design — a zero-weight step, not NaN
            return jnp.sum(vals * w) / jnp.maximum(jnp.sum(w), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(lambda g: lax.pmean(g, axes), grads)
        loss = lax.pmean(loss, axes)
        new_params = jax.tree.map(
            lambda p, g: p - cfg.lr * g, params, grads
        )
        return new_params, loss

    sgd_smap = jax.shard_map(
        sgd_body, mesh=mesh,
        in_specs=(P(), P(axes), P(axes), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def draw(key, n, m):
        return _draw(key, n, N, cfg.scheme, m=m)

    # NOTE: step_fn/refresh/chunk_fn mirror pairwise_sgd._compiled_trainer
    # token-for-token — the chunk-boundary key-fold discipline (refresh
    # only at t > t0; r0 = t0 - t0 % n_r startup regather) lives in BOTH
    # trainers. Any change to that contract must be applied to both;
    # the chunking-invariance tests of each trainer pin the discipline.
    def step_fn(carry, t, t0, Xc, Xo):
        params, Ab, Bb = carry
        kt = fold(root, "step", t)

        def refresh(_):
            kr = fold(root, "repartition", t)
            k1, k2 = jax.random.split(kr)
            return (
                sharded_take(Xc, draw(k1, n1, m1), shard_blocks),
                sharded_take(Xo, draw(k2, n2, m2), shard_blocks),
            )

        Ab, Bb = lax.cond(
            (t % cfg.repartition_every == 0) & (t > t0),
            refresh, lambda _: (Ab, Bb), None,
        )
        params, loss = sgd_smap(params, Ab, Bb, kt)
        return (params, Ab, Bb), loss

    def chunk_fn(params, Xc, Xo, t0, chunk_len):
        r0 = t0 - t0 % cfg.repartition_every
        kr = fold(root, "repartition", r0)
        k1, k2 = jax.random.split(kr)
        Ab = sharded_take(Xc, draw(k1, n1, m1), shard_blocks)
        Bb = sharded_take(Xo, draw(k2, n2, m2), shard_blocks)
        (params, _, _), losses = lax.scan(
            functools.partial(step_fn, t0=t0, Xc=Xc, Xo=Xo),
            (params, Ab, Bb), t0 + jnp.arange(chunk_len)
        )
        return params, losses

    return jax.jit(chunk_fn, static_argnums=4)


def train_triplet(
    params,
    X_class: np.ndarray,
    X_other: np.ndarray,
    cfg: TripletTrainConfig,
    mesh=None,
    eval_every: Optional[int] = None,
    eval_data=None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
    embedder=None,
    chaos=None,
    heal_retries: int = 2,
    retry_backoff_s: float = 0.05,
    tracer=None,
    metrics=None,
):
    """Distributed triplet SGD: anchors/positives from X_class (the
    target class), negatives from X_other. Returns (params, history);
    with ``eval_every`` + ``eval_data=(Xc_test, Xo_test)`` the history
    also carries the held-out triplet-accuracy curve (training runs in
    scan chunks between evaluations; keys fold from absolute step
    indices, so the chunked trajectory IS the unchunked one).

    ``embedder``: any frozen-dataclass plugin with
    ``apply(params, X, xp)`` (models.scorers.LinearEmbed / MLPEmbed)
    [VERDICT r4 next #9]; None infers the linear embedding from a bare
    {"W": [d, k]} params dict, so the r4 call sites run unchanged.

    Checkpoint/resume [SURVEY §5.5, same contract as train_pairwise]:
    with ``checkpoint_path``, params + loss history + the accuracy
    curve persist every ``checkpoint_every`` steps (default: at eval
    boundaries, or once at the end without eval_every), and an
    existing checkpoint resumes from its saved step EXACTLY (cfg.steps
    may grow across resumes; every other field must match). Scan
    chunks realign to ABSOLUTE eval/checkpoint boundaries, so a resume
    from any saved step evaluates at the same steps as the straight
    run.

    Elastic re-sharding + chaos [ISSUE 4, same contract as
    train_pairwise]: a failed chunk heals through
    ``parallel.self_heal.MeshHealer`` — probe, rebuild the mesh at the
    SAME logical width from the spare-device pool, re-place data and
    params, retry with bounded jittered backoff. ``chaos`` fires at the
    ``train_step`` / ``checkpoint`` hook points. ``tracer`` [ISSUE 6]:
    scan chunks and checkpoint saves become ``train.chunk`` /
    ``train.checkpoint`` spans, same taxonomy as ``train_pairwise``."""
    kernel = get_kernel(cfg.kernel)
    if kernel.kind != "triplet":
        raise ValueError(
            f"triplet learner needs a degree-3 kernel, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    if kernel.name == "triplet_indicator":
        raise ValueError(
            "the indicator has zero gradient almost everywhere; train "
            "with 'triplet_hinge' and evaluate with "
            "evaluate_triplet_accuracy"
        )
    mesh = mesh if mesh is not None else make_mesh(cfg.n_workers)
    N = int(np.prod(mesh.devices.shape))
    n1, n2 = len(X_class), len(X_other)
    if min(n1 // N, n2 // N) < 2:
        raise ValueError(f"n=({n1},{n2}) too small for {N} workers")

    from tuplewise_tpu.parallel.device_partition import pad_put

    Xc, Xo = pad_put(X_class, mesh), pad_put(X_other, mesh)
    replicated = NamedSharding(mesh, P())
    params = jax.device_put(
        jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params),
        replicated,
    )
    if embedder is None:
        embedder = _default_embedder(params)
    run_chunk = _compiled_triplet_trainer(
        embedder, dataclasses.replace(cfg, steps=0), mesh, n1, n2
    )

    from tuplewise_tpu.utils.checkpoint import (
        resume_progress, save_checkpoint,
    )

    # embedder identity is part of the checkpoint contract: resuming a
    # linear run with an MLP embedder (or vice versa) must fail loudly
    # as a config mismatch, not as a shape error deep in device_put.
    # The inferred-linear default keeps the r4 config schema (no
    # 'embedder' key), so pre-r5 linear checkpoints still resume.
    from tuplewise_tpu.models.scorers import LinearEmbed

    ck_config = dataclasses.asdict(cfg)
    if not isinstance(embedder, LinearEmbed):
        ck_config["embedder"] = repr(embedder)
    start, ck = resume_progress(
        checkpoint_path, ck_config,
        progress_key="steps", requested=cfg.steps,
    )
    loss_parts, curve_steps, curve_acc = [], [], []
    if ck is not None:
        loss_parts = [ck["extra"]["loss"]]
        # the curve survives the crash too — a resumed run must not
        # silently truncate the committed accuracy history
        curve_steps = list(ck["extra"].get("curve_steps", []))
        curve_acc = list(ck["extra"].get("curve_acc", []))
        params = jax.device_put(
            {k: jnp.asarray(v, jnp.float32)
             for k, v in ck["params"].items()},
            replicated,
        )

    ckpt_every = checkpoint_every or eval_every

    def next_boundary(t):
        """Nearest ABSOLUTE eval/checkpoint boundary past t — chunks
        realign after any resume, so eval steps match the straight
        run's regardless of where the checkpoint landed."""
        nxt = cfg.steps
        for e in (eval_every, ckpt_every):
            if e:
                nxt = min(nxt, t - t % e + e)
        return nxt

    from tuplewise_tpu.obs.tracing import maybe_span

    def save(step):
        with maybe_span(tracer, "train.checkpoint", step=step):
            save_checkpoint(
                checkpoint_path,
                step=step,
                params=jax.tree.map(np.asarray, params),
                extra={
                    "loss": np.concatenate(loss_parts),
                    "curve_steps": np.asarray(curve_steps),
                    "curve_acc": np.asarray(curve_acc),
                },
                config=ck_config,
            )
        if chaos is not None:
            # durable-state preemption point ('sigkill' dies here)
            chaos.fire("checkpoint")

    # ---- elastic heal-and-retry around each chunk [ISSUE 4] ---------- #
    from tuplewise_tpu.parallel.self_heal import Backoff, MeshHealer

    healer = None
    if heal_retries:
        healer = MeshHealer(
            mesh, fixed_width=N, pool=list(jax.devices()), chaos=chaos,
            backoff=Backoff(base_s=retry_backoff_s, seed=cfg.seed),
            metrics=metrics, tracer=tracer)
    g_step = None
    if metrics is not None:
        g_step = metrics.gauge("train_step")
        metrics.gauge("mesh_width").set(N)

    def on_heal(h):
        nonlocal mesh, replicated, Xc, Xo, params, run_chunk
        mesh = h.mesh
        replicated = NamedSharding(mesh, P())
        Xc, Xo = pad_put(X_class, mesh), pad_put(X_other, mesh)
        params = jax.device_put(jax.tree.map(np.asarray, params),
                                replicated)
        run_chunk = _compiled_triplet_trainer(
            embedder, dataclasses.replace(cfg, steps=0), mesh, n1, n2)

    t0 = start
    while t0 < cfg.steps:
        t1 = next_boundary(t0)

        def attempt(t0=t0, t1=t1):
            if chaos is not None:
                chaos.fire("train_step")
            return run_chunk(params, Xc, Xo, jnp.asarray(t0, jnp.int32),
                             t1 - t0)

        with maybe_span(tracer, "train.chunk", step=t0, steps=t1 - t0):
            if healer is not None:
                params, losses = healer.run(attempt,
                                            retries=heal_retries,
                                            on_heal=on_heal)
            else:
                params, losses = attempt()
        loss_parts.append(np.asarray(losses))
        if g_step is not None:
            g_step.set(t1)
        if eval_every is not None and (
            t1 % eval_every == 0 or t1 == cfg.steps
        ):
            curve_steps.append(t1)
            curve_acc.append(
                evaluate_triplet_accuracy(params, *eval_data,
                                          embedder=embedder)
            )
        if checkpoint_path and (
            ckpt_every is None or t1 % ckpt_every == 0
            or t1 == cfg.steps
        ):
            save(t1)
        t0 = t1
    hist = {
        "loss": (np.concatenate(loss_parts) if loss_parts
                 else np.empty(0, np.float32)),
    }
    if eval_every is not None:
        hist["eval_steps"] = np.asarray(curve_steps)
        hist["test_acc"] = np.asarray(curve_acc)
    if healer is not None:
        hist["recovery"] = {
            "resumed_from": int(start),
            "reshard_events": healer.reshard_events,
            "retries_total": healer.retries_total,
            "mesh_workers": healer.n_workers,
        }
    return jax.tree.map(np.asarray, params), hist


@functools.lru_cache(maxsize=1)
def _eval_estimator():
    """ONE cached evaluator: a fresh Estimator re-jits its programs on
    every call (~1.6 s vs 0.08 s reused — a suite run makes ~500
    evaluations). impl="pallas": the distance factorization serves the
    complete statistic on TPU (XLA tiles elsewhere / custom kernels)."""
    from tuplewise_tpu.estimators.estimator import Estimator

    return Estimator("triplet_indicator", backend="jax", impl="pallas")


def evaluate_triplet_accuracy(
    params, X_class, X_other, *, n_triplets: Optional[int] = None,
    seed: int = 0, embedder=None,
) -> float:
    """Config 4's indicator statistic on the EMBEDDED data — the
    fraction of (i, j in class; k outside) relative-similarity
    constraints the learned metric satisfies. Complete by default
    (the Pallas distance factorization makes it cheap); pass
    n_triplets for the incomplete estimate at large n. ``embedder``
    defaults to the linear map a bare {"W"} params dict implies."""
    if embedder is None:
        embedder = _default_embedder(params)
    p = jax.tree.map(np.asarray, params)
    Ec = np.asarray(embedder.apply(p, np.asarray(X_class), np))
    Eo = np.asarray(embedder.apply(p, np.asarray(X_other), np))
    est = _eval_estimator()
    if n_triplets is None:
        return est.complete(Ec, Eo)
    return est.incomplete(Ec, Eo, n_pairs=n_triplets, seed=seed)
