"""Evaluation metrics [SURVEY §3 "Evaluation"].

Rank-based AUC (Mann-Whitney with midrank tie handling): an O(n log n)
oracle for the O(n1*n2) AUC U-statistic — by construction
``auc_score(s_pos, s_neg) == U_n(auc_kernel)`` exactly, which makes it a
strong independent correctness check for every pair-sum backend.
"""

from __future__ import annotations

import numpy as np


def auc_score(pos_scores: np.ndarray, neg_scores: np.ndarray) -> float:
    """AUC = P(s_pos > s_neg) + 0.5 P(s_pos = s_neg), via midranks."""
    pos = np.asarray(pos_scores).ravel()
    neg = np.asarray(neg_scores).ravel()
    n1, n2 = len(pos), len(neg)
    allv = np.concatenate([pos, neg])
    order = np.argsort(allv, kind="mergesort")
    ranks = np.empty(len(allv))
    ranks[order] = np.arange(1, len(allv) + 1)
    # midranks for ties
    sorted_v = allv[order]
    i = 0
    while i < len(sorted_v):
        j = i
        while j + 1 < len(sorted_v) and sorted_v[j + 1] == sorted_v[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = 0.5 * (i + 1 + j + 1)
        i = j + 1
    rank_sum_pos = ranks[:n1].sum()
    return float((rank_sum_pos - n1 * (n1 + 1) / 2.0) / (n1 * n2))
