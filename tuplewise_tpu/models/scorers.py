"""Scoring models s_theta for pairwise ranking [SURVEY §1.3].

The paper's experiments use a linear scorer; an MLP is included so the
learner generalizes beyond it. Models are pure-functional: parameters are
pytrees (dicts of arrays), ``apply(params, X, xp)`` works under both
NumPy (oracle) and JAX (jit/grad/vmap) — the same dual-namespace pattern
as the kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LinearScorer:
    """s(x) = x @ w + b."""

    dim: int

    def init(self, seed: int = 0) -> Params:
        rng = np.random.default_rng(seed)
        return {
            "w": rng.standard_normal(self.dim) / np.sqrt(self.dim),
            "b": np.zeros(()),
        }

    def apply(self, params: Params, X, xp) -> Any:
        return X @ params["w"] + params["b"]


@dataclasses.dataclass(frozen=True)
class MLPScorer:
    """Two-layer tanh MLP scorer: s(x) = v @ tanh(x @ W1 + b1) + c."""

    dim: int
    hidden: int = 32

    def init(self, seed: int = 0) -> Params:
        rng = np.random.default_rng(seed)
        return {
            "W1": rng.standard_normal((self.dim, self.hidden)) / np.sqrt(self.dim),
            "b1": np.zeros(self.hidden),
            "v": rng.standard_normal(self.hidden) / np.sqrt(self.hidden),
            "c": np.zeros(()),
        }

    def apply(self, params: Params, X, xp) -> Any:
        h = xp.tanh(X @ params["W1"] + params["b1"])
        return h @ params["v"] + params["c"]


def init_scorer(name: str, dim: int, seed: int = 0, **kw):
    scorer = {"linear": LinearScorer, "mlp": MLPScorer}[name](dim, **kw)
    return scorer, scorer.init(seed)


# --------------------------------------------------------------------- #
# Embedding models e_theta: R^d -> R^k for the triplet learner          #
# [SURVEY §1.3 learner generality; VERDICT r4 next #9]                  #
# --------------------------------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class LinearEmbed:
    """e(x) = x @ W — the paper's linear metric (Mahalanobis factor)."""

    dim: int
    embed_dim: int

    def init(self, seed: int = 0) -> Params:
        rng = np.random.default_rng(seed)
        return {
            "W": rng.standard_normal((self.dim, self.embed_dim))
            / np.sqrt(self.dim),
        }

    def apply(self, params: Params, X, xp) -> Any:
        return X @ params["W"]


@dataclasses.dataclass(frozen=True)
class MLPEmbed:
    """Two-layer tanh MLP embedding: e(x) = tanh(x @ W1 + b1) @ W2 —
    a NONLINEAR metric through the same budgeted triplet path; closes
    the Bayes-ceiling gap on tasks a linear projection cannot separate
    (e.g. radial class structure, RESULTS §6.5b)."""

    dim: int
    hidden: int = 32
    embed_dim: int = 2

    def init(self, seed: int = 0) -> Params:
        rng = np.random.default_rng(seed)
        return {
            "W1": rng.standard_normal((self.dim, self.hidden))
            / np.sqrt(self.dim),
            "b1": np.zeros(self.hidden),
            "W2": rng.standard_normal((self.hidden, self.embed_dim))
            / np.sqrt(self.hidden),
        }

    def apply(self, params: Params, X, xp) -> Any:
        return xp.tanh(X @ params["W1"] + params["b1"]) @ params["W2"]
