"""Multi-process launch: make the dcn mesh axis launchable, not just
modeled [SURVEY §5.8; VERDICT r2 next #8].

The 2-D (dcn x ici) ring primitives and the hierarchical mesh layout
are validated in-process on virtual devices; this module supplies the
missing entry point for REAL multi-host runs:

* :func:`initialize` wraps ``jax.distributed.initialize`` behind
  explicit arguments or ``TUPLEWISE_DIST_*`` environment flags, so a
  launcher (mpirun / k8s indexed jobs / manual shells) can bring up the
  process group without code changes;
* :func:`global_mesh` builds the mesh from the PROCESS topology after
  initialization: the leading ("dcn") axis enumerates processes, the
  trailing ("w") axis the devices within each process — exactly the
  layout ring_pair_stats_2d keeps block rotation on ICI for.

On a single process both degrade gracefully: ``initialize`` is a no-op
without flags, and ``global_mesh`` returns the local 1-D or 2-D mesh.
A real 2-process CPU smoke test lives in tests/test_distributed.py
(subprocesses coordinate over localhost; the complete-U ring value must
match the single-process oracle bit-for-bit in f32).
"""

from __future__ import annotations

import os
from typing import Optional

_ENV_PREFIX = "TUPLEWISE_DIST_"


def dist_env() -> dict:
    """The TUPLEWISE_DIST_* launch flags present in the environment:
    COORDINATOR (host:port), NUM_PROCESSES, PROCESS_ID."""
    out = {}
    for key, cast in (("COORDINATOR", str), ("NUM_PROCESSES", int),
                      ("PROCESS_ID", int)):
        val = os.environ.get(_ENV_PREFIX + key)
        if val is not None:
            out[key.lower()] = cast(val)
    return out


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    retries: int = 0,
    retry_backoff_s: float = 0.5,
    chaos=None,
) -> bool:
    """Bring up the JAX process group; returns True when distributed
    mode is active.

    Explicit arguments win; otherwise the TUPLEWISE_DIST_* environment
    flags apply; with neither, this is a no-op (single-process mode) —
    the flag-gating of VERDICT r2 next #8. Must run before any jax
    computation, like jax.distributed.initialize itself.

    ``retries`` [ISSUE 4]: bring-up on a preempted-and-restarted pod is
    racy — the coordinator may not be listening yet when a restarted
    worker comes back. Failed initialization retries with the shared
    bounded jittered backoff (``parallel.self_heal.Backoff``) before
    surfacing the error. ``chaos`` fires the ``dist_init`` hook before
    each attempt (deterministic bring-up-failure injection in tests).
    """
    env = dist_env()
    coordinator_address = coordinator_address or env.get("coordinator")
    if num_processes is None:
        num_processes = env.get("num_processes")
    if process_id is None:
        process_id = env.get("process_id")
    if (coordinator_address is None and num_processes is None
            and process_id is None):
        return False   # nothing set anywhere: single-process mode
    if not (coordinator_address and num_processes is not None
            and process_id is not None):
        raise ValueError(
            "distributed launch needs coordinator_address, num_processes "
            f"AND process_id (got {coordinator_address!r}, "
            f"{num_processes!r}, {process_id!r}); set all three "
            f"{_ENV_PREFIX}* flags or pass them explicitly"
        )
    import jax

    from tuplewise_tpu.parallel.self_heal import Backoff

    backoff = Backoff(base_s=retry_backoff_s, cap_s=10.0,
                      seed=int(process_id))
    attempt = 0
    while True:
        try:
            if chaos is not None:
                chaos.fire("dist_init")
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=int(num_processes),
                process_id=int(process_id),
            )
            return True
        except Exception:
            attempt += 1
            if attempt > retries:
                raise
            backoff.sleep(attempt)


def global_mesh():
    """Device mesh from the process topology.

    Multi-process: a 2-D (dcn, w) mesh with one dcn row per process —
    jax.devices() orders devices by process index, so consecutive
    groups of ``local_device_count`` share a process and the trailing
    axis stays intra-host (ICI). Single-process: the local 1-D mesh
    (or 2-D when the caller wants one, via make_mesh_2d directly).
    """
    import jax

    from tuplewise_tpu.parallel.mesh import make_mesh, make_mesh_2d

    if jax.process_count() == 1:
        return make_mesh()
    return make_mesh_2d(jax.process_count(), jax.local_device_count())
