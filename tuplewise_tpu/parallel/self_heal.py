"""Elastic mesh self-healing — shared by serving AND the batch path
[ISSUE 4].

PR 3 taught the serving index to survive device loss with a private
recovery loop (probe → rebuild the mesh over survivors → re-place the
host-authoritative data → bounded backoff → retry). The batch path —
SGD trainers, the mesh Monte-Carlo driver, the Estimator itself — needs
the identical protocol, so this module factors it out:

* :class:`Backoff` — ONE bounded-exponential-backoff implementation
  (with deterministic seeded jitter so synchronized retry storms
  de-correlate), replacing the ad-hoc ``sleep(min(base * 2**k, cap))``
  the serving index carried privately.
* :class:`MeshHealer` — owns the mutable mesh reference plus the
  recovery counters (``reshard_events`` / ``shard_retries_total`` /
  ``recovery_time_s``, the same metric names the serving exit summary
  and ``bench.py --chaos`` report). ``run(fn)`` executes a mesh
  computation with the full heal-and-retry protocol around it.

Two reshard policies, chosen by who can tolerate a width change:

* **shrink** (``fixed_width=None``, the serving index): rebuild over
  the survivors of the CURRENT mesh. Counting is additive over any
  partition, so sharded counts stay bit-identical at any width.
* **fixed width** (``fixed_width=N``, trainers / mesh Monte-Carlo):
  the logical worker count is part of the experiment's semantics
  (every PRNG key folds a shard index; block sizes are n // N), so a
  reshard must KEEP the width — lost slots are backfilled from the
  spare-device ``pool``. Results are then bit-identical by
  construction: values depend on (rep, step, logical shard index),
  never on which physical chip computed them. When the pool can no
  longer sustain the width, :class:`HealExhaustedError` is raised —
  the job falls back to checkpoint/resume on a healthy pool rather
  than silently continuing a DIFFERENT experiment at a smaller N.

A ``MeshHealer(mesh=None)`` degrades to retry-with-backoff only (no
probe, no reshard) — the non-mesh backends use it so every batch path
shares one retry discipline.

jax is imported lazily (inside methods), keeping
``tuplewise_tpu.parallel`` importable for numpy-only use.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import numpy as np


class HealExhaustedError(RuntimeError):
    """The device pool can no longer sustain the required mesh width —
    resume the job from its checkpoint on a healthy pool instead."""


class Backoff:
    """Bounded exponential backoff with deterministic seeded jitter.

    ``delay_s(attempt)`` (1-based) is ``base_s * 2**(attempt-1)``
    capped at ``cap_s``, stretched by up to ``jitter`` fraction drawn
    from a seeded generator — deterministic per instance, decorrelated
    across instances with different seeds (retry storms from many
    workers must not re-synchronize on the failed resource).
    """

    def __init__(self, base_s: float = 0.02, cap_s: float = 1.0,
                 jitter: float = 0.25, seed: int = 0):
        if base_s < 0 or cap_s < 0:
            raise ValueError(f"backoff times must be >= 0: "
                             f"base_s={base_s}, cap_s={cap_s}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self._rng = np.random.default_rng(seed)

    def delay_s(self, attempt: int) -> float:
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        d = min(self.base_s * (2.0 ** (attempt - 1)), self.cap_s)
        if self.jitter:
            d *= 1.0 + self.jitter * float(self._rng.random())
        return d

    def sleep(self, attempt: int) -> None:
        time.sleep(self.delay_s(attempt))


class MeshHealer:
    """Probe → reshard over survivors → re-place → backoff → retry.

    Args:
      mesh: the mesh to heal, or None for retry-with-backoff only.
      fixed_width: keep the mesh at exactly this many workers across
        reshards, backfilling lost slots from ``pool`` (trainers and
        Monte-Carlo, whose semantics bake in the logical width); None
        shrinks to the survivors (the serving index, whose counts are
        width-invariant).
      pool: devices eligible for rebuilds (default: the mesh's own
        devices — shrink-only). Pass ``jax.devices()`` to let a
        reshard use spare chips outside the original mesh.
      chaos: a ``testing.chaos.FaultInjector`` whose ``take_dropped()``
        supplies the dead-worker set a scheduled fault declared, in
        place of a real probe (deterministic failure topology on a
        healthy CPU mesh).
      probe_timeout_s: wall-clock bound on the health probe (a hung
        device must not hang the healer).
      metrics: a ``utils.profiling.MetricsRegistry`` to record
        ``reshard_events`` / ``shard_retries_total`` /
        ``recovery_time_s`` into (create-or-return, so the serving
        index shares its registry); None = a private one.
      backoff: a :class:`Backoff`; None = defaults.
      tracer: an ``obs.tracing.Tracer`` — each heal round becomes a
        ``heal.round`` span (probe/reshard children) in whatever trace
        triggered the recovery [ISSUE 6]; None = no spans.
      flight: an ``obs.flight.FlightRecorder`` — every heal round and
        exhaustion records a lifecycle event with the correlating
        trace id; None = no events.
    """

    def __init__(self, mesh=None, *, fixed_width: Optional[int] = None,
                 pool: Optional[Sequence] = None, chaos=None,
                 probe_timeout_s: float = 5.0, metrics=None,
                 backoff: Optional[Backoff] = None, tracer=None,
                 flight=None):
        from tuplewise_tpu.utils.profiling import MetricsRegistry

        if fixed_width is not None and mesh is None:
            raise ValueError("fixed_width needs a mesh to keep at width")
        self.mesh = mesh
        self.fixed_width = fixed_width
        self.chaos = chaos
        self.probe_timeout_s = probe_timeout_s
        self.backoff = backoff if backoff is not None else Backoff()
        self.tracer = tracer
        self.flight = flight
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._c_reshard = self.metrics.counter("reshard_events")
        self._c_retries = self.metrics.counter("shard_retries_total")
        self._h_recovery = self.metrics.histogram("recovery_time_s")
        if mesh is not None:
            devices = list(mesh.devices.flat)
            self._pool = list(pool) if pool is not None else devices
            if fixed_width is not None and len(devices) != fixed_width:
                raise ValueError(
                    f"fixed_width={fixed_width} but the mesh has "
                    f"{len(devices)} devices")
        else:
            self._pool = []

    # ------------------------------------------------------------------ #
    @property
    def n_workers(self) -> Optional[int]:
        if self.mesh is None:
            return None
        return int(np.prod(self.mesh.devices.shape))

    @property
    def reshard_events(self) -> int:
        return self._c_reshard.value

    @property
    def retries_total(self) -> int:
        return self._c_retries.value

    # ------------------------------------------------------------------ #
    def _probe_dropped(self) -> tuple:
        """Dead-worker set: the chaos schedule's declared topology when
        one is pending, else a real (wall-clock-bounded) mesh probe."""
        dropped = self.chaos.take_dropped() if self.chaos is not None \
            else None
        if dropped is not None:
            return tuple(dropped)
        from tuplewise_tpu.parallel.faults import detect_dropped_workers

        try:
            return detect_dropped_workers(
                self.mesh, timeout_s=self.probe_timeout_s)
        except Exception:
            # the detector itself failed (all devices unreachable, or
            # the probe machinery died): retry on the same mesh — if
            # the fault was transient the retry succeeds, else the
            # retry bound surfaces the original error
            return ()

    def _reshard(self) -> bool:
        """Probe and rebuild the mesh; True when the mesh changed.
        Raises :class:`HealExhaustedError` when nothing is left to
        rebuild over (or the pool can't sustain ``fixed_width``)."""
        from tuplewise_tpu.parallel.mesh import make_mesh

        dropped = self._probe_dropped()
        if not dropped:
            return False
        dead = {self.mesh.devices.flat[int(w)] for w in dropped
                if 0 <= int(w) < self.mesh.devices.size}
        self._pool = [d for d in self._pool if d not in dead]
        if self.fixed_width is not None:
            if len(self._pool) < self.fixed_width:
                raise HealExhaustedError(
                    f"device pool ({len(self._pool)} alive) can no "
                    f"longer sustain the mesh width {self.fixed_width}; "
                    "resume from the checkpoint on a healthy pool")
            new_devices = self._pool[: self.fixed_width]
        else:
            new_devices = [d for d in self.mesh.devices.flat
                           if d not in dead]
            if not new_devices:
                raise HealExhaustedError(
                    "every mesh device failed; nothing to reshard over")
        self.mesh = make_mesh(len(new_devices), devices=new_devices)
        return True

    def resize(self, width: int) -> bool:
        """Deliberate mesh re-width [ISSUE 11] — a control-plane
        actuation, not a recovery: rebuild the mesh at ``width``
        workers from the surviving device pool (growth uses the spare
        devices the pool holds beyond the current mesh; shrink keeps
        the pool's prefix, so a later grow restores the same devices).
        Returns True when the mesh changed; the CALLER re-places its
        device state, exactly as after ``heal``. Refused (False) for
        ``fixed_width`` policies (the width is part of the experiment's
        semantics there), mesh-less healers, out-of-pool widths, and
        no-op widths. Counts as a ``reshard_events`` and records a
        ``mesh_resize`` flight event."""
        from tuplewise_tpu.parallel.mesh import make_mesh

        if self.mesh is None or self.fixed_width is not None:
            return False
        width = int(width)
        old = self.n_workers
        if width < 1 or width > len(self._pool) or width == old:
            return False
        self.mesh = make_mesh(width, devices=self._pool[:width])
        self._c_reshard.inc()
        if self.flight is not None:
            self.flight.record("mesh_resize", from_width=old,
                               to_width=width)
        return True

    def heal(self, attempt: int,
             on_heal: Optional[Callable] = None) -> bool:
        """One recovery round: probe/reshard, let the caller re-place
        (``on_heal(self)`` — device buffers may be torn even when the
        mesh itself survived, so re-placement is unconditional), record
        the recovery, back off. Returns True when the mesh changed."""
        from tuplewise_tpu.obs.tracing import maybe_span

        changed = False
        if self.mesh is not None:
            t0 = time.perf_counter()
            with maybe_span(self.tracer, "heal.round", attempt=attempt):
                with maybe_span(self.tracer, "heal.probe_reshard"):
                    changed = self._reshard()
                if on_heal is not None:
                    on_heal(self)
            self._c_reshard.inc()
            dt = time.perf_counter() - t0
            self._h_recovery.observe(dt)
            if self.flight is not None:
                self.flight.record(
                    "heal", attempt=attempt, mesh_changed=changed,
                    mesh_width=self.n_workers, recovery_s=dt)
        elif on_heal is not None:
            on_heal(self)
        self.backoff.sleep(attempt)
        return changed

    def run(self, fn: Callable[[], object], *, retries: int = 3,
            on_heal: Optional[Callable] = None):
        """Execute ``fn()`` under the heal-and-retry protocol: on
        failure, heal (probe → reshard → ``on_heal`` re-placement →
        backoff) and retry, at most ``retries`` times — persistent
        failure re-raises rather than spinning. ``HealExhaustedError``
        propagates immediately (retrying cannot help)."""
        attempt = 0
        while True:
            try:
                return fn()
            except HealExhaustedError:
                raise
            except Exception:
                attempt += 1
                if attempt > retries:
                    raise
                self._c_retries.inc()
                self.heal(attempt, on_heal=on_heal)
