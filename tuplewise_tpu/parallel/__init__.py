# NOTE: tuplewise_tpu.parallel.mesh is intentionally NOT imported here —
# it imports jax at module top, and the numpy oracle path must stay
# importable without jax. Use `from tuplewise_tpu.parallel.mesh import
# make_mesh, shard_axis_name` directly.
from tuplewise_tpu.parallel.faults import (
    alive_mask,
    detect_dropped_workers,
    normalize_dropped,
    run_with_fault_tolerance,
    sample_failures,
    survivors,
)
from tuplewise_tpu.parallel.partition import (
    draw_pair_design,
    draw_triplet_design,
    partition_indices,
    partition_two_sample,
)
from tuplewise_tpu.parallel.self_heal import (
    Backoff,
    HealExhaustedError,
    MeshHealer,
)

# tuplewise_tpu.parallel.distributed (multi-process launch) is likewise
# not imported here: it is jax-adjacent and must run BEFORE jax init.

__all__ = [
    "Backoff",
    "HealExhaustedError",
    "MeshHealer",
    "alive_mask",
    "detect_dropped_workers",
    "draw_pair_design",
    "draw_triplet_design",
    "normalize_dropped",
    "run_with_fault_tolerance",
    "partition_indices",
    "partition_two_sample",
    "sample_failures",
    "survivors",
]
