"""L2 — partitioner / repartitioner.

Splits sample indices across N workers and reshuffles them between rounds
[SURVEY §2 L2, §3 "Partitioner / repartitioner"]. Schemes analyzed by the
paper [SURVEY §1.2]:

* ``"swor"`` — sampling WITHOUT replacement: one global permutation cut
  into N equal blocks (remainder dropped so shapes stay static for XLA).
* ``"swr"``  — sampling WITH replacement: each worker draws its block
  i.i.d. uniformly from the full index range.
* **proportional** (stratified) two-sample partitioning: each worker gets
  an equal share of *each class*, which is what keeps the local-average
  estimator well-defined and unbiased for two-sample statistics.

These run on the host (NumPy): in the reference's in-process simulation
they ARE the communication layer; in the TPU build they only decide the
initial packing, while steady-state repartitioning happens on-device via
`jax.random` permutations + XLA-inserted collectives
(tuplewise_tpu.backends.mesh_backend).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def partition_indices(
    n: int,
    n_workers: int,
    rng: np.random.Generator,
    scheme: str = "swor",
) -> np.ndarray:
    """Partition ``range(n)`` into ``n_workers`` equal blocks.

    Returns an int array of shape [n_workers, n // n_workers]; with
    ``"swor"`` the blocks are disjoint (remainder indices dropped),
    with ``"swr"`` each entry is an i.i.d. uniform draw.
    """
    per = n // n_workers
    if per == 0:
        raise ValueError(f"n={n} too small for {n_workers} workers")
    if scheme == "swor":
        perm = rng.permutation(n)[: per * n_workers]
        return perm.reshape(n_workers, per)
    if scheme == "swr":
        return rng.integers(0, n, size=(n_workers, per))
    raise ValueError(f"unknown partition scheme {scheme!r}")


def partition_two_sample(
    n_pos: int,
    n_neg: int,
    n_workers: int,
    rng: np.random.Generator,
    scheme: str = "swor",
) -> Tuple[np.ndarray, np.ndarray]:
    """Proportional (class-stratified) two-sample partition.

    Each worker receives ``n_pos // N`` positives and ``n_neg // N``
    negatives — the stratification required for unbiased local-average
    estimation of two-sample U-statistics [SURVEY §1.2 item 2].

    Returns (pos_idx [N, n_pos//N], neg_idx [N, n_neg//N]).
    """
    return (
        partition_indices(n_pos, n_workers, rng, scheme),
        partition_indices(n_neg, n_workers, rng, scheme),
    )


# ---------------------------------------------------------------------------
# Incomplete-U pair sampling designs [SURVEY §1.1 incomplete; PAPERS.md:6]
# ---------------------------------------------------------------------------

def _distinct_uniform(
    rng: np.random.Generator, grid: int, size: int
) -> np.ndarray:
    """``size`` distinct uniform draws from range(grid) without ever
    materializing the grid: exact permutation-based choice for small
    grids, draw-and-dedup (uniform over distinct subsets) for huge ones."""
    if size > grid:
        raise ValueError(f"cannot draw {size} distinct tuples from a "
                         f"grid of {grid}")
    if grid <= max(4 * size, 1 << 20):
        return rng.choice(grid, size=size, replace=False)
    out = np.unique(rng.integers(0, grid, size=size + size // 8 + 16))
    while len(out) < size:
        extra = rng.integers(0, grid, size=size // 4 + 16)
        out = np.unique(np.concatenate([out, extra]))
    rng.shuffle(out)
    return out[:size]


def design_pad_len(n_pairs: int, design: str) -> int:
    """Fixed buffer length for a design's index/weight arrays — the
    SINGLE definition shared by every consumer that pads realized
    draws to a static shape (harness.variance, harness.mesh_mc,
    ops.device_design). swr/swor realize exactly n_pairs; bernoulli's
    Binomial size gets 8-sigma headroom (truncation ~1e-15/draw), so
    one compile covers every rep."""
    if design == "bernoulli":
        import math

        return n_pairs + 8 * int(math.ceil(math.sqrt(n_pairs))) + 8
    return n_pairs


def draw_pair_design(
    rng: np.random.Generator,
    n1: int,
    n2: int,
    n_pairs: int,
    design: str = "swr",
    *,
    one_sample: bool = False,
):
    """(i, j) index arrays sampling the n1 x n2 tuple grid.

    Designs (incomplete U-statistics, Clemencon/Colin/Bellet):
      "swr"       — n_pairs i.i.d. uniform draws with replacement;
      "swor"      — n_pairs DISTINCT tuples;
      "bernoulli" — every tuple kept independently with probability
                    n_pairs/grid, simulated exactly: realized sample
                    size ~ Binomial(grid, p), then a uniform distinct
                    sample of that size (floored at 1 so the estimator
                    stays defined).

    one_sample: the grid is the OFF-DIAGONAL of an (n1 x n1) grid,
    encoded with n2 = n1 - 1 columns; returned j is shifted past i so
    callers index the original array directly.
    """
    grid = n1 * n2
    if design == "swr":
        i = rng.integers(0, n1, size=n_pairs)
        j = rng.integers(0, n2, size=n_pairs)
    elif design in ("swor", "bernoulli"):
        if design == "bernoulli":
            p = n_pairs / grid
            if p > 1.0:
                raise ValueError(
                    f"bernoulli rate n_pairs/grid = {p:.3f} exceeds 1")
            size = max(1, int(rng.binomial(grid, p)))
        else:
            size = n_pairs
        lin = _distinct_uniform(rng, grid, size)
        i, j = lin // n2, lin % n2
    else:
        raise ValueError(
            f"unknown sampling design {design!r}; "
            "choose 'swr', 'swor', or 'bernoulli'"
        )
    if one_sample:
        j = np.where(j >= i, j + 1, j)
    return np.asarray(i), np.asarray(j)


def draw_triplet_design(
    rng: np.random.Generator,
    n1: int,
    n2: int,
    n_tuples: int,
    design: str = "swr",
):
    """(i, j, k) index arrays sampling the degree-3 tuple grid
    {(i, j, k) : i, j in range(n1), i != j, k in range(n2)} — anchor /
    positive from the first sample, negative from the second
    [SURVEY §1.1 degree-3; VERDICT r2 next #4].

    Same designs as :func:`draw_pair_design`; swor/bernoulli linearize
    the grid as ((i * (n1-1) + j') * n2 + k) with j' the off-diagonal
    column (j shifted past i), reusing the dedup sampler, so distinctness
    is exact over ordered (i, j, k) triples. The swr branch draws
    i, then shifted j, then k — the exact call sequence the NumPy
    backend always used, so seeds reproduce historical results.
    """
    if n1 < 2:
        raise ValueError(f"need n1 >= 2 anchors/positives, got {n1}")
    grid = n1 * (n1 - 1) * n2
    if design == "swr":
        i = rng.integers(0, n1, size=n_tuples)
        j = rng.integers(0, n1 - 1, size=n_tuples)
        j = np.where(j >= i, j + 1, j)
        k = rng.integers(0, n2, size=n_tuples)
        return np.asarray(i), np.asarray(j), np.asarray(k)
    if design not in ("swor", "bernoulli"):
        raise ValueError(
            f"unknown sampling design {design!r}; "
            "choose 'swr', 'swor', or 'bernoulli'"
        )
    if design == "bernoulli":
        p = n_tuples / grid
        if p > 1.0:
            raise ValueError(
                f"bernoulli rate n_tuples/grid = {p:.3f} exceeds 1")
        size = max(1, int(rng.binomial(grid, p)))
    else:
        size = n_tuples
    lin = _distinct_uniform(rng, grid, size)
    k = lin % n2
    rest = lin // n2
    i, jp = rest // (n1 - 1), rest % (n1 - 1)
    j = np.where(jp >= i, jp + 1, jp)
    return np.asarray(i), np.asarray(j), np.asarray(k)


# ---------------------------------------------------------------------------
# Packing for the device mesh: static [N, cap] blocks + validity masks
# ---------------------------------------------------------------------------

def pack_all(values: np.ndarray, n_workers: int):
    """Deterministically pack EVERY row into [N, cap, ...] + mask + ids.

    Keeps all n rows — cap = ceil(n / N), tail zero-padded with a
    zero mask — which is what complete (all-pairs) statistics need.
    Returns (packed, mask, ids) with ids = original row index (padding
    gets id -1, excluded by masks anyway).
    """
    n = len(values)
    cap = -(-n // n_workers)
    pad = n_workers * cap - n
    packed = np.concatenate(
        [values, np.zeros((pad,) + values.shape[1:], values.dtype)]
    ).reshape((n_workers, cap) + values.shape[1:])
    mask = np.concatenate(
        [np.ones(n), np.zeros(pad)]
    ).reshape(n_workers, cap)
    ids = np.concatenate(
        [np.arange(n), np.full(pad, -1)]
    ).astype(np.int32).reshape(n_workers, cap)
    return packed, mask, ids
