"""Mesh-parallel sorted-run counting — the serving index's shard layer.

The serving hot path needs, per query batch q, the integer counts

    less[i] = #{v in base : v <  q[i]}
    leq[i]  = #{v in base : v <= q[i]}

against a sorted base run. Counting is additive over ANY partition of
the multiset into sorted parts, so the base run can be split into one
contiguous slice per device: each shard binary-searches its slice and a
``lax.psum`` over the mesh axis sums the per-shard counts. Integer
sums are exact, so the sharded counts are BIT-IDENTICAL to the
single-host ``searchsorted`` at every mesh size — the online path gets
the batch ring's scaling (per-shard work + one reduction) without
touching the index's exactness contract.

Layout: ``place_base`` pads each slice to a power-of-two per-shard
bucket with +inf (finite scores sort below the padding, so insertion
indices are unchanged) and places the [S, cap] block one-row-per-device
via the mesh backend's row placement. The jitted count kernel is cached
per (mesh, cap, q_bucket), giving O(log n) distinct compiled shapes as
the base run grows through the bucket ladder — the same discipline as
the single-host index.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

_MIN_BUCKET = 256


def next_bucket(n: int, min_bucket: int = _MIN_BUCKET) -> int:
    b = min_bucket
    while b < n:
        b *= 2
    return b


def mesh_size(mesh) -> int:
    return int(np.prod(mesh.devices.shape))


def place_base(mesh, sorted_arr: np.ndarray, dtype) -> Tuple[object, int]:
    """Pad + place a sorted base run as [S, cap] contiguous slices.

    Returns (device_array, cap). Each row holds one sorted slice padded
    with +inf; rows are placed one-per-device via the mesh backend's
    row placement (the same NamedSharding the ring estimators use).
    """
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.backends.mesh_backend import row_sharding

    S = mesh_size(mesh)
    n = len(sorted_arr)
    per = -(-n // S) if n else 0       # ceil; 0 rows only when base empty
    cap = next_bucket(max(per, 1))
    out = np.full((S, cap), np.inf, dtype=dtype)
    for s in range(S):
        chunk = sorted_arr[s * per:(s + 1) * per]
        out[s, : len(chunk)] = chunk
    return jax.device_put(jnp.asarray(out), row_sharding(mesh)), cap


@functools.lru_cache(maxsize=None)
def sharded_count_fn(mesh, cap: int, q_bucket: int):
    """Jitted (base_shards [S, cap], queries [q_bucket]) -> (less, leq).

    Per-shard ``searchsorted`` against the local slice, psum'd over
    every mesh axis; outputs are replicated [q_bucket] int counts.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def body(b, q):
        # local slice arrives as [1, cap]; +inf padding never shifts the
        # insertion index of a finite query
        less = jnp.searchsorted(b[0], q, side="left")
        leq = jnp.searchsorted(b[0], q, side="right")
        return lax.psum(less, axes), lax.psum(leq, axes)

    @jax.jit
    def f(base_sh, q):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axes), P()), out_specs=(P(), P()),
            check_vma=False,
        )(base_sh, q)

    return f


def sharded_counts(mesh, base_dev, cap: int, q: np.ndarray,
                   dtype, chaos=None) -> Tuple[np.ndarray, np.ndarray]:
    """(less, leq) int64 counts of queries against the placed base run.

    ``chaos`` (a ``testing.chaos.FaultInjector``) fires the
    ``sharded_count`` hook before the device call — a scheduled fault
    raises here exactly where a dead mesh device would, so the serving
    index's self-healing retry path is exercised deterministically
    [ISSUE 3].
    """
    if chaos is not None:
        chaos.fire("sharded_count")
    qb = next_bucket(len(q))
    q_p = np.zeros(qb, dtype=dtype)
    q_p[: len(q)] = q
    less, leq = sharded_count_fn(mesh, cap, qb)(base_dev, q_p)
    return (np.asarray(less)[: len(q)].astype(np.int64),
            np.asarray(leq)[: len(q)].astype(np.int64))
