"""Mesh-parallel sorted-run counting — the serving index's shard layer.

The serving hot path needs, per query batch q, the integer counts

    less[i] = #{v in base : v <  q[i]}
    leq[i]  = #{v in base : v <= q[i]}

against a sorted base run. Counting is additive over ANY partition of
the multiset into sorted parts, so the base run can be split into one
contiguous slice per device: each shard binary-searches its slice and a
``lax.psum`` over the mesh axis sums the per-shard counts. Integer
sums are exact, so the sharded counts are BIT-IDENTICAL to the
single-host ``searchsorted`` at every mesh size — the online path gets
the batch ring's scaling (per-shard work + one reduction) without
touching the index's exactness contract.

Layout: ``place_base`` pads each slice to a power-of-two per-shard
bucket with +inf (finite scores sort below the padding, so insertion
indices are unchanged) and places the [S, cap] block one-row-per-device
via the mesh backend's row placement. The jitted count kernel is cached
per (mesh, cap, q_bucket), giving O(log n) distinct compiled shapes as
the base run grows through the bucket ladder — the same discipline as
the single-host index.

**Delta runs** [ISSUE 5]: additivity extends to any NUMBER of sorted
runs, so the index's delta-compaction mode places a small sorted delta
run next to the base and counts ``base + delta`` in ONE jitted call
under ONE psum (``sharded_multi_count_fn``) — shipping O(buffer)
bytes per minor compaction instead of re-placing the O(n) base. The
index keeps the delta CONSOLIDATED (one run), so compiled shapes
follow the two bucket ladders, never a transient run count.

**On-mesh major merge** [ISSUE 5]: folding the deltas back into the
base never round-trips through the host. The host (authoritative for
the runs) computes a merge *plan* — for each output shard, the
contiguous base-rank and delta-rank windows whose union is exactly its
slice of the merged run (any contiguous rank range of a two-way merge
is the merge of contiguous ranges of the inputs) — and the jitted
kernel executes it: each shard ``all_gather``s the (small) delta
blocks, receives its base-boundary overlap from its mesh NEIGHBORS via
two ``lax.ppermute`` block exchanges, selects its windows, and sorts
them into its output row. Interconnect traffic is O(Σ|deltas| +
per-shard block) per link; host→device traffic is ZERO. The plan is
valid when every output shard's base window lies within one hop of its
own slice (always true once the base dominates the deltas — the
steady state the trigger guarantees); otherwise the caller falls back
to the host merge + full re-placement.

``place_base`` also accounts every host→device byte it ships
(``bytes_h2d``) and — when the bucket ladder's (per, cap) geometry is
unchanged — re-ships only the rows whose content actually changed,
reassembling the block from the surviving per-device shards
(``bytes_h2d_saved`` counts what the naive full re-ship would have
cost) [ISSUE 5 satellite].

**Tenant axis** [ISSUE 8]: the bucket ladder generalizes to a FLEET of
independent sorted runs — thousands of per-tenant statistics
multiplexed over one mesh. ``place_tenant_pack`` packs every tenant's
sorted run into ONE shared padded ``[S, T_bucket, cap]`` device buffer
(tenant t's slice s in row ``[s, t]``, +inf padded; per-tenant lengths
live on the host — the +inf padding makes device-side length masks
unnecessary for counting, because a finite query's insertion index
never crosses the padding). ``tenant_count_fn`` is the tenant-axis
count kernel: a vmapped per-row ``searchsorted`` over BOTH class
packs and both query blocks under ONE psum, so one jitted call serves
a whole coalesced batch of tenants' queries. Compile shapes follow
the ``(T_bucket, cap, q_bucket)`` ladder — powers of two in each axis
— never the live tenant count or the batch's tenant mix.

**Dirty-row pack placement** [ISSUE 9]: the ``place_base`` prev-trick
generalized to the tenant axis. A fleet re-place used to ship the
whole ``[S, T_bucket, cap]`` block even when ONE tenant of 256
compacted. ``place_tenant_pack(prev=..., dirty=...)`` keeps the
resident per-device shards and ships only the dirty tenants' rows: a
small ``[db, cap]`` block per device is scattered into the shard at
the dirty slots (a jitted ``.at[0, idx].set(..., mode="drop")`` —
out-of-range padding indices drop, so the dirty count pads to a tiny
power-of-two bucket without a compile shape per count), and the
global array reassembles from the surviving single-device shards.
Host→device bytes per re-place become O(dirty · cap · S) instead of
O(T_bucket · cap · S) — the incomplete-U budget framing applied to
transfer: per-tenant maintenance cost scales with per-tenant change,
not fleet size. Reuse requires stable geometry (same T_bucket, the
required cap no larger than the placed cap, same mesh); a T_bucket or
cap outgrowth forces the full ship, exactly like the base-run ladder.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence, Tuple

import numpy as np

from tuplewise_tpu.obs.ledger import device_section

_MIN_BUCKET = 256


def next_bucket(n: int, min_bucket: int = _MIN_BUCKET) -> int:
    b = min_bucket
    while b < n:
        b *= 2
    return b


def mesh_size(mesh) -> int:
    return int(np.prod(mesh.devices.shape))


def _block(sorted_arr: np.ndarray, S: int, per: int, cap: int,
           dtype) -> np.ndarray:
    """The [S, cap] host block ``place_base`` ships: one sorted slice
    per row, +inf padded."""
    out = np.full((S, cap), np.inf, dtype=dtype)
    for s in range(S):
        chunk = sorted_arr[s * per:(s + 1) * per]
        out[s, : len(chunk)] = chunk
    return out


def _count_bytes(metrics, shipped: int, saved: int) -> None:
    if metrics is None:
        return
    if shipped:
        metrics.counter("bytes_h2d").inc(shipped)
    if saved:
        metrics.counter("bytes_h2d_saved").inc(saved)


def place_base(mesh, sorted_arr: np.ndarray, dtype, *, prev=None,
               metrics=None, chaos=None) -> Tuple[object, int, int]:
    """Pad + place a sorted run as [S, cap] contiguous slices.

    Returns ``(device_array, cap, shipped_bytes)``. Each row holds one
    sorted slice padded with +inf; rows are placed one-per-device via
    the mesh backend's row placement (the same NamedSharding the ring
    estimators use).

    ``prev`` — ``(prev_arr, prev_dev, prev_cap)`` of the placement this
    one replaces. When the bucket geometry (per, cap) is unchanged,
    rows whose content is identical are NOT re-shipped: the new block
    is assembled from the surviving single-device shards plus
    device_puts of only the changed rows [ISSUE 5 satellite]. The
    saved bytes are credited to ``bytes_h2d_saved``.

    ``metrics`` — a MetricsRegistry receiving ``bytes_h2d`` /
    ``bytes_h2d_saved``; ``chaos`` fires the ``place_base`` hook.
    """
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.backends.mesh_backend import row_sharding

    if chaos is not None:
        chaos.fire("place_base")
    S = mesh_size(mesh)
    n = len(sorted_arr)
    per = -(-n // S) if n else 0       # ceil; 0 rows only when base empty
    cap = next_bucket(max(per, 1))
    itemsize = np.dtype(dtype).itemsize
    full_bytes = S * cap * itemsize

    changed = None
    if prev is not None:
        prev_arr, prev_dev, prev_cap = prev
        if (prev_arr is not None and prev_dev is not None
                and prev_cap == cap
                and (-(-len(prev_arr) // S) if len(prev_arr) else 0) == per):
            changed = []
            for s in range(S):
                a = sorted_arr[s * per:(s + 1) * per]
                b = prev_arr[s * per:(s + 1) * per]
                if len(a) != len(b) or not np.array_equal(a, b):
                    changed.append(s)
            if not changed:
                _count_bytes(metrics, 0, full_bytes)
                return prev_dev, cap, 0
            if len(changed) < S:
                try:
                    dev = _reuse_rows(mesh, prev_dev, sorted_arr, changed,
                                      S, per, cap, dtype)
                    shipped = len(changed) * cap * itemsize
                    _count_bytes(metrics, shipped, full_bytes - shipped)
                    return dev, cap, shipped
                except Exception:
                    pass    # any API/topology mismatch: full re-ship

    out = _block(sorted_arr, S, per, cap, dtype)
    dev = jax.device_put(jnp.asarray(out), row_sharding(mesh))
    _count_bytes(metrics, full_bytes, 0)
    return dev, cap, full_bytes


def _reuse_rows(mesh, prev_dev, sorted_arr: np.ndarray,
                changed: Sequence[int], S: int, per: int, cap: int,
                dtype):
    """Assemble a [S, cap] placement shipping only ``changed`` rows:
    unchanged rows reuse the previous placement's single-device shards
    in place (zero transfer)."""
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.backends.mesh_backend import row_sharding

    sharding = row_sharding(mesh)
    by_row = {}
    for sh in prev_dev.addressable_shards:
        by_row[sh.index[0].start or 0] = sh
    if sorted(by_row) != list(range(S)):
        raise RuntimeError("previous placement does not cover the mesh")
    changed_set = set(changed)
    pieces = []
    for s in range(S):
        if s in changed_set:
            row = np.full((1, cap), np.inf, dtype=dtype)
            chunk = sorted_arr[s * per:(s + 1) * per]
            row[0, : len(chunk)] = chunk
            pieces.append(jax.device_put(jnp.asarray(row),
                                         by_row[s].device))
        else:
            pieces.append(by_row[s].data)
    return jax.make_array_from_single_device_arrays(
        (S, cap), sharding, pieces)


@functools.lru_cache(maxsize=None)
def sharded_count_fn(mesh, cap: int, q_bucket: int):
    """Jitted (base_shards [S, cap], queries [q_bucket]) -> (less, leq).

    Per-shard ``searchsorted`` against the local slice, psum'd over
    every mesh axis; outputs are replicated [q_bucket] int counts.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def body(b, q):
        # local slice arrives as [1, cap]; +inf padding never shifts the
        # insertion index of a finite query
        less = jnp.searchsorted(b[0], q, side="left")
        leq = jnp.searchsorted(b[0], q, side="right")
        return lax.psum(less, axes), lax.psum(leq, axes)

    @jax.jit
    def f(base_sh, q):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axes), P()), out_specs=(P(), P()),
            check_vma=False,
        )(base_sh, q)

    return f


@functools.lru_cache(maxsize=None)
def sharded_multi_count_fn(mesh, caps: Tuple[int, ...], q_bucket: int):
    """Jitted multi-run counts: (runs tuple of [S, cap_i], queries) ->
    (less, leq) summed over EVERY run under ONE psum [ISSUE 5].

    Counting is additive over runs, so base + delta-run counts need one
    collective, not one per run; the compile cache is keyed on the cap
    tuple — bounded by the bucket ladder times ``max_delta_runs``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    k = len(caps)

    def body(runs, q):
        less = jnp.zeros(q.shape, dtype=jnp.int32)
        leq = jnp.zeros(q.shape, dtype=jnp.int32)
        for b in runs:
            less = less + jnp.searchsorted(b[0], q, side="left")
            leq = leq + jnp.searchsorted(b[0], q, side="right")
        return lax.psum(less, axes), lax.psum(leq, axes)

    @jax.jit
    def f(runs, q):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=((P(axes),) * k, P()), out_specs=(P(), P()),
            check_vma=False,
        )(runs, q)

    return f


def sharded_counts(mesh, base_dev, cap: int, q: np.ndarray,
                   dtype, chaos=None,
                   deltas: Sequence[Tuple[object, int]] = ()
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """(less, leq) int64 counts of queries against the placed run(s).

    ``deltas`` — additional placed sorted runs ``(device_array, cap)``
    (the index's delta runs); their counts are summed with the base's
    inside one jitted call / one psum. ``base_dev`` may be None when
    only deltas exist (fresh index whose base never formed).

    ``chaos`` (a ``testing.chaos.FaultInjector``) fires the
    ``sharded_count`` hook before the device call — a scheduled fault
    raises here exactly where a dead mesh device would, so the serving
    index's self-healing retry path is exercised deterministically
    [ISSUE 3].
    """
    if chaos is not None:
        chaos.fire("sharded_count")
    qb = next_bucket(len(q))
    q_p = np.zeros(qb, dtype=dtype)
    q_p[: len(q)] = q
    runs, caps = [], []
    if base_dev is not None:
        runs.append(base_dev)
        caps.append(cap)
    for d, c in deltas:
        runs.append(d)
        caps.append(c)
    if not runs:
        z = np.zeros(len(q), dtype=np.int64)
        return z, z
    # host-tax dispatch boundary [ISSUE 14]: key mirrors the jit
    # factory cache key, so first-seen == ladder-growth compile
    with device_section(("sharded_count", mesh, tuple(caps), qb)) as ds:
        if len(runs) == 1:
            less, leq = sharded_count_fn(mesh, caps[0], qb)(runs[0], q_p)
        else:
            less, leq = sharded_multi_count_fn(
                mesh, tuple(caps), qb)(tuple(runs), q_p)
        ds.dispatched()
        less = np.asarray(less)[: len(q)].astype(np.int64)
        leq = np.asarray(leq)[: len(q)].astype(np.int64)
    return less, leq


# --------------------------------------------------------------------- #
# Pallas-fused signed counts [ISSUE 10]                                  #
# --------------------------------------------------------------------- #

# geometries whose Pallas lowering failed once: the request path falls
# back to the XLA twin and never retries the broken shape per call
_KERNEL_BROKEN: set = set()


def _pad_run(arr: np.ndarray, cap: int, dtype) -> np.ndarray:
    out = np.full(cap, np.inf, dtype=dtype)
    out[: len(arr)] = arr
    return out


@functools.lru_cache(maxsize=None)
def _xla_signed_pair_fn(mesh, caps: Tuple[int, ...],
                        signs: Tuple[int, ...],
                        assign: Tuple[int, ...], q_bucket: int):
    """XLA twin of the fused kernel — the automatic fallback target
    [ISSUE 10]: per-run searchsorted pairs, signed accumulation into
    the same [4, q_bucket] int32 block, ONE psum (mesh) or none
    (mesh=None). Bit-identical to the kernel by integer exactness."""
    import jax
    import jax.numpy as jnp

    k = len(caps)

    def accum(rows, qa, qb):
        out = jnp.zeros((4, q_bucket), dtype=jnp.int32)
        for r in range(k):
            q = qa if assign[r] == 0 else qb
            row = 2 * assign[r]
            less = jnp.searchsorted(rows[r], q, side="left")
            leq = jnp.searchsorted(rows[r], q, side="right")
            out = out.at[row].add(signs[r] * less.astype(jnp.int32))
            out = out.at[row + 1].add(signs[r] * leq.astype(jnp.int32))
        return out

    if mesh is None:
        return jax.jit(lambda runs, qa, qb: accum(runs, qa, qb))

    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def body(runs, qa, qb):
        return lax.psum(accum(tuple(r[0] for r in runs), qa, qb), axes)

    @jax.jit
    def f(runs, qa, qb):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=((P(axes),) * k, P(), P()), out_specs=P(),
            check_vma=False,
        )(runs, qa, qb)

    return f


def _count_kernel_metrics(metrics, fallback: bool) -> None:
    if metrics is None:
        return
    name = ("count_kernel_fallbacks_total" if fallback
            else "count_kernel_calls_total")
    metrics.counter(name).inc()


def signed_pair_counts(mesh, runs_a, runs_b, q_a: np.ndarray,
                       q_b: np.ndarray, dtype, *, kernel=None,
                       chaos=None, metrics=None):
    """Fused signed counts of two query sets — the serving count hot
    loop in ONE device dispatch [ISSUE 10].

    ``runs_a`` / ``runs_b``: sequences of ``(run, cap, sign)`` counted
    against ``q_a`` / ``q_b`` respectively (sign +1 for base/delta
    runs, −1 for the tombstone multiset — additivity over signed
    multisets). With a mesh each run is a placed ``[S, cap]`` device
    array; with ``mesh=None`` each is the host sorted array, padded
    here to its bucket. Returns four int64 arrays ``(less_a, leq_a,
    less_b, leq_b)`` trimmed to the query lengths.

    ``kernel``: None = XLA searchsorted path (one jitted signed
    dispatch); else the bool is the Pallas interpret flag and the
    counts run through ONE ``ops.pallas_counts`` invocation per
    device. Any kernel failure falls back to the XLA twin in the same
    call — bit-identical integers — and latches the geometry so a
    broken Mosaic lowering is never retried per request. A failure
    that ALSO breaks the XLA twin (a dead mesh device) propagates to
    the caller's heal loop without latching. ``chaos`` fires the
    ``sharded_count`` hook, exactly like :func:`sharded_counts`.
    """
    if chaos is not None:
        chaos.fire("sharded_count")
    la, lb = len(q_a), len(q_b)
    if not runs_a and not runs_b:
        return (np.zeros(la, np.int64), np.zeros(la, np.int64),
                np.zeros(lb, np.int64), np.zeros(lb, np.int64))
    qb_bucket = next_bucket(max(la, lb, 1))
    qa_p = np.zeros(qb_bucket, dtype=dtype)
    qa_p[:la] = q_a
    qb_p = np.zeros(qb_bucket, dtype=dtype)
    qb_p[:lb] = q_b
    devs, caps, signs, assign = [], [], [], []
    for side, rs in ((0, runs_a), (1, runs_b)):
        for dev, cap, sign in rs:
            if mesh is None:
                dev = _pad_run(np.asarray(dev, dtype=dtype), cap, dtype)
            devs.append(dev)
            caps.append(cap)
            signs.append(sign)
            assign.append(side)
    key = (mesh, tuple(caps), tuple(signs), tuple(assign), qb_bucket)

    def _xla():
        # host-tax dispatch boundary [ISSUE 14]; the key carries
        # kernel=False so a post-fallback XLA compile still counts
        with device_section(("signed_pair", key, False)) as ds:
            f = _xla_signed_pair_fn(mesh, key[1], key[2], key[3],
                                    qb_bucket)
            raw = f(tuple(devs), qa_p, qb_p)
            ds.dispatched()
            return np.asarray(raw)

    if kernel is not None and key not in _KERNEL_BROKEN:
        try:
            from tuplewise_tpu.ops import pallas_counts

            if pallas_counts.FORCE_FAIL:
                raise RuntimeError("forced kernel failure (test hook)")
            if mesh is None:
                f = pallas_counts.flat_signed_count_fn(
                    key[1], key[2], key[3], qb_bucket, bool(kernel))
            else:
                f = pallas_counts.sharded_signed_count_fn(
                    mesh, key[1], key[2], key[3], qb_bucket,
                    bool(kernel))
            with device_section(("signed_pair", key, True)) as ds:
                raw = f(tuple(devs), qa_p, qb_p)
                ds.dispatched()
                out = np.asarray(raw)
            _count_kernel_metrics(metrics, fallback=False)
        except Exception:
            # the XLA twin decides whether the KERNEL was the problem:
            # if it also fails (dead device), propagate to the healer
            # without latching; if it succeeds, the lowering is broken
            # for this geometry — latch and serve the XLA result
            out = _xla()
            _KERNEL_BROKEN.add(key)
            _count_kernel_metrics(metrics, fallback=True)
    else:
        out = _xla()
    out = out.astype(np.int64)
    return (out[0, :la], out[1, :la], out[2, :lb], out[3, :lb])


# --------------------------------------------------------------------- #
# on-mesh major merge [ISSUE 5]                                         #
# --------------------------------------------------------------------- #

class MergePlan(NamedTuple):
    """Host-computed plan for the on-mesh merge.

    ``pos`` — each delta element's rank in the merged run (padded to a
    bucket with an out-of-range sentinel); ``meta = (n, per_b,
    per_out, n_out)``; ``cap_out`` is the output bucket; ``ok`` is
    False when some output shard's base window reaches beyond the
    one-hop neighbor blocks (the caller then takes the host fallback).
    """

    pos: np.ndarray
    meta: np.ndarray
    cap_out: int
    per_out: int
    ok: bool


def plan_major_merge(base: np.ndarray, delta_full: np.ndarray,
                     S: int) -> MergePlan:
    """Compute the merge plan on the host.

    The host is authoritative for both sorted runs, so the plan is one
    ``searchsorted``: delta element j lands at merged rank
    ``searchsorted(base, d_j, 'right') + j`` (base-before-delta on
    ties). The one-hop validity check counts delta ranks below each
    output shard boundary. O(m log n) host work for an O(n) merge —
    the expensive part stays on the mesh; only O(m) plan integers ride
    along (the same order as the delta itself).
    """
    n, m = len(base), len(delta_full)
    per_b = -(-n // S)
    n_out = n + m
    per_out = -(-n_out // S)
    cap_out = next_bucket(max(per_out, 1))
    pos = np.searchsorted(base, delta_full, side="right") + np.arange(m)
    lo = per_out * np.arange(S, dtype=np.int64)
    hi = np.minimum(n_out, lo + per_out)
    lo_d = np.searchsorted(pos, lo, side="left")
    hi_d = np.searchsorted(pos, hi, side="left")
    lo_b = lo - lo_d
    hi_b = hi - hi_d
    s_idx = np.arange(S, dtype=np.int64)
    ok = bool(np.all(lo_b >= (s_idx - 1) * per_b)
              and np.all(hi_b <= (s_idx + 2) * per_b))
    pos_pad = np.full(next_bucket(max(m, 1)), np.iinfo(np.int32).max,
                      dtype=np.int32)
    pos_pad[:m] = pos
    meta = np.asarray([n, per_b, per_out, n_out], dtype=np.int32)
    return MergePlan(pos=pos_pad, meta=meta, cap_out=cap_out,
                     per_out=per_out, ok=ok)


@functools.lru_cache(maxsize=None)
def delta_append_fn(mesh, cap_old: int, cap_chunk: int, cap_new: int):
    """Jitted per-shard append of a placed chunk into the placed delta
    run [ISSUE 5]: each shard rank-merges its (sorted) delta row with
    its (sorted) chunk row — no collectives, no host traffic beyond
    the O(b) chunk itself. Rows need not partition the delta
    contiguously: counting is additive over ANY partition into sorted
    runs, so per-row sorted unions are exactly as good as slices.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def body(old, chunk):
        o = old[0]
        c_row = chunk[0]
        if cap_new > cap_old:
            o = jnp.concatenate(
                [o, jnp.full(cap_new - cap_old, jnp.inf, o.dtype)])
        jc = jnp.arange(cap_chunk, dtype=jnp.int32)
        # chunk padding (+inf) is banished out of range -> dropped
        pd = jnp.where(jnp.isfinite(c_row),
                       jc + jnp.searchsorted(o, c_row, side="right"),
                       cap_new)
        marks = jnp.zeros(cap_new, dtype=jnp.int32
                          ).at[pd].add(1, mode="drop")
        i = jnp.arange(cap_new, dtype=jnp.int32)
        cum = jnp.cumsum(marks) - marks
        take_c = c_row[jnp.clip(cum, 0, cap_chunk - 1)]
        take_o = o[jnp.clip(i - cum, 0, cap_new - 1)]
        return jnp.where(marks > 0, take_c, take_o)[None]

    @jax.jit
    def f(old, chunk):
        return jax.shard_map(body, mesh=mesh,
                             in_specs=(P(axes), P(axes)),
                             out_specs=P(axes), check_vma=False,
                             )(old, chunk)

    return f


# The merge executes as a SEQUENCE of short device programs — one
# boundary-exchange window build, then cap_out/_MERGE_CHUNK chunk
# programs, then one assembly concat — rather than one monolithic
# kernel: the merge shares the device with the request path's count
# kernels, so the LONGEST single program (not the merge total) is the
# pause ceiling a compaction can impose on a concurrent count. Chunking
# bounds that quantum; counts interleave between chunks.
_MERGE_CHUNK = 32768


@functools.lru_cache(maxsize=None)
def _merge_window_fn(mesh, cap_base: int):
    """Jitted neighbor boundary exchange: each shard receives BOTH
    neighbors' base blocks via ``lax.ppermute`` (an output slice's
    base window can overhang into the adjacent shards' slices after
    rebalancing) and returns its [3, cap_base] window, flattened to
    keep the output row-sharded."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    axis = axes[0]
    S = mesh_size(mesh)
    fwd = [(i, (i + 1) % S) for i in range(S)]     # receive left block
    bwd = [(i, (i - 1) % S) for i in range(S)]     # receive right block

    def body(base):
        from_left = lax.ppermute(base[0], axis, fwd)
        from_right = lax.ppermute(base[0], axis, bwd)
        return jnp.concatenate([from_left, base[0], from_right])[None]

    @jax.jit
    def f(base_sh):
        return jax.shard_map(body, mesh=mesh, in_specs=P(axes),
                             out_specs=P(axes), check_vma=False,
                             )(base_sh)

    return f


@functools.lru_cache(maxsize=None)
def _merge_delta_fn(mesh, delta_caps: Tuple[int, ...]):
    """Jitted delta replication: ``all_gather`` the placed delta
    blocks and sort once (+inf padding sorts to the tail, so ranks
    [0, m) are the delta multiset) — shared by every merge chunk."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    axis = axes[0]

    def body(deltas):
        return jnp.sort(jnp.concatenate(
            [lax.all_gather(d[0], axis, tiled=True) for d in deltas]))

    @jax.jit
    def f(delta_shs):
        return jax.shard_map(body, mesh=mesh,
                             in_specs=((P(axes),) * len(delta_caps),),
                             out_specs=P(), check_vma=False,
                             )(delta_shs)

    return f


@functools.lru_cache(maxsize=None)
def _merge_chunk_fn(mesh, cap_base: int, delta_cap: int,
                    pos_cap: int, chunk: int):
    """Jitted merge chunk: build ``chunk`` consecutive slots of every
    shard's output row by rank arithmetic — no sort, no out-sized
    search.

    Output slot r (global rank ``s*per_out + chunk_start + i``) holds
    a delta element iff r is one of the host-planned delta positions
    (one small binary search over ``pos``); otherwise it holds base
    rank ``r - #deltas_before``, gathered from the one-hop window.
    The delta VALUES come from :func:`_merge_delta_fn`'s replicated
    gather of the placed blocks — zero host→device data bytes.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    axis = axes[0]

    def body(window, delta_full, pos, meta, chunk_start):
        s = lax.axis_index(axis)
        n, per_b, per_out = meta[0], meta[1], meta[2]
        n_out = meta[3]
        w = window[0].reshape(3, cap_base)
        local = chunk_start + jnp.arange(chunk, dtype=jnp.int32)
        start = s * per_out + chunk_start
        r = s * per_out + local                 # global output ranks
        # deltas-before-each-slot WITHOUT an out-sized binary search:
        # the planned positions hitting this window are a CONTIGUOUS
        # range of the sorted ``pos`` (at most ``chunk`` of them), so
        # dynamic-slice that range, scatter it into per-slot marks,
        # cumsum, and offset by the scalar count below the window —
        # O(chunk) work, one scalar search
        c_lo = jnp.searchsorted(pos, start, side="left")
        pos_win = lax.dynamic_slice(pos, (c_lo,), (chunk,))
        rel = pos_win - start
        # negative indices would WRAP (NumPy semantics) before the
        # drop check — clamp them out of range instead
        rel = jnp.where(rel >= 0, rel, chunk)
        marks = jnp.zeros(chunk, dtype=jnp.int32
                          ).at[rel].add(1, mode="drop")
        c = c_lo + jnp.cumsum(marks) - marks
        is_d = marks > 0
        b_rank = r - c
        blk = b_rank // per_b - (s - 1)
        off = b_rank - (b_rank // per_b) * per_b
        bval = w[jnp.clip(blk, 0, 2), jnp.clip(off, 0, cap_base - 1)]
        bval = jnp.where((b_rank < n) & (blk >= 0) & (blk < 3),
                         bval, jnp.inf)
        dval = delta_full[jnp.clip(c, 0, delta_full.shape[0] - 1)]
        out = jnp.where(is_d, dval, bval)
        valid = (local < per_out) & (r < n_out)
        return jnp.where(valid, out, jnp.inf)[None]

    @jax.jit
    def f(window, delta_full, pos, meta, chunk_start):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axes), P(), P(), P(), P()),
            out_specs=P(axes), check_vma=False,
        )(window, delta_full, pos, meta, chunk_start)

    return f


@functools.lru_cache(maxsize=None)
def _merge_assemble_fn(mesh, chunk: int, parts: int):
    """Jitted concat of the chunk outputs into the [S, cap_out] row."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def body(*chunks):
        return jnp.concatenate([c[0] for c in chunks])[None]

    @jax.jit
    def f(*chunks):
        return jax.shard_map(body, mesh=mesh,
                             in_specs=(P(axes),) * parts,
                             out_specs=P(axes), check_vma=False,
                             )(*chunks)

    return f


def sharded_major_merge(mesh, base_dev, cap_base: int,
                        delta_devs: Sequence[Tuple[object, int]],
                        plan: MergePlan, chaos=None
                        ) -> Tuple[object, int]:
    """Execute a host-computed :func:`plan_major_merge` on the mesh;
    returns the merged ``(device_array, cap_out)`` — exactly the
    layout ``place_base`` would produce. No base bytes cross the
    host→device boundary; only the O(m) plan integers ride along.
    ``chaos`` fires the ``major_merge`` hook (a raise here exercises
    the index's host fallback) [ISSUE 5].
    """
    if chaos is not None:
        chaos.fire("major_merge")
    caps = tuple(c for _, c in delta_devs)
    deltas = tuple(d for d, _ in delta_devs)
    chunk = min(plan.cap_out, _MERGE_CHUNK)
    parts = plan.cap_out // chunk
    pos = plan.pos
    if len(pos) < chunk:    # dynamic_slice window needs >= chunk
        pad = np.full(chunk - len(pos), np.iinfo(np.int32).max,
                      dtype=np.int32)
        pos = np.concatenate([pos, pad])
    window = _merge_window_fn(mesh, cap_base)(base_dev)
    delta_full = _merge_delta_fn(mesh, caps)(deltas)
    fchunk = _merge_chunk_fn(mesh, cap_base, int(delta_full.shape[0]),
                             len(pos), chunk)
    outs = [fchunk(window, delta_full, pos, plan.meta,
                   np.int32(k * chunk)) for k in range(parts)]
    if parts == 1:
        return outs[0], plan.cap_out
    return (_merge_assemble_fn(mesh, chunk, parts)(*outs),
            plan.cap_out)


# --------------------------------------------------------------------- #
# tenant axis [ISSUE 8]                                                  #
# --------------------------------------------------------------------- #

_MIN_TENANT_BUCKET = 8


def tenant_bucket(n: int, min_bucket: int = _MIN_TENANT_BUCKET) -> int:
    """Tenant-row bucket: power of two >= n (the T axis of the
    (T_bucket, cap, q_bucket) compile-shape ladder)."""
    return next_bucket(max(n, 1), min_bucket=min_bucket)


def place_tenant_pack(mesh, runs: Sequence[np.ndarray], t_bucket: int,
                      dtype, *, prev=None, dirty=None, metrics=None,
                      chaos=None) -> Tuple[object, int, int]:
    """Pack a fleet of sorted runs into one shared padded device buffer.

    ``runs[t]`` is tenant slot t's sorted host run (may be empty; slots
    past ``len(runs)`` are empty rows). With a mesh, the pack is
    ``[S, t_bucket, cap]`` — tenant t's contiguous slice s (its own
    ``per_t = ceil(n_t / S)`` split) in row ``[s, t]`` — placed one
    leading-row per device via the same NamedSharding the base runs
    use; without a mesh it is a single-device ``[t_bucket, cap]``
    block. ``cap`` is the bucket of the LARGEST per-shard slice, shared
    by every tenant (the shared-buffer trade: one compile shape for the
    whole fleet, padding proportional to the biggest tenant). All
    padding is +inf, so finite queries count exactly without masks.

    ``prev`` — ``(prev_dev, prev_cap, prev_t_bucket)`` of the placement
    this one replaces; ``dirty`` — the slot indices whose runs changed
    since it (None = unknown/all). When the geometry is stable (same
    ``t_bucket``, required cap <= ``prev_cap``, same mesh width) only
    the dirty slots' rows are shipped and scattered into the resident
    per-device shards [ISSUE 9 tentpole]; the bytes a naive full
    re-ship would have cost land in ``bytes_h2d_saved``.

    Returns ``(device_array, cap, shipped_bytes)``; bytes are credited
    to ``bytes_h2d`` like every other placement. ``chaos`` fires the
    ``place_base`` hook (a raise here exercises the fleet's
    retry/heal path).
    """
    import jax
    import jax.numpy as jnp

    if chaos is not None:
        chaos.fire("place_base")
    S = mesh_size(mesh) if mesh is not None else 1
    pers = [-(-len(r) // S) if len(r) else 0 for r in runs]
    need_cap = next_bucket(max(pers, default=1) or 1)
    itemsize = np.dtype(dtype).itemsize

    if prev is not None and dirty is not None:
        prev_dev, prev_cap, prev_tb = prev
        # geometry-stable reuse: keep the (possibly larger) placed cap
        # — extra +inf padding never changes a finite query's counts —
        # and ship only the dirty rows. Any mismatch falls through to
        # the full ship below.
        if (prev_dev is not None and prev_tb == t_bucket
                and need_cap <= prev_cap
                and all(0 <= t < t_bucket for t in dirty)):
            full_bytes = S * t_bucket * prev_cap * itemsize
            if not dirty:
                _count_bytes(metrics, 0, full_bytes)
                return prev_dev, prev_cap, 0
            try:
                dev, shipped = _update_pack_rows(
                    mesh, prev_dev, runs, sorted(dirty), S, t_bucket,
                    prev_cap, dtype)
                _count_bytes(metrics, shipped, full_bytes - shipped)
                return dev, prev_cap, shipped
            except Exception:
                pass    # any API/topology mismatch: full re-ship

    cap = need_cap
    block = np.full((S, t_bucket, cap), np.inf, dtype=dtype)
    for t, r in enumerate(runs):
        per = pers[t]
        for s in range(S):
            chunk = r[s * per:(s + 1) * per]
            if len(chunk):
                block[s, t, : len(chunk)] = chunk
    shipped = block.nbytes
    if mesh is None:
        dev = jnp.asarray(block[0])
    else:
        from tuplewise_tpu.backends.mesh_backend import row_sharding

        dev = jax.device_put(jnp.asarray(block), row_sharding(mesh))
    _count_bytes(metrics, shipped, 0)
    return dev, cap, shipped


@functools.lru_cache(maxsize=None)
def _pack_scatter_fn(t_bucket: int, cap: int, db: int, sharded: bool):
    """Jitted dirty-row scatter [ISSUE 9]: write ``db`` replacement
    rows into a resident pack shard at the given slot indices. The
    dirty count pads to the power-of-two bucket ``db``; padding
    entries carry slot index ``t_bucket`` (out of range) and drop —
    one compiled shape per (t_bucket, cap, db) ladder point, never per
    dirty set."""
    import jax

    if sharded:
        @jax.jit
        def f(shard, rows, idx):
            # shard [1, T, cap] (one device's slice of every tenant)
            return shard.at[0, idx, :].set(rows, mode="drop")
    else:
        @jax.jit
        def f(block, rows, idx):
            return block.at[idx, :].set(rows, mode="drop")
    return f


def _update_pack_rows(mesh, prev_dev, runs, dirty, S: int,
                      t_bucket: int, cap: int, dtype):
    """Ship only ``dirty`` slots' rows into the resident pack; returns
    ``(device_array, shipped_bytes)``. Per device s, the replacement
    block holds each dirty tenant's slice s (+inf padded to cap); the
    scatter runs on that device's shard and the global array
    reassembles from the surviving single-device pieces — exactly the
    ``_reuse_rows`` protocol with a tenant axis."""
    import jax
    import jax.numpy as jnp

    itemsize = np.dtype(dtype).itemsize
    db = next_bucket(len(dirty), min_bucket=1)
    idx = np.full(db, t_bucket, dtype=np.int32)     # padding: dropped
    idx[: len(dirty)] = dirty

    def dirty_rows(s: int) -> np.ndarray:
        rows = np.full((db, cap), np.inf, dtype=dtype)
        for i, t in enumerate(dirty):
            r = runs[t] if t < len(runs) else ()
            per = -(-len(r) // S) if len(r) else 0
            chunk = r[s * per:(s + 1) * per]
            if len(chunk):
                rows[i, : len(chunk)] = chunk
        return rows

    if mesh is None:
        fn = _pack_scatter_fn(t_bucket, cap, db, sharded=False)
        dev = fn(prev_dev, jnp.asarray(dirty_rows(0)),
                 jnp.asarray(idx))
        return dev, db * cap * itemsize

    from tuplewise_tpu.backends.mesh_backend import row_sharding

    sharding = row_sharding(mesh)
    by_row = {}
    for sh in prev_dev.addressable_shards:
        by_row[sh.index[0].start or 0] = sh
    if sorted(by_row) != list(range(S)):
        raise RuntimeError("previous pack does not cover the mesh")
    fn = _pack_scatter_fn(t_bucket, cap, db, sharded=True)
    pieces = []
    for s in range(S):
        rows_dev = jax.device_put(jnp.asarray(dirty_rows(s)),
                                  by_row[s].device)
        idx_dev = jax.device_put(jnp.asarray(idx), by_row[s].device)
        pieces.append(fn(by_row[s].data, rows_dev, idx_dev))
    dev = jax.make_array_from_single_device_arrays(
        (S, t_bucket, cap), sharding, pieces)
    return dev, S * db * cap * itemsize


@functools.lru_cache(maxsize=None)
def tenant_count_fn(mesh, t_bucket: int, cap_pos: int, cap_neg: int,
                    q_bucket: int):
    """Jitted tenant-axis fleet count [ISSUE 8]: ONE call, ONE psum.

    ``(pos_pack [S, T, cap_pos], neg_pack [S, T, cap_neg],
    q_vs_neg [T, qb], q_vs_pos [T, qb]) -> (less_n, leq_n, less_p,
    leq_p)`` — each ``[T, qb]`` replicated int counts. Row t of each
    query block is tenant slot t's padded queries; a vmapped per-row
    ``searchsorted`` against the tenant's own rows keeps every tenant's
    counts independent, and the single tuple psum sums the per-shard
    slices. Serving a whole coalesced multi-tenant micro-batch is one
    dispatch of this function, however many tenants it touches.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def _rows(pack, q, side):
        return jax.vmap(
            lambda row, qq: jnp.searchsorted(row, qq, side=side))(pack, q)

    def body(pos, neg, qn, qp):
        # local packs arrive as [1, T, cap]
        out = (_rows(neg[0], qn, "left"), _rows(neg[0], qn, "right"),
               _rows(pos[0], qp, "left"), _rows(pos[0], qp, "right"))
        return lax.psum(out, axes)

    @jax.jit
    def f(pos, neg, qn, qp):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axes), P(axes), P(), P()),
            out_specs=(P(), P(), P(), P()), check_vma=False,
        )(pos, neg, qn, qp)

    return f


@functools.lru_cache(maxsize=None)
def tenant_count_local_fn(t_bucket: int, cap_pos: int, cap_neg: int,
                          q_bucket: int):
    """Single-device twin of :func:`tenant_count_fn` (no mesh): packs
    are ``[T, cap]`` blocks, outputs identical."""
    import jax
    import jax.numpy as jnp

    def _rows(pack, q, side):
        return jax.vmap(
            lambda row, qq: jnp.searchsorted(row, qq, side=side))(pack, q)

    @jax.jit
    def f(pos, neg, qn, qp):
        return (_rows(neg, qn, "left"), _rows(neg, qn, "right"),
                _rows(pos, qp, "left"), _rows(pos, qp, "right"))

    return f


def tenant_pack_counts(mesh, pos_pack, cap_pos: int, neg_pack,
                       cap_neg: int, t_bucket: int,
                       q_vs_neg: np.ndarray, q_vs_pos: np.ndarray,
                       dtype, chaos=None, kernel=None, metrics=None):
    """Dispatch one fleet count: padded ``[t_bucket, qb]`` query blocks
    against both class packs. Returns four ``[t_bucket, qb]`` int64
    arrays ``(less_n, leq_n, less_p, leq_p)``. ``chaos`` fires the
    ``sharded_count`` hook — the same point a dead mesh device
    surfaces at, so fleet healing is driven by the same specs as the
    single-tenant index [ISSUE 8].

    ``kernel``: None = the XLA vmapped-searchsorted path; else the
    bool is the Pallas interpret flag and the whole fleet batch runs
    through ONE ``ops.pallas_counts`` tenant-axis invocation per
    device (queries enter transposed so the per-tenant outer compare
    needs no in-kernel transpose), with the same
    fallback-then-latch discipline as :func:`signed_pair_counts`
    [ISSUE 10].
    """
    if chaos is not None:
        chaos.fire("sharded_count")
    qb = q_vs_neg.shape[1]
    key = ("tenant", mesh, t_bucket, cap_pos, cap_neg, qb)

    def _xla():
        # host-tax dispatch boundary [ISSUE 14] — the fleet's ONE
        # count call per coalesced micro-batch
        with device_section(("tenant_count", key, False)) as ds:
            if mesh is None:
                fn = tenant_count_local_fn(t_bucket, cap_pos, cap_neg,
                                           qb)
            else:
                fn = tenant_count_fn(mesh, t_bucket, cap_pos, cap_neg,
                                     qb)
            raw = fn(pos_pack, neg_pack, q_vs_neg, q_vs_pos)
            ds.dispatched()
            return tuple(np.asarray(o).astype(np.int64) for o in raw)

    if kernel is not None and key not in _KERNEL_BROKEN:
        try:
            from tuplewise_tpu.ops import pallas_counts

            if pallas_counts.FORCE_FAIL:
                raise RuntimeError("forced kernel failure (test hook)")
            qn_t = np.ascontiguousarray(q_vs_neg.T)
            qp_t = np.ascontiguousarray(q_vs_pos.T)
            if mesh is None:
                fn = pallas_counts.tenant_signed_count_local_fn(
                    t_bucket, cap_pos, cap_neg, qb, bool(kernel))
            else:
                fn = pallas_counts.tenant_signed_count_fn(
                    mesh, t_bucket, cap_pos, cap_neg, qb, bool(kernel))
            with device_section(("tenant_count", key, True)) as ds:
                raw = fn(pos_pack, neg_pack, qn_t, qp_t)
                ds.dispatched()
                out = np.asarray(raw)
            _count_kernel_metrics(metrics, fallback=False)
            out = out.astype(np.int64)
            return (out[0].T, out[1].T, out[2].T, out[3].T)
        except Exception:
            res = _xla()    # a dead device fails here too -> heals
            _KERNEL_BROKEN.add(key)
            _count_kernel_metrics(metrics, fallback=True)
            return res
    return _xla()
