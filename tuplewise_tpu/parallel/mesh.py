"""Device-mesh construction for the distributed backend [SURVEY §5.8].

One data shard per chip on a 1-D mesh; the mesh axis name ``"w"``
("workers") is what `shard_map` bodies psum/ppermute over. Multi-chip
code paths are validated without TPU hardware via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` [SURVEY §5.1].
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

shard_axis_name = "w"


def make_mesh(n_workers: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``n_workers`` devices (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_workers is None:
        n_workers = len(devices)
    if n_workers > len(devices):
        raise ValueError(
            f"requested {n_workers} workers but only {len(devices)} devices"
        )
    return jax.make_mesh((n_workers,), (shard_axis_name,), devices=devices[:n_workers])
