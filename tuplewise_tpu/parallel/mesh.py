"""Device-mesh construction for the distributed backend [SURVEY §5.8].

One data shard per chip on a 1-D mesh; the mesh axis name ``"w"``
("workers") is what `shard_map` bodies psum/ppermute over. Multi-chip
code paths are validated without TPU hardware via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` [SURVEY §5.1].
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

shard_axis_name = "w"
dcn_axis_name = "dcn"


def make_mesh(n_workers: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``n_workers`` devices (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_workers is None:
        n_workers = len(devices)
    if n_workers > len(devices):
        raise ValueError(
            f"requested {n_workers} workers but only {len(devices)} devices"
        )
    return jax.make_mesh((n_workers,), (shard_axis_name,), devices=devices[:n_workers])


def make_mesh_2d(
    n_dcn: int,
    n_ici: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A 2-D ``(dcn, ici)``-named mesh for multi-host topologies
    [SURVEY §5.8]: the trailing ("w") axis is the fast intra-slice ICI
    ring; the leading ("dcn") axis spans host/slice boundaries. The ring
    primitives rotate blocks over "w" and cross "dcn" once per full
    inner cycle (ring_pair_stats_2d), so collectives ride ICI, not DCN.

    On a real multi-host system pass ``devices`` ordered so consecutive
    groups of ``n_ici`` share a slice (jax.devices() already is).
    """
    if devices is None:
        devices = jax.devices()
    if n_ici is None:
        if len(devices) % n_dcn:
            raise ValueError(
                f"{len(devices)} devices do not divide into {n_dcn} hosts"
            )
        n_ici = len(devices) // n_dcn
    need = n_dcn * n_ici
    if need > len(devices):
        raise ValueError(
            f"requested {n_dcn}x{n_ici} mesh but only "
            f"{len(devices)} devices"
        )
    return jax.make_mesh(
        (n_dcn, n_ici), (dcn_axis_name, shard_axis_name),
        devices=devices[:need],
    )
