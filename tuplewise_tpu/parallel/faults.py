"""Failure simulation, detection, and drop-and-renormalize tolerance.

[SURVEY §5.4]: the reference (single-process NumPy) has no failure
handling, but the repartitioned estimator family is *naturally* tolerant
to losing a worker: each surviving worker's local U-statistic is itself
an unbiased estimate under a random partition, so the master can simply
average over survivors — "drop and renormalize". This module makes that
first-class:

* ``alive_mask`` / ``normalize_dropped`` — declare which workers are
  lost; estimator schemes renormalize over the survivors.
* ``sample_failures`` — independent per-worker failure injection for
  fault-tolerance experiments (never kills the last survivor).
* ``check_mesh_health`` — failure *detection*: runs a tiny psum across
  the mesh and checks every device contributed. On this single-host
  simulation it exercises the collective path end-to-end; on a real
  multi-host deployment a dead/hung chip surfaces here as a mismatch,
  timeout, or runtime error, which the caller maps to a dropped-worker
  set for the estimators above.

Statistical note: dropping workers does NOT bias local-average or
repartitioned estimators (each per-worker value is unbiased); it only
raises variance by the lost 1/N factor — the same communication/accuracy
currency the paper trades in [SURVEY §1.2].
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional, Sequence, Tuple

import numpy as np


class ProbeTimeout(RuntimeError):
    """A health probe did not return within its deadline — the device
    (or collective) is treated as hung, which is a failure mode, not an
    exception to swallow silently."""


def _run_bounded(fn: Callable[[], object],
                 timeout_s: Optional[float]) -> object:
    """Run ``fn`` with a wall-clock bound [ISSUE 3 satellite].

    A *hung* device does not raise — it blocks forever, which would
    turn the failure detector itself into the hang it exists to detect.
    The probe runs in a daemon helper thread; if it misses the deadline
    the caller gets ``ProbeTimeout`` and the thread is abandoned (it
    holds no locks of ours; a wedged XLA collective cannot be cancelled
    from Python anyway). ``timeout_s`` of None keeps the old synchronous
    behavior."""
    if timeout_s is None:
        return fn()
    box: dict = {}

    def run():
        try:
            box["value"] = fn()
        except BaseException as e:      # noqa: BLE001 — relayed below
            box["exc"] = e

    t = threading.Thread(target=run, name="tuplewise-probe", daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        raise ProbeTimeout(f"health probe hung past {timeout_s}s")
    if "exc" in box:
        raise box["exc"]
    return box["value"]


def normalize_dropped(
    dropped: Iterable[int], n_workers: int
) -> Tuple[int, ...]:
    """Validate + canonicalize a dropped-worker set (sorted, unique)."""
    d = sorted(set(int(w) for w in dropped))
    if any(w < 0 or w >= n_workers for w in d):
        raise ValueError(
            f"dropped workers {d} out of range for n_workers={n_workers}"
        )
    if len(d) >= n_workers:
        raise ValueError(
            f"cannot drop all {n_workers} workers: no survivors to "
            "renormalize over"
        )
    return tuple(d)


def alive_mask(n_workers: int, dropped: Iterable[int] = ()) -> np.ndarray:
    """Float {0,1} mask over workers; mask[w] == 0 iff w is dropped."""
    d = normalize_dropped(dropped, n_workers)
    mask = np.ones(n_workers, dtype=np.float64)
    mask[list(d)] = 0.0
    return mask


def sample_failures(
    seed: int, n_workers: int, p_fail: float
) -> Tuple[int, ...]:
    """Independent worker failures with probability p_fail each,
    conditioned on at least one survivor (resampling the would-be
    last victim back to life)."""
    if not 0.0 <= p_fail < 1.0:
        raise ValueError(f"p_fail must be in [0, 1), got {p_fail}")
    rng = np.random.default_rng(seed)
    fails = rng.random(n_workers) < p_fail
    if fails.all():
        fails[rng.integers(n_workers)] = False
    return tuple(int(w) for w in np.nonzero(fails)[0])


def survivors(n_workers: int, dropped: Sequence[int]) -> Tuple[int, ...]:
    d = set(normalize_dropped(dropped, n_workers))
    return tuple(w for w in range(n_workers) if w not in d)


def _collective_probe(mesh) -> bool:
    """The raw psum probe body — separated so the timeout wrapper (and
    tests simulating a hang) can replace exactly the part that talks to
    devices."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    n = int(np.prod(mesh.devices.shape))

    def probe():
        # psum over EVERY mesh axis: on a 2-D (dcn x ici) mesh summing a
        # single axis would count only that axis's extent and wrongly
        # report an unhealthy mesh.
        return jax.lax.psum(jnp.ones(()), axes)

    out = jax.jit(
        jax.shard_map(
            probe, mesh=mesh, in_specs=(), out_specs=P(),
            check_vma=False,
        )
    )()
    return int(out) == n


def _device_probe(dev) -> bool:
    """Tiny transfer+compute against one device; True when it answers."""
    import jax
    import jax.numpy as jnp

    x = jax.device_put(jnp.ones(()), dev)
    return float(x + 1) == 2.0


def check_mesh_health(mesh, timeout_s: Optional[float] = None) -> bool:
    """Failure detection probe: every device contributes 1 to a psum;
    a healthy N-device mesh returns N everywhere. Raises nothing itself —
    runtime errors from dead devices propagate to the caller, which
    should translate them (or a False return) into a dropped set.

    ``timeout_s`` bounds the probe's wall clock (a hung device blocks a
    collective forever rather than raising): on expiry the mesh is
    reported unhealthy (False) instead of hanging the detector."""
    try:
        return bool(_run_bounded(lambda: _collective_probe(mesh),
                                 timeout_s))
    except ProbeTimeout:
        return False


def detect_dropped_workers(
    mesh, timeout_s: Optional[float] = None
) -> Tuple[int, ...]:
    """Map an unhealthy mesh to the set of dead workers.

    Fast path: the collective ``check_mesh_health`` probe — healthy
    means no per-device work at all. On failure (False, or the
    collective itself raising, which is how a dead chip actually
    surfaces), fall back to probing each device INDIVIDUALLY with a
    tiny transfer+compute; devices that raise — or hang past
    ``timeout_s`` [ISSUE 3 satellite] — are the dropped set.
    Raises if every device fails (nothing to renormalize over)."""
    try:
        if check_mesh_health(mesh, timeout_s=timeout_s):
            return ()
    except Exception:
        pass  # collective died: fall through to per-device probing
    dropped = []
    for w, dev in enumerate(mesh.devices.flat):
        try:
            if not _run_bounded(lambda d=dev: _device_probe(d), timeout_s):
                dropped.append(w)
        except Exception:
            dropped.append(w)
    n = mesh.devices.size
    if len(dropped) >= n:
        raise RuntimeError(
            f"all {n} devices failed the health probe; cannot renormalize"
        )
    return tuple(dropped)


def run_with_fault_tolerance(
    estimator,
    scheme: str,
    A,
    B=None,
    *,
    detector=None,
    **kwargs,
):
    """Probe health -> derive the dropped set -> run the estimator, in
    one call [SURVEY §5.4 end-to-end]: no manual glue between detection
    and the drop-and-renormalize machinery.

    scheme: "local" or "repartitioned" — the schemes whose per-worker
    values stay individually unbiased under worker loss (complete /
    incomplete statistics need every shard's data, so a dead worker is
    not recoverable by renormalizing and the caller must re-pack).

    detector: () -> dropped tuple; defaults to
    ``detect_dropped_workers`` on the estimator's mesh (mesh backend)
    or no-failures for single-process backends. kwargs pass through to
    the estimator method (n_rounds, seed, scheme=partition scheme...).
    """
    methods = {"local": "local_average", "repartitioned": "repartitioned"}
    if scheme not in methods:
        raise ValueError(
            f"fault tolerance applies to {sorted(methods)} schemes "
            f"(per-worker values stay unbiased under loss); got {scheme!r}"
        )
    if detector is None:
        mesh = getattr(estimator.backend, "mesh", None)
        if mesh is not None:
            detector = lambda: detect_dropped_workers(mesh)  # noqa: E731
        else:
            detector = tuple
    dropped = normalize_dropped(detector(), estimator.n_workers)
    return getattr(estimator, methods[scheme])(
        A, B, dropped_workers=dropped, **kwargs
    )
