"""On-device partitioning helpers shared by the mesh backend, the
learner, and the harness [SURVEY §2 L2 — device side].

Host-side partitioning lives in parallel.partition (NumPy, importable
without jax); these are the `jax.random` equivalents used inside jitted
programs. One implementation so SWOR/SWR semantics can never diverge
between the estimator backend, the trainer, and the experiment harness.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def linear_shard_index(axes):
    """Row-major linearized shard index across one or more mesh axes —
    THE worker id inside shard_map bodies. One implementation shared by
    the mesh backend, the trainer, and the mesh-MC harness: per-shard
    PRNG fold chains (``fold(key, "shard"/"pair_sample", w)``) must
    derive the same w everywhere or cross-module reproducibility
    silently breaks."""
    w = jax.lax.axis_index(axes[0])
    for ax in axes[1:]:
        w = w * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
    return w


def draw_blocks(key, n: int, n_workers: int, scheme: str = "swor",
                m: Optional[int] = None) -> jnp.ndarray:
    """[N, m] int32 worker index blocks over range(n).

    swor: one global permutation cut into N blocks (random remainder
    dropped when n > N*m); swr: i.i.d. uniform draws. Mirrors
    partition.partition_indices.
    """
    m = n // n_workers if m is None else m
    if scheme == "swor":
        idx = jax.random.permutation(key, n)[: n_workers * m]
        return idx.reshape(n_workers, m).astype(jnp.int32)
    if scheme == "swr":
        return jax.random.randint(key, (n_workers, m), 0, n, dtype=jnp.int32)
    raise ValueError(f"unknown partition scheme {scheme!r}")


def pad_put(X, mesh: Mesh, dtype=jnp.float32) -> jnp.ndarray:
    """Zero-pad axis 0 to a multiple of the mesh size and device_put
    sharded on the worker axis.

    Padding (never truncation) keeps every real row reachable: callers
    draw indices over the TRUE n, so padded rows are never gathered and
    ragged sizes drop a random remainder per round.
    """
    X = np.asarray(X)
    n_shards = int(np.prod(mesh.devices.shape))
    pad = (-len(X)) % n_shards
    if pad:
        X = np.concatenate([X, np.zeros((pad,) + X.shape[1:], X.dtype)])
    # shard axis 0 over EVERY mesh axis (1-D and 2-D meshes alike)
    spec = P(tuple(mesh.axis_names), *([None] * (X.ndim - 1)))
    return jax.device_put(jnp.asarray(X, dtype), NamedSharding(mesh, spec))
