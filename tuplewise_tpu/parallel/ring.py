"""ring_pairs — cross-shard all-pairs over the ICI ring [SURVEY §5.7, §7 step 5].

The build's signature primitive. Each chip holds one data shard; to touch
every cross-shard pair, shard blocks rotate around the ring via
`lax.ppermute` while each chip accumulates pair-kernel sums between its
resident block and the visiting block — structurally the communication
pattern of ring attention, applied to tuplewise kernels instead of
attention [SURVEY §3 "Cross-shard pair computation", §5.7]. After N
steps every (shard_i, shard_j) block pair has been visited exactly once;
a final `lax.psum` yields the global sum.

These functions run INSIDE `jax.shard_map` bodies: array arguments are
per-shard local blocks, and `axis_name` names the mesh axis to rotate
over. Compute between rotations is the tiled reduction of ops.pair_tiles,
so each ppermute can overlap with a long tile loop.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tuplewise_tpu.ops import pair_tiles


def _ring_perm(axis_name):
    n = lax.axis_size(axis_name)
    return [(i, (i + 1) % n) for i in range(n)]


def _rotate(state, axis_name):
    """ppermute every array of a visiting-block state one ring step."""
    perm = _ring_perm(axis_name)
    return tuple(lax.ppermute(x, axis_name, perm) for x in state)


def _make_stats_fn(
    kernel, mask_a, ids_a, *, tile_a, tile_b, use_ids, impl, interpret=None,
    no_masks=False, n_a=None, n_b=None,
):
    """Build the per-stop (resident, visiting) -> (sum, count) reduction.

    impl="pallas" routes diff kernels without id exclusion through the
    hand-tiled mask-aware Pallas kernel (ops.pallas_pairs) — ~4x the XLA
    scan path per chip, which is what lets the DISTRIBUTED estimator run
    at single-chip Pallas throughput [SURVEY §7 step 5]. Everything else
    (feature kernels, id-aware one-sample paths, impl="xla") uses the
    checkpointed XLA tile reduction. interpret mode makes the Pallas
    path run on the CPU test mesh, so parity tests cover it; pass
    interpret explicitly when the executing mesh's platform differs
    from the default backend (MeshBackend does).

    no_masks=True (with the static block sizes n_a, n_b) asserts that
    every row on both sides is valid — no padding anywhere on the ring —
    which is trace-time knowledge only the CALLER has (a mask array's
    values are invisible here). The reduction then dispatches to the
    interior/edge-decomposed UNMASKED Pallas path at ANY block size
    (ops.pallas_pairs.pallas_pair_sum_any): the mask multiply the masked
    kernel pays on every tile (~15% of throughput at the n=2^20 bench
    shape even with all-ones masks — docs/ring_overlap.md) is paid only
    on thin edge strips when blocks don't divide the tiles
    [VERDICT r2 next #3; VERDICT r3 next #1]."""
    if impl == "pallas" and kernel.kind == "diff" and not use_ids:
        from tuplewise_tpu.ops.pallas_pairs import (
            pallas_masked_pair_sum, pallas_pair_sum_any,
        )

        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"

        if no_masks and n_a and n_b:
            count = float(n_a) * float(n_b)

            def fast_stats_fn(a, bv, mbv, ibv):
                del mbv, ibv  # every row valid by caller contract
                s = pallas_pair_sum_any(
                    a, bv, kernel=kernel,
                    tile_a=tile_a, tile_b=tile_b, interpret=interpret,
                )
                return (
                    s.astype(a.dtype),
                    jnp.asarray(count, a.dtype),
                )

            return fast_stats_fn

        def stats_fn(a, bv, mbv, ibv):
            del ibv
            ma = jnp.ones(a.shape[0], a.dtype) if mask_a is None else mask_a
            s = pallas_masked_pair_sum(
                a, bv, ma, mbv, kernel=kernel,
                tile_a=tile_a, tile_b=tile_b, interpret=interpret,
            )
            # the kernel accumulates in f32 regardless of input dtype;
            # cast back so the ring's scan carry keeps the caller's dtype
            return (
                s.astype(a.dtype),
                (jnp.sum(ma) * jnp.sum(mbv)).astype(a.dtype),
            )

        return stats_fn

    def stats_fn(a, bv, mbv, ibv):
        return pair_tiles.pair_stats(
            kernel, a, bv,
            mask_a=mask_a, mask_b=mbv,
            ids_a=ids_a if use_ids else None,
            ids_b=ibv if use_ids else None,
            tile_a=tile_a, tile_b=tile_b,
        )

    return stats_fn


def _ring_accumulate(stats_fn, a, visiting, *, axis_name, acc):
    """One full rotation of the visiting (b, mask, ids) state around
    ``axis_name``, accumulating tiled pair stats against the resident
    block at every stop. Returns (acc, visiting) with the visiting state
    back at its starting shard (a full cycle is the identity
    permutation), so callers can nest rotations hierarchically.

    Double-buffered [SURVEY §7 "Ring step vs compute overlap"]: the
    ppermute that fetches the NEXT visiting block is issued before the
    current block's tile reduction in program order, and neither depends
    on the other's result, so XLA's latency-hiding scheduler can fly the
    collective-permute over the reduction (async collective-permute on
    TPU). The rotated state rides the scan carry as the second buffer."""
    n_shards = lax.axis_size(axis_name)

    def step(carry, _):
        (s, c), vis = carry
        bv, mbv, ibv = vis
        nxt = _rotate(vis, axis_name)      # in flight during the reduction
        ds, dc = stats_fn(a, bv, mbv, ibv)
        return ((s + ds, c + dc), nxt), None

    (acc, visiting), _ = lax.scan(
        step, (acc, visiting), None, length=n_shards
    )
    return acc, visiting


def ring_pair_stats(
    kernel,
    a: jnp.ndarray,
    b: jnp.ndarray,
    mask_a: Optional[jnp.ndarray] = None,
    mask_b: Optional[jnp.ndarray] = None,
    ids_a: Optional[jnp.ndarray] = None,
    ids_b: Optional[jnp.ndarray] = None,
    *,
    axis_name: str,
    tile_a: int = 1024,
    tile_b: int = 1024,
    impl: str = "xla",
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Global (sum, count) of h over ALL cross- and within-shard pairs.

    a, b: this shard's blocks of the two samples (one-sample statistics
    pass the same block with its ids). The b-side block (with its mask
    and ids) rotates around the ring; the a-side stays resident.

    impl: "xla" (checkpointed tile scan) or "pallas" (mask-aware Pallas
    kernel for diff kernels without ids; anything else falls back to
    XLA). Pallas tiles are (tile_a, tile_b) directly.

    Returns the SAME (sum, count) on every shard (psum'd), equal to the
    single-device pair_stats over the concatenated data — the ring
    invariance property tested in tests/test_mesh_backend.py.

    Passing mask_a=mask_b=None is a trace-time PROMISE that every row is
    valid on every shard (blocks are symmetric across the ring), which
    unlocks the unmasked Pallas fast path when block sizes divide the
    tiles — callers with padding anywhere must pass real masks.
    """
    if (ids_a is None) != (ids_b is None):
        raise ValueError(
            "ring_pair_stats needs BOTH ids_a and ids_b (or neither); "
            "a lone ids side would silently mis-exclude pairs"
        )
    dtype = a.dtype
    mb = jnp.ones(b.shape[0], dtype) if mask_b is None else mask_b
    use_ids = ids_a is not None
    ib = jnp.zeros(b.shape[0], jnp.int32) if ids_b is None else ids_b.astype(jnp.int32)

    stats_fn = _make_stats_fn(
        kernel, mask_a, ids_a,
        tile_a=tile_a, tile_b=tile_b, use_ids=use_ids, impl=impl,
        interpret=interpret,
        no_masks=mask_a is None and mask_b is None,
        n_a=a.shape[0], n_b=b.shape[0],
    )
    (s, c), _ = _ring_accumulate(
        stats_fn, a, (b, mb, ib),
        axis_name=axis_name,
        acc=(jnp.zeros((), dtype), jnp.zeros((), dtype)),
    )
    return lax.psum(s, axis_name), lax.psum(c, axis_name)


def ring_pair_stats_2d(
    kernel,
    a: jnp.ndarray,
    b: jnp.ndarray,
    mask_a: Optional[jnp.ndarray] = None,
    mask_b: Optional[jnp.ndarray] = None,
    ids_a: Optional[jnp.ndarray] = None,
    ids_b: Optional[jnp.ndarray] = None,
    *,
    ici_axis: str,
    dcn_axis: str,
    tile_a: int = 1024,
    tile_b: int = 1024,
    impl: str = "xla",
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Hierarchical cross-shard all-pairs over a 2-D (dcn, ici) mesh —
    the multi-host layout of [SURVEY §5.8]: chips within a host/pod slice
    are connected by fast ICI; hosts by slow DCN.

    Double ring, communication-hierarchy-aware: the visiting block does a
    FULL ici rotation (fast, I-1 hops per cycle) for every ONE dcn
    rotation (slow, D-1 hops total), so each device sees every b block
    while DCN carries only D-1 block transfers per device instead of the
    D*I-1 a flat ring over all devices would route across host
    boundaries. Same invariance contract as ring_pair_stats: returns the
    (sum, count) of the single-device computation, psum'd over both axes.
    """
    if (ids_a is None) != (ids_b is None):
        raise ValueError(
            "ring_pair_stats_2d needs BOTH ids_a and ids_b (or neither)"
        )
    dtype = a.dtype
    mb = jnp.ones(b.shape[0], dtype) if mask_b is None else mask_b
    use_ids = ids_a is not None
    ib = jnp.zeros(b.shape[0], jnp.int32) if ids_b is None else ids_b.astype(jnp.int32)
    n_dcn = lax.axis_size(dcn_axis)

    stats_fn = _make_stats_fn(
        kernel, mask_a, ids_a,
        tile_a=tile_a, tile_b=tile_b, use_ids=use_ids, impl=impl,
        interpret=interpret,
        no_masks=mask_a is None and mask_b is None,
        n_a=a.shape[0], n_b=b.shape[0],
    )

    def outer(carry, _):
        acc, vis = carry
        acc, vis = _ring_accumulate(
            stats_fn, a, vis, axis_name=ici_axis, acc=acc,
        )
        return (acc, _rotate(vis, dcn_axis)), None

    init = (
        (jnp.zeros((), dtype), jnp.zeros((), dtype)),
        (b, mb, ib),
    )
    ((s, c), _), _ = lax.scan(outer, init, None, length=n_dcn)
    both = (dcn_axis, ici_axis)
    return lax.psum(s, both), lax.psum(c, both)


def ring_triplet_stats(
    kernel,
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask_x: Optional[jnp.ndarray] = None,
    mask_y: Optional[jnp.ndarray] = None,
    ids_x: Optional[jnp.ndarray] = None,
    *,
    axis_name: str,
    tile: int = 64,
    impl: str = "xla",
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Global (sum, count) of h(x_i, x_j, y_k) over ALL triplets with
    i != j (by id) — a DOUBLE ring: the positives block x rotates in the
    outer loop, and for each of its N positions the negatives block y
    completes a full inner rotation, so all (shard_i, shard_j, shard_k)
    block triples are visited (N^2 communication steps).

    Anchors stay resident; O(N^2) ppermutes of small blocks ride the ICI
    ring while each step runs the O(m^3) tile reduction.

    ids_x is REQUIRED: anchor/positive exclusion must use GLOBAL row ids
    — a per-shard local arange would spuriously exclude cross-shard
    (anchor, positive) combinations that share a local offset.
    """
    if ids_x is None:
        raise ValueError(
            "ring_triplet_stats requires global ids_x; per-shard local "
            "indices would mis-exclude cross-shard anchor/positive pairs"
        )
    n_shards = lax.axis_size(axis_name)
    dtype = x.dtype
    mx = jnp.ones(x.shape[0], dtype) if mask_x is None else mask_x
    my = jnp.ones(y.shape[0], dtype) if mask_y is None else mask_y
    ix = ids_x.astype(jnp.int32)
    perm = _ring_perm(axis_name)

    # anchors: resident block (x, mx, ix); positives: visiting (p); negatives: visiting (ynext)
    def inner_step(carry, _, p, mp, ip):
        s, c, yv, myv = carry
        ds, dc = _triplet_block(kernel, x, mx, ix, p, mp, ip, yv, myv,
                                tile, impl, interpret)
        yv = lax.ppermute(yv, axis_name, perm)
        myv = lax.ppermute(myv, axis_name, perm)
        return (s + ds, c + dc, yv, myv), None

    def outer_step(carry, _):
        s, c, p, mp, ip, yv, myv = carry
        import functools

        (s, c, yv, myv), _ = lax.scan(
            functools.partial(inner_step, p=p, mp=mp, ip=ip),
            (s, c, yv, myv),
            None,
            length=n_shards,
        )
        p = lax.ppermute(p, axis_name, perm)
        mp = lax.ppermute(mp, axis_name, perm)
        ip = lax.ppermute(ip, axis_name, perm)
        return (s, c, p, mp, ip, yv, myv), None

    init = (
        jnp.zeros((), dtype), jnp.zeros((), dtype),
        x, mx, ix, y, my,
    )
    (s, c, *_), _ = lax.scan(outer_step, init, None, length=n_shards)
    return lax.psum(s, axis_name), lax.psum(c, axis_name)


def _triplet_block(kernel, a, ma, ia, p, mp, ip, yk, mk, tile,
                   impl="xla", interpret=None):
    """One double-ring step: the generalized triplet reduction over
    (resident anchors, visiting positives, visiting negatives).
    impl="pallas" routes the built-in sqdist triplet kernels through
    the distance factorization (ops.pallas_triplets) — MXU distance
    matmuls + the hand-tiled pair kernel per anchor [VERDICT r3
    next #3]; anything else keeps the XLA tile scan."""
    from tuplewise_tpu.ops.pallas_triplets import triplet_stats_best

    return triplet_stats_best(
        kernel, a, yk, mask_x=ma, mask_y=mk, ids_x=ia,
        positives=p, mask_p=mp, ids_p=ip, tile=tile,
        impl=impl, interpret=interpret,
    )


def _hier_cycle(state, axes, step_fn, acc):
    """Visit all N = prod(axis sizes) ring positions of ``state``:
    nested scans rotate over the LAST axis innermost (fast/ICI) and hop
    earlier axes once per completed inner cycle (slow/DCN) — so a full
    cycle is the identity permutation and cross-host hops are minimal.
    ``step_fn(acc, state) -> acc`` runs at every position."""
    ax, rest = axes[0], axes[1:]

    def body(carry, _):
        acc, st = carry
        if rest:
            acc, st = _hier_cycle(st, rest, step_fn, acc)
        else:
            acc = step_fn(acc, st)
        return (acc, _rotate(st, ax)), None

    (acc, state), _ = lax.scan(
        body, (acc, state), None, length=lax.axis_size(ax)
    )
    return acc, state


def ring_triplet_stats_2d(
    kernel,
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask_x: Optional[jnp.ndarray] = None,
    mask_y: Optional[jnp.ndarray] = None,
    ids_x: Optional[jnp.ndarray] = None,
    *,
    ici_axis: str,
    dcn_axis: str,
    tile: int = 64,
    impl: str = "xla",
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Degree-3 complete statistic over a 2-D (dcn, ici) mesh: the
    TRIPLE-nested hierarchical ring. Anchors stay resident; the
    positives block walks all N = D*I ring positions (ici-inner,
    dcn-outer), and for each position the negatives block completes a
    full hierarchical cycle — N^2 compute steps, with DCN crossed only
    once per completed ICI cycle at either level. Same invariance
    contract as the 1-D ring_triplet_stats.

    ids_x is REQUIRED (global row ids) for anchor/positive exclusion,
    exactly as in the 1-D version.
    """
    if ids_x is None:
        raise ValueError(
            "ring_triplet_stats_2d requires global ids_x; per-shard "
            "local indices would mis-exclude cross-shard pairs"
        )
    dtype = x.dtype
    mx = jnp.ones(x.shape[0], dtype) if mask_x is None else mask_x
    my = jnp.ones(y.shape[0], dtype) if mask_y is None else mask_y
    ix = ids_x.astype(jnp.int32)
    axes = (dcn_axis, ici_axis)

    def at_p_position(acc, p_state):
        p, mp, ip = p_state

        def at_y_position(acc2, y_state):
            yv, myv = y_state
            s, c = acc2
            ds, dc = _triplet_block(
                kernel, x, mx, ix, p, mp, ip, yv, myv, tile,
                impl, interpret,
            )
            return (s + ds, c + dc)

        acc, _ = _hier_cycle((y, my), axes, at_y_position, acc)
        return acc

    init = (jnp.zeros((), dtype), jnp.zeros((), dtype))
    (s, c), _ = _hier_cycle((x, mx, ix), axes, at_p_position, init)
    return lax.psum(s, axes), lax.psum(c, axes)
