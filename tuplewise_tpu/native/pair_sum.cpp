// Native pair-kernel reduction engine for the CPU backend family.
//
// The TPU compute path is JAX/XLA/Pallas (ops/); this C++ engine is the
// native runtime for the host-side reference/serial path: the same
// blockwise streaming reduction as backends/numpy_backend.py, compiled
// with -O3 and parallelized over rows with OpenMP when available.
//
// Determinism: each row's inner reduction is sequential, per-row results
// land in a row_sums array indexed by row, and the final fold over rows
// is a sequential Kahan sum — so the result is independent of thread
// scheduling and reproducible run-to-run.
//
// Kernel ids mirror ops/kernels.py exactly:
//   0 = auc       g(d) = 1{d>0} + 0.5*1{d==0}
//   1 = hinge     g(d) = max(0, 1 - d)
//   2 = logistic  g(d) = log(1 + exp(-d))   (stable softplus)
//
// Exclusion semantics match NumpyBackend._pair_stats: when use_ids is
// set, grid cells with ids_a[i] == ids_b[j] are skipped (one-sample
// diagonal and with-replacement duplicates).

#include <cmath>
#include <cstdint>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

inline double softplus_neg(double d) {
    // log(1 + exp(-d)), stable for any d
    if (d > 0.0) {
        return std::log1p(std::exp(-d));
    }
    return -d + std::log1p(std::exp(d));
}

inline double eval_diff(int kernel_id, double d) {
    switch (kernel_id) {
        case 0:  // auc indicator with half-weight ties
            return d > 0.0 ? 1.0 : (d == 0.0 ? 0.5 : 0.0);
        case 1:  // hinge
            return d < 1.0 ? 1.0 - d : 0.0;
        default:  // 2: logistic
            return softplus_neg(d);
    }
}

struct Acc {
    double sum = 0.0;
    int64_t count = 0;
};

// Sequential Kahan fold of per-row partials (deterministic).
void fold_rows(const std::vector<Acc>& rows, double* out_sum,
               int64_t* out_count) {
    double s = 0.0, comp = 0.0;
    int64_t c = 0;
    for (const Acc& r : rows) {
        double y = r.sum - comp;
        double t = s + y;
        comp = (t - s) - y;
        s = t;
        c += r.count;
    }
    *out_sum = s - comp;
    *out_count = c;
}

}  // namespace

extern "C" {

// (sum, count) of g(a_i - b_j) over the (masked-by-ids) pair grid.
void pair_stats_diff(int kernel_id, const double* a, int64_t n1,
                     const double* b, int64_t n2, const int64_t* ids_a,
                     const int64_t* ids_b, int use_ids, double* out_sum,
                     int64_t* out_count) {
    std::vector<Acc> rows(static_cast<size_t>(n1));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n1; ++i) {
        const double ai = a[i];
        const int64_t ia = use_ids ? ids_a[i] : 0;
        double s = 0.0, comp = 0.0;
        int64_t c = 0;
        for (int64_t j = 0; j < n2; ++j) {
            if (use_ids && ia == ids_b[j]) continue;
            const double v = eval_diff(kernel_id, ai - b[j]);
            double y = v - comp;
            double t = s + y;
            comp = (t - s) - y;
            s = t;
            ++c;
        }
        rows[static_cast<size_t>(i)].sum = s - comp;
        rows[static_cast<size_t>(i)].count = c;
    }
    fold_rows(rows, out_sum, out_count);
}

// (sum, count) of the scatter kernel h(x, x') = ||x - x'||^2 / 2 over
// the [n1, n2] grid of d-dimensional rows, with id exclusion.
void pair_stats_scatter(const double* a, int64_t n1, const double* b,
                        int64_t n2, int64_t dim, const int64_t* ids_a,
                        const int64_t* ids_b, int use_ids, double* out_sum,
                        int64_t* out_count) {
    std::vector<Acc> rows(static_cast<size_t>(n1));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n1; ++i) {
        const double* xi = a + i * dim;
        const int64_t ia = use_ids ? ids_a[i] : 0;
        double s = 0.0, comp = 0.0;
        int64_t c = 0;
        for (int64_t j = 0; j < n2; ++j) {
            if (use_ids && ia == ids_b[j]) continue;
            const double* yj = b + j * dim;
            double d2 = 0.0;
            for (int64_t k = 0; k < dim; ++k) {
                const double diff = xi[k] - yj[k];
                d2 += diff * diff;
            }
            const double v = 0.5 * d2;
            double y = v - comp;
            double t = s + y;
            comp = (t - s) - y;
            s = t;
            ++c;
        }
        rows[static_cast<size_t>(i)].sum = s - comp;
        rows[static_cast<size_t>(i)].count = c;
    }
    fold_rows(rows, out_sum, out_count);
}

// (sum, count) of the degree-3 metric-learning kernel
// h(x_i, x_j, y_k) over ids_x[i] != ids_x[j] (anchor/positive
// exclusion), all k — mirroring NumpyBackend._triplet_stats exactly.
// kernel_id: 0 = indicator 1{d(a,n) > d(a,p) + margin},
//            1 = hinge max(0, margin + d(a,p) - d(a,n)),
// with d = SQUARED euclidean distance (ops/kernels.py semantics).
// Per anchor i, the n2 anchor-negative distances are computed once
// (O(n2 d)) and reused across all positives j, so the triple loop
// costs O(n1^2 n2 + n1 n2 d) instead of O(n1^2 n2 d).
void triplet_stats_native(int kernel_id, double margin, const double* x,
                          int64_t n1, const double* y, int64_t n2,
                          int64_t dim, const int64_t* ids_x,
                          double* out_sum, int64_t* out_count) {
    std::vector<Acc> rows(static_cast<size_t>(n1));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n1; ++i) {
        const double* xi = x + i * dim;
        std::vector<double> dan(static_cast<size_t>(n2));
        for (int64_t kk = 0; kk < n2; ++kk) {
            const double* yk = y + kk * dim;
            double d2 = 0.0;
            for (int64_t d = 0; d < dim; ++d) {
                const double diff = xi[d] - yk[d];
                d2 += diff * diff;
            }
            dan[static_cast<size_t>(kk)] = d2;
        }
        double s = 0.0, comp = 0.0;
        int64_t c = 0;
        for (int64_t j = 0; j < n1; ++j) {
            if (ids_x[j] == ids_x[i]) continue;
            const double* xj = x + j * dim;
            double dap = 0.0;
            for (int64_t d = 0; d < dim; ++d) {
                const double diff = xi[d] - xj[d];
                dap += diff * diff;
            }
            // plain f64 sum over the n2 negatives (values are O(1), so
            // a block of <=1e7 terms keeps ~1e-10 relative error), then
            // ONE Kahan add per (i, j): a Kahan chain in the innermost
            // loop would serialize it on the compensation dependency
            double block = 0.0;
            if (kernel_id == 0) {
                const double thresh = dap + margin;
                for (int64_t kk = 0; kk < n2; ++kk) {
                    block += dan[static_cast<size_t>(kk)] > thresh
                                 ? 1.0 : 0.0;
                }
            } else {
                const double base = margin + dap;
                for (int64_t kk = 0; kk < n2; ++kk) {
                    const double h = base - dan[static_cast<size_t>(kk)];
                    block += h > 0.0 ? h : 0.0;
                }
            }
            double yv = block - comp;
            double t = s + yv;
            comp = (t - s) - yv;
            s = t;
            c += n2;
        }
        rows[static_cast<size_t>(i)].sum = s - comp;
        rows[static_cast<size_t>(i)].count = c;
    }
    fold_rows(rows, out_sum, out_count);
}

int native_num_threads() {
#ifdef _OPENMP
    return omp_get_max_threads();
#else
    return 1;
#endif
}

}  // extern "C"
