// Native pair-kernel reduction engine for the CPU backend family.
//
// The TPU compute path is JAX/XLA/Pallas (ops/); this C++ engine is the
// native runtime for the host-side reference/serial path: the same
// blockwise streaming reduction as backends/numpy_backend.py, compiled
// with -O3 and parallelized over rows with OpenMP when available.
//
// Determinism: each row's inner reduction is sequential, per-row results
// land in a row_sums array indexed by row, and the final fold over rows
// is a sequential Kahan sum — so the result is independent of thread
// scheduling and reproducible run-to-run.
//
// Kernel ids mirror ops/kernels.py exactly:
//   0 = auc       g(d) = 1{d>0} + 0.5*1{d==0}
//   1 = hinge     g(d) = max(0, 1 - d)
//   2 = logistic  g(d) = log(1 + exp(-d))   (stable softplus)
//
// Exclusion semantics match NumpyBackend._pair_stats: when use_ids is
// set, grid cells with ids_a[i] == ids_b[j] are skipped (one-sample
// diagonal and with-replacement duplicates).

#include <cmath>
#include <cstdint>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

inline double softplus_neg(double d) {
    // log(1 + exp(-d)), stable for any d
    if (d > 0.0) {
        return std::log1p(std::exp(-d));
    }
    return -d + std::log1p(std::exp(d));
}

inline double eval_diff(int kernel_id, double d) {
    switch (kernel_id) {
        case 0:  // auc indicator with half-weight ties
            return d > 0.0 ? 1.0 : (d == 0.0 ? 0.5 : 0.0);
        case 1:  // hinge
            return d < 1.0 ? 1.0 - d : 0.0;
        default:  // 2: logistic
            return softplus_neg(d);
    }
}

struct Acc {
    double sum = 0.0;
    int64_t count = 0;
};

// Sequential Kahan fold of per-row partials (deterministic).
void fold_rows(const std::vector<Acc>& rows, double* out_sum,
               int64_t* out_count) {
    double s = 0.0, comp = 0.0;
    int64_t c = 0;
    for (const Acc& r : rows) {
        double y = r.sum - comp;
        double t = s + y;
        comp = (t - s) - y;
        s = t;
        c += r.count;
    }
    *out_sum = s - comp;
    *out_count = c;
}

}  // namespace

extern "C" {

// (sum, count) of g(a_i - b_j) over the (masked-by-ids) pair grid.
void pair_stats_diff(int kernel_id, const double* a, int64_t n1,
                     const double* b, int64_t n2, const int64_t* ids_a,
                     const int64_t* ids_b, int use_ids, double* out_sum,
                     int64_t* out_count) {
    std::vector<Acc> rows(static_cast<size_t>(n1));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n1; ++i) {
        const double ai = a[i];
        const int64_t ia = use_ids ? ids_a[i] : 0;
        double s = 0.0, comp = 0.0;
        int64_t c = 0;
        for (int64_t j = 0; j < n2; ++j) {
            if (use_ids && ia == ids_b[j]) continue;
            const double v = eval_diff(kernel_id, ai - b[j]);
            double y = v - comp;
            double t = s + y;
            comp = (t - s) - y;
            s = t;
            ++c;
        }
        rows[static_cast<size_t>(i)].sum = s - comp;
        rows[static_cast<size_t>(i)].count = c;
    }
    fold_rows(rows, out_sum, out_count);
}

// (sum, count) of the scatter kernel h(x, x') = ||x - x'||^2 / 2 over
// the [n1, n2] grid of d-dimensional rows, with id exclusion.
void pair_stats_scatter(const double* a, int64_t n1, const double* b,
                        int64_t n2, int64_t dim, const int64_t* ids_a,
                        const int64_t* ids_b, int use_ids, double* out_sum,
                        int64_t* out_count) {
    std::vector<Acc> rows(static_cast<size_t>(n1));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n1; ++i) {
        const double* xi = a + i * dim;
        const int64_t ia = use_ids ? ids_a[i] : 0;
        double s = 0.0, comp = 0.0;
        int64_t c = 0;
        for (int64_t j = 0; j < n2; ++j) {
            if (use_ids && ia == ids_b[j]) continue;
            const double* yj = b + j * dim;
            double d2 = 0.0;
            for (int64_t k = 0; k < dim; ++k) {
                const double diff = xi[k] - yj[k];
                d2 += diff * diff;
            }
            const double v = 0.5 * d2;
            double y = v - comp;
            double t = s + y;
            comp = (t - s) - y;
            s = t;
            ++c;
        }
        rows[static_cast<size_t>(i)].sum = s - comp;
        rows[static_cast<size_t>(i)].count = c;
    }
    fold_rows(rows, out_sum, out_count);
}

int native_num_threads() {
#ifdef _OPENMP
    return omp_get_max_threads();
#else
    return 1;
#endif
}

}  // extern "C"
