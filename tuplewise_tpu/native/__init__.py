"""Native (C++) runtime components, loaded via ctypes — no pybind11.

``load_pair_lib()`` compiles ``pair_sum.cpp`` on first use with the
system ``g++`` (``-O3 -fopenmp``, falling back to no OpenMP, then to no
native library at all) and caches the shared object under ``_build/``
keyed by a source hash, so rebuilds happen only when the source changes.
Everything degrades gracefully: callers get ``None`` when no compiler is
available and fall back to the pure-NumPy path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "pair_sum.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")

_lock = threading.Lock()
_cached: Optional[object] = None
_tried = False


def _source_tag() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _compile(out_path: str) -> bool:
    flag_sets = (
        ["-O3", "-march=native", "-fopenmp"],
        ["-O3", "-march=native"],
        ["-O3"],
    )
    for flags in flag_sets:
        cmd = ["g++", "-std=c++17", "-shared", "-fPIC", *flags,
               _SRC, "-o", out_path]
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired):
            return False
        if r.returncode == 0:
            return True
        print(
            f"[tuplewise_tpu.native] g++ {' '.join(flags)} failed: "
            f"{r.stderr.strip()[:500]}",
            file=sys.stderr,
        )
    return False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_i64p = ctypes.POINTER(ctypes.c_int64)
    c_dp = ctypes.POINTER(ctypes.c_double)
    lib.pair_stats_diff.argtypes = [
        ctypes.c_int, c_dp, ctypes.c_int64, c_dp, ctypes.c_int64,
        c_i64p, c_i64p, ctypes.c_int, c_dp, c_i64p,
    ]
    lib.pair_stats_diff.restype = None
    lib.pair_stats_scatter.argtypes = [
        c_dp, ctypes.c_int64, c_dp, ctypes.c_int64, ctypes.c_int64,
        c_i64p, c_i64p, ctypes.c_int, c_dp, c_i64p,
    ]
    lib.pair_stats_scatter.restype = None
    lib.triplet_stats_native.argtypes = [
        ctypes.c_int, ctypes.c_double, c_dp, ctypes.c_int64, c_dp,
        ctypes.c_int64, ctypes.c_int64, c_i64p, c_dp, c_i64p,
    ]
    lib.triplet_stats_native.restype = None
    lib.native_num_threads.argtypes = []
    lib.native_num_threads.restype = ctypes.c_int
    return lib


def load_pair_lib() -> Optional[ctypes.CDLL]:
    """The compiled pair-reduction library, or None if unavailable.

    Thread-safe; compiles at most once per process."""
    global _cached, _tried
    with _lock:
        if _tried:
            return _cached
        _tried = True
        so = os.path.join(_BUILD_DIR, f"pair_sum_{_source_tag()}.so")
        if not os.path.exists(so):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            tmp = so + f".tmp{os.getpid()}"
            if not _compile(tmp):
                return None
            os.replace(tmp, so)
        try:
            _cached = _configure(ctypes.CDLL(so))
        except OSError as e:
            print(f"[tuplewise_tpu.native] load failed: {e}",
                  file=sys.stderr)
            _cached = None
        return _cached
