from tuplewise_tpu.data.synthetic import make_gaussians, true_gaussian_auc
from tuplewise_tpu.data.loaders import load_adult, load_mnist_embeddings

__all__ = [
    "make_gaussians",
    "true_gaussian_auc",
    "load_adult",
    "load_mnist_embeddings",
]
