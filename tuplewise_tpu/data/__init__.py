from tuplewise_tpu.data.synthetic import make_gaussians, true_gaussian_auc
from tuplewise_tpu.data.loaders import load_adult, load_mnist_embeddings
from tuplewise_tpu.data.splits import (
    load_adult_splits,
    make_gaussian_splits,
    standardize_pair,
    stratified_split,
)

__all__ = [
    "make_gaussians",
    "true_gaussian_auc",
    "load_adult",
    "load_adult_splits",
    "load_mnist_embeddings",
    "make_gaussian_splits",
    "standardize_pair",
    "stratified_split",
]
