"""L0 — real-dataset loaders (UCI Adult, MNIST embeddings).

BASELINE configs 2 and 4 name UCI Adult (bipartite ranking) and MNIST
embeddings (degree-3 triplet kernels) [SURVEY §3 "Dataset loaders"].

This environment has **zero network egress**, so each loader:

1. first looks for a real on-disk copy (``path=`` argument or
   ``TUPLEWISE_DATA_DIR``) — either a pre-converted ``.npz`` blob OR the
   CANONICAL raw distribution files (``adult.data`` CSV for UCI Adult;
   ``train-images-idx3-ubyte[.gz]`` / ``train-labels-idx1-ubyte[.gz]``
   for MNIST, embedded via a deterministic PCA projection), and
2. otherwise falls back to a *deterministic synthetic surrogate* with the
   same schema/shape statistics, clearly marked via the returned
   ``meta["synthetic"]`` flag.

The surrogate keeps every downstream code path (loaders -> partitioner ->
estimators -> learner) runnable and testable; dropping the real files
into ``TUPLEWISE_DATA_DIR`` requires no code change.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

_ADULT_DIM = 14  # UCI Adult: 6 continuous + 8 categorical attributes
_MNIST_EMB_DIM = 32
_MNIST_CLASSES = 10

# adult.data column schema (UCI census-income): position -> continuous?
_ADULT_CONTINUOUS = (0, 2, 4, 10, 11, 12)   # age, fnlwgt, education-num,
#                                             capital-gain/loss, hours/week
_ADULT_N_COLS = 15                           # 14 attributes + label

# Canonical category sets per the UCI adult.names spec, sorted. Encoding
# against the FULL canonical vocabulary (not the categories that happen
# to appear in one file) keeps the design matrix aligned across
# adult.data and adult.test — e.g. 'Holand-Netherlands' occurs once in
# adult.data and never in adult.test; a per-file vocabulary would shift
# every later one-hot column between the two.
_ADULT_CATEGORIES = {
    1: (  # workclass
        "Federal-gov", "Local-gov", "Never-worked", "Private",
        "Self-emp-inc", "Self-emp-not-inc", "State-gov", "Without-pay",
    ),
    3: (  # education
        "10th", "11th", "12th", "1st-4th", "5th-6th", "7th-8th", "9th",
        "Assoc-acdm", "Assoc-voc", "Bachelors", "Doctorate", "HS-grad",
        "Masters", "Preschool", "Prof-school", "Some-college",
    ),
    5: (  # marital-status
        "Divorced", "Married-AF-spouse", "Married-civ-spouse",
        "Married-spouse-absent", "Never-married", "Separated", "Widowed",
    ),
    6: (  # occupation
        "Adm-clerical", "Armed-Forces", "Craft-repair", "Exec-managerial",
        "Farming-fishing", "Handlers-cleaners", "Machine-op-inspct",
        "Other-service", "Priv-house-serv", "Prof-specialty",
        "Protective-serv", "Sales", "Tech-support", "Transport-moving",
    ),
    7: (  # relationship
        "Husband", "Not-in-family", "Other-relative", "Own-child",
        "Unmarried", "Wife",
    ),
    8: (  # race
        "Amer-Indian-Eskimo", "Asian-Pac-Islander", "Black", "Other",
        "White",
    ),
    9: ("Female", "Male"),  # sex
    13: (  # native-country
        "Cambodia", "Canada", "China", "Columbia", "Cuba",
        "Dominican-Republic", "Ecuador", "El-Salvador", "England",
        "France", "Germany", "Greece", "Guatemala", "Haiti",
        "Holand-Netherlands", "Honduras", "Hong", "Hungary", "India",
        "Iran", "Ireland", "Italy", "Jamaica", "Japan", "Laos", "Mexico",
        "Nicaragua", "Outlying-US(Guam-USVI-etc)", "Peru", "Philippines",
        "Poland", "Portugal", "Puerto-Rico", "Scotland", "South",
        "Taiwan", "Thailand", "Trinadad&Tobago", "United-States",
        "Vietnam", "Yugoslavia",
    ),
}


def _data_dir() -> str:
    return os.environ.get("TUPLEWISE_DATA_DIR", os.path.join(os.path.dirname(__file__), "_cache"))


def parse_adult_csv(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Parse the canonical ``adult.data`` / ``adult.test`` CSV.

    Schema: 6 continuous + 8 categorical attributes, comma-separated
    with a ``<=50K`` / ``>50K`` label (trailing '.' in adult.test).
    Rows containing missing values ('?') are dropped — the standard
    preprocessing for this dataset. Categoricals are one-hot encoded
    against the CANONICAL UCI vocabulary (``_ADULT_CATEGORIES``), so
    adult.data and adult.test yield identically laid-out design
    matrices even though some categories appear in only one file. A
    column whose values fall outside the canonical set (toy fixtures)
    falls back to that file's own sorted categories.

    Returns (X [n, d] float64 un-standardized, y [n] int {0, 1}).
    """
    rows = []
    with open(path) as f:
        for line in f:
            parts = [p.strip() for p in line.strip().rstrip(".").split(",")]
            if len(parts) != _ADULT_N_COLS or "?" in parts:
                continue
            rows.append(parts)
    if not rows:
        raise ValueError(f"no parseable rows in {path!r}")
    cols = list(zip(*rows))
    blocks = []
    for c in range(_ADULT_N_COLS - 1):
        if c in _ADULT_CONTINUOUS:
            blocks.append(np.asarray(cols[c], float)[:, None])
        else:
            seen = set(cols[c])
            canon = _ADULT_CATEGORIES[c]
            if seen <= set(canon):
                cats = canon
            else:
                # out-of-vocabulary values: this file gets its OWN
                # vocabulary for the column, which breaks alignment
                # with any canonically-encoded file — say so loudly.
                import warnings

                warnings.warn(
                    f"{path!r} column {c}: non-canonical categories "
                    f"{sorted(seen - set(canon))!r}; using a file-local "
                    f"vocabulary (design matrix will NOT align with "
                    f"canonically-encoded adult files)",
                    stacklevel=2,
                )
                cats = tuple(sorted(seen))
            code = {v: k for k, v in enumerate(cats)}
            idx = np.asarray([code[v] for v in cols[c]])
            onehot = np.zeros((len(idx), len(cats)))
            onehot[np.arange(len(idx)), idx] = 1.0
            blocks.append(onehot)
    X = np.concatenate(blocks, axis=1)
    y = np.asarray([1 if v.startswith(">50K") else 0 for v in cols[-1]])
    return X, y


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX-format file (the canonical MNIST distribution
    format), transparently gunzipping ``.gz``. Magic: 2 zero bytes,
    dtype code (0x08 = uint8), ndim, then ndim big-endian u32 dims."""
    opener = gzip.open if path.endswith(".gz") else open

    def read_exact(f, k):
        buf = f.read(k)
        if len(buf) != k:  # truncated copy — keep the ValueError contract
            raise ValueError(
                f"{path!r}: truncated IDX header "
                f"(wanted {k} bytes, got {len(buf)})"
            )
        return buf

    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", read_exact(f, 4))
        if zero != 0 or dtype_code != 0x08:
            raise ValueError(
                f"{path!r} is not a uint8 IDX file "
                f"(magic {zero:#x}/{dtype_code:#x})"
            )
        dims = struct.unpack(f">{ndim}I", read_exact(f, 4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    if data.size != int(np.prod(dims)):
        raise ValueError(
            f"{path!r}: payload {data.size} != header dims {dims}"
        )
    return data.reshape(dims)


def _find_idx_pair(dirs) -> Optional[Tuple[str, str]]:
    for d in dirs:
        for suffix in ("", ".gz"):
            imgs = os.path.join(d, f"train-images-idx3-ubyte{suffix}")
            labs = os.path.join(d, f"train-labels-idx1-ubyte{suffix}")
            if os.path.exists(imgs) and os.path.exists(labs):
                return imgs, labs
    return None


def mnist_pca_embeddings(
    images: np.ndarray, dim: int = _MNIST_EMB_DIM
) -> np.ndarray:
    """Deterministic PCA embedding of raw [n, 28, 28] uint8 images:
    center, project onto the top ``dim`` eigenvectors of the pixel
    covariance (sign-fixed so the result is reproducible across BLAS
    implementations), scale to unit average norm."""
    flat = images.reshape(len(images), -1).astype(np.float64) / 255.0
    mu = flat.mean(axis=0)
    centered = flat - mu
    cov = centered.T @ centered / len(flat)
    vals, vecs = np.linalg.eigh(cov)
    top = vecs[:, np.argsort(vals)[::-1][:dim]]
    # sign convention: largest-|component| entry of each PC is positive
    signs = np.sign(top[np.argmax(np.abs(top), axis=0), np.arange(dim)])
    E = centered @ (top * signs)
    return E / (np.linalg.norm(E, axis=1).mean() + 1e-12)


def load_adult(
    path: Optional[str] = None,
    n: int = 32561,
    seed: int = 0,
    standardize: bool = True,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """UCI Adult as a binary task: features, labels in {0, 1}.

    Returns (X [n, d] float64 standardized, y [n] int, meta); pass
    ``standardize=False`` for raw features (the train/test split path
    standardizes with train-side statistics instead — see
    :mod:`tuplewise_tpu.data.splits`). Real-data
    resolution order: ``path=`` (either format) -> ``adult.npz`` (keys
    ``X``, ``y``) -> the canonical ``adult.data``/``adult.csv`` CSV
    parsed by :func:`parse_adult_csv`. With nothing on disk, generates
    a deterministic surrogate: a mixture where the positive class
    (~24%, the real Adult positive rate) is shifted along a random
    direction with heterogeneous per-feature scales — enough structure
    for ranking experiments.
    """
    candidates = [path] if path else []
    candidates += [
        os.path.join(_data_dir(), f)
        for f in ("adult.npz", "adult.data", "adult.csv")
    ]
    for c in candidates:
        if not (c and os.path.exists(c)):
            continue
        if c.endswith(".npz"):
            blob = np.load(c)
            X, y = np.asarray(blob["X"], float), np.asarray(blob["y"], int)
        else:
            X, y = parse_adult_csv(c)
        if len(X) > n:  # honor the requested size on real data too
            keep = np.random.default_rng(seed).choice(len(X), n, replace=False)
            X, y = X[keep], y[keep]
        if standardize:
            X = (X - X.mean(0)) / (X.std(0) + 1e-12)
        return X, y, {"synthetic": False, "source": c}

    rng = np.random.default_rng(seed + 1043)
    d = _ADULT_DIM
    pos_rate = 0.2408
    y = (rng.random(n) < pos_rate).astype(int)
    scales = rng.uniform(0.5, 2.0, size=d)
    direction = rng.standard_normal(d)
    direction /= np.linalg.norm(direction)
    X = rng.standard_normal((n, d)) * scales
    # Mild nonlinear class structure: shift + a curved component.
    X[y == 1] += 1.2 * direction * scales
    X[y == 1, 0] += 0.3 * X[y == 1, 1] ** 2 * 0.1
    if standardize:
        X = (X - X.mean(0)) / (X.std(0) + 1e-12)
    return X, y, {"synthetic": True, "source": "surrogate(adult)"}


def load_mnist_embeddings(
    path: Optional[str] = None,
    n: int = 10000,
    dim: int = _MNIST_EMB_DIM,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """MNIST embeddings for triplet metric-learning statistics.

    Returns (E [n, dim] float64, labels [n] int in [0, 10), meta).
    Real-data resolution order: ``path=`` npz -> ``mnist_embeddings.npz``
    (keys ``E``, ``labels``) -> the canonical raw IDX pair
    ``train-images-idx3-ubyte[.gz]`` / ``train-labels-idx1-ubyte[.gz]``,
    embedded with the deterministic PCA projection
    (:func:`mnist_pca_embeddings`). With nothing on disk, generates
    class-clustered unit-scale embeddings: 10 well-separated class
    centroids with intra-class spread, mimicking a trained embedding's
    geometry.
    """
    candidates = [path] if path else []
    candidates.append(os.path.join(_data_dir(), "mnist_embeddings.npz"))
    for c in candidates:
        if c and os.path.exists(c):
            blob = np.load(c)
            E = np.asarray(blob["E"], float)
            labels = np.asarray(blob["labels"], int)
            if len(E) > n:  # honor the requested size on real data too
                keep = np.random.default_rng(seed).choice(len(E), n, replace=False)
                E, labels = E[keep], labels[keep]
            return E, labels, {"synthetic": False, "source": c}

    idx = _find_idx_pair([_data_dir()])
    if idx is not None:
        imgs, labs = idx
        images = _read_idx(imgs)
        labels = _read_idx(labs).astype(int)
        if images.ndim != 3 or len(images) != len(labels):
            raise ValueError(
                f"IDX pair mismatch: images {images.shape}, "
                f"labels {labels.shape}"
            )
        if len(images) > n:
            keep = np.random.default_rng(seed).choice(
                len(images), n, replace=False
            )
            images, labels = images[keep], labels[keep]
        E = mnist_pca_embeddings(images, dim=min(dim, images[0].size))
        return E, labels, {"synthetic": False, "source": imgs}

    rng = np.random.default_rng(seed + 60283)
    centroids = rng.standard_normal((_MNIST_CLASSES, dim)) * 2.0
    labels = rng.integers(0, _MNIST_CLASSES, size=n)
    E = centroids[labels] + 0.6 * rng.standard_normal((n, dim))
    return E, labels, {"synthetic": True, "source": "surrogate(mnist-emb)"}
