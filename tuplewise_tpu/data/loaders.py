"""L0 — real-dataset loaders (UCI Adult, MNIST embeddings).

BASELINE configs 2 and 4 name UCI Adult (bipartite ranking) and MNIST
embeddings (degree-3 triplet kernels) [SURVEY §3 "Dataset loaders"].

This environment has **zero network egress**, so each loader:

1. first looks for a real on-disk copy (``path=`` argument or
   ``TUPLEWISE_DATA_DIR``), and
2. otherwise falls back to a *deterministic synthetic surrogate* with the
   same schema/shape statistics, clearly marked via the returned
   ``meta["synthetic"]`` flag.

The surrogate keeps every downstream code path (loaders -> partitioner ->
estimators -> learner) runnable and testable; swapping in the real files
requires no code change.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

_ADULT_DIM = 14  # UCI Adult: 6 continuous + 8 categorical attributes
_MNIST_EMB_DIM = 32
_MNIST_CLASSES = 10


def _data_dir() -> str:
    return os.environ.get("TUPLEWISE_DATA_DIR", os.path.join(os.path.dirname(__file__), "_cache"))


def load_adult(
    path: Optional[str] = None,
    n: int = 32561,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """UCI Adult as a binary task: features, labels in {0, 1}.

    Returns (X [n, d] float64 standardized, y [n] int, meta). If no real
    ``adult.npz`` is found (keys ``X``, ``y``), generates a deterministic
    surrogate: a mixture where the positive class (~24%, the real Adult
    positive rate) is shifted along a random direction with heterogeneous
    per-feature scales — enough structure for ranking experiments.
    """
    candidates = [path] if path else []
    candidates.append(os.path.join(_data_dir(), "adult.npz"))
    for c in candidates:
        if c and os.path.exists(c):
            blob = np.load(c)
            X, y = np.asarray(blob["X"], float), np.asarray(blob["y"], int)
            if len(X) > n:  # honor the requested size on real data too
                keep = np.random.default_rng(seed).choice(len(X), n, replace=False)
                X, y = X[keep], y[keep]
            X = (X - X.mean(0)) / (X.std(0) + 1e-12)
            return X, y, {"synthetic": False, "source": c}

    rng = np.random.default_rng(seed + 1043)
    d = _ADULT_DIM
    pos_rate = 0.2408
    y = (rng.random(n) < pos_rate).astype(int)
    scales = rng.uniform(0.5, 2.0, size=d)
    direction = rng.standard_normal(d)
    direction /= np.linalg.norm(direction)
    X = rng.standard_normal((n, d)) * scales
    # Mild nonlinear class structure: shift + a curved component.
    X[y == 1] += 1.2 * direction * scales
    X[y == 1, 0] += 0.3 * X[y == 1, 1] ** 2 * 0.1
    X = (X - X.mean(0)) / (X.std(0) + 1e-12)
    return X, y, {"synthetic": True, "source": "surrogate(adult)"}


def load_mnist_embeddings(
    path: Optional[str] = None,
    n: int = 10000,
    dim: int = _MNIST_EMB_DIM,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """MNIST embeddings for triplet metric-learning statistics.

    Returns (E [n, dim] float64, labels [n] int in [0, 10), meta). If no
    real ``mnist_embeddings.npz`` (keys ``E``, ``labels``) is found,
    generates class-clustered unit-scale embeddings: 10 well-separated
    class centroids with intra-class spread, mimicking a trained
    embedding's geometry.
    """
    candidates = [path] if path else []
    candidates.append(os.path.join(_data_dir(), "mnist_embeddings.npz"))
    for c in candidates:
        if c and os.path.exists(c):
            blob = np.load(c)
            E = np.asarray(blob["E"], float)
            labels = np.asarray(blob["labels"], int)
            if len(E) > n:  # honor the requested size on real data too
                keep = np.random.default_rng(seed).choice(len(E), n, replace=False)
                E, labels = E[keep], labels[keep]
            return E, labels, {"synthetic": False, "source": c}

    rng = np.random.default_rng(seed + 60283)
    centroids = rng.standard_normal((_MNIST_CLASSES, dim)) * 2.0
    labels = rng.integers(0, _MNIST_CLASSES, size=n)
    E = centroids[labels] + 0.6 * rng.standard_normal((n, dim))
    return E, labels, {"synthetic": True, "source": "surrogate(mnist-emb)"}
