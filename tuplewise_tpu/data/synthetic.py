"""L0 — synthetic data generation.

Two-class Gaussians with controllable separation, the calibration dataset
of BASELINE config 1 ("AUC U-statistic on 2-class synthetic Gaussians")
[SURVEY §3 "Synthetic data gen"]. The closed-form true AUC of the optimal
linear score makes these the correctness oracle for every estimator.
"""

from __future__ import annotations

import math

import numpy as np


def make_gaussians(
    n_pos: int,
    n_neg: int,
    dim: int = 1,
    separation: float = 1.0,
    seed: int = 0,
):
    """Two-class isotropic Gaussians separated along the first axis.

    Positives ~ N(separation * e_1, I), negatives ~ N(0, I).

    Returns:
      (X, Y): float64 arrays of shape [n_pos, dim] and [n_neg, dim].
    """
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n_pos, dim))
    X[:, 0] += separation
    Y = rng.standard_normal((n_neg, dim))
    return X, Y


def true_gaussian_auc(separation: float) -> float:
    """Exact AUC of the score s(x) = x_1 under :func:`make_gaussians`.

    s(X) - s(Y) ~ N(separation, 2), so
    AUC = P(s(X) > s(Y)) = Phi(separation / sqrt(2)).
    """
    return 0.5 * (1.0 + math.erf(separation / 2.0))
