"""L0 — train/test splitting for honest held-out evaluation.

SURVEY §3 "Evaluation: test AUC (rank-based)" / §4.4 "AUC-on-test
evaluation": the learning experiments report AUC on data the model never
trained on. Two paths:

* :func:`load_adult_splits` — the canonical UCI split when both
  ``adult.data`` and ``adult.test`` are on disk (the loader's canonical
  vocabulary keeps their design matrices column-aligned); otherwise a
  seeded stratified split of whatever :func:`~.loaders.load_adult`
  resolves (real single file, npz, or surrogate).
* :func:`stratified_split` — the generic utility, class-stratified so
  both classes appear on both sides at the original ratio.

Standardization is always fit on the TRAIN side only and applied to
both (:func:`standardize_pair`) — fitting on pooled data would leak the
test distribution into the features.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from tuplewise_tpu.data.loaders import _data_dir, load_adult, parse_adult_csv


def stratified_split(
    X: np.ndarray,
    y: np.ndarray,
    test_fraction: float = 0.25,
    seed: int = 0,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Seeded class-stratified split into ((X_tr, y_tr), (X_te, y_te)).

    Each label class contributes ``round(test_fraction * count)`` rows
    (at least 1, at most count - 1 so neither side loses a class) to the
    test side; within-class assignment is a seeded permutation.
    """
    X, y = np.asarray(X), np.asarray(y)
    if not 0.0 < test_fraction < 1.0:
        raise ValueError(f"test_fraction must be in (0, 1), got {test_fraction}")
    rng = np.random.default_rng(seed)
    test_mask = np.zeros(len(y), dtype=bool)
    for cls in np.unique(y):
        idx = np.flatnonzero(y == cls)
        if len(idx) < 2:
            raise ValueError(
                f"class {cls!r} has {len(idx)} row(s); need >= 2 to split"
            )
        k = int(np.clip(round(test_fraction * len(idx)), 1, len(idx) - 1))
        test_mask[rng.permutation(idx)[:k]] = True
    tr, te = ~test_mask, test_mask
    return (X[tr], y[tr]), (X[te], y[te])


def standardize_pair(
    X_train: np.ndarray, X_test: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Standardize both blocks with the TRAIN mean/std (no test leakage)."""
    mu = X_train.mean(axis=0)
    sd = X_train.std(axis=0) + 1e-12
    return (X_train - mu) / sd, (X_test - mu) / sd


def load_adult_splits(
    n: int = 32561,
    seed: int = 0,
    test_fraction: float = 0.25,
):
    """UCI Adult as a held-out-evaluation task.

    Returns ``(X_tr, y_tr, X_te, y_te, meta)``. Resolution order:

    1. canonical ``adult.data`` + ``adult.test`` both in
       ``TUPLEWISE_DATA_DIR`` → the official UCI split, column-aligned
       by the canonical vocabulary (``meta["split"] = "adult.test"``);
       train subsampled to ``n`` if larger (test kept whole — it is the
       evaluation yardstick);
    2. whatever :func:`load_adult` resolves (single real file, npz, or
       the deterministic surrogate) → seeded stratified split
       (``meta["split"] = "stratified"``).

    Features are standardized with train statistics in both paths.
    """
    d = _data_dir()
    tr_path = os.path.join(d, "adult.data")
    te_path = os.path.join(d, "adult.test")
    if os.path.exists(tr_path) and os.path.exists(te_path):
        X_tr, y_tr = parse_adult_csv(tr_path)
        X_te, y_te = parse_adult_csv(te_path)
        if len(X_tr) > n:
            keep = np.random.default_rng(seed).choice(
                len(X_tr), n, replace=False
            )
            X_tr, y_tr = X_tr[keep], y_tr[keep]
        X_tr, X_te = standardize_pair(X_tr, X_te)
        meta = {
            "synthetic": False,
            "source": tr_path,
            "split": "adult.test",
            "test_source": te_path,
        }
        return X_tr, y_tr, X_te, y_te, meta

    X, y, meta = load_adult(n=n, seed=seed, standardize=False)
    (X_tr, y_tr), (X_te, y_te) = stratified_split(
        X, y, test_fraction=test_fraction, seed=seed + 7919
    )
    X_tr, X_te = standardize_pair(X_tr, X_te)
    meta = dict(meta, split="stratified", test_fraction=test_fraction)
    return X_tr, y_tr, X_te, y_te, meta


def make_gaussian_splits(
    n_train_per_class: int,
    n_test_per_class: int,
    dim: int = 5,
    separation: float = 1.0,
    seed: int = 0,
):
    """Disjoint train/test Gaussian draws (fresh population samples).

    Returns ``(Xp_tr, Xn_tr, Xp_te, Xn_te)``. One draw of
    ``n_train + n_test`` rows per class, split by position — so the
    test rows are i.i.d. fresh samples, the honest analogue of
    evaluating on the population.
    """
    from tuplewise_tpu.data.synthetic import make_gaussians

    X, Y = make_gaussians(
        n_train_per_class + n_test_per_class,
        n_train_per_class + n_test_per_class,
        dim=dim, separation=separation, seed=seed,
    )
    return (
        X[:n_train_per_class], Y[:n_train_per_class],
        X[n_train_per_class:], Y[n_train_per_class:],
    )
