from tuplewise_tpu.ops.kernels import (
    Kernel,
    auc_kernel,
    hinge_kernel,
    logistic_kernel,
    scatter_kernel,
    triplet_hinge_kernel,
    triplet_indicator_kernel,
    get_kernel,
)

__all__ = [
    "Kernel",
    "auc_kernel",
    "hinge_kernel",
    "logistic_kernel",
    "scatter_kernel",
    "triplet_hinge_kernel",
    "triplet_indicator_kernel",
    "get_kernel",
]
