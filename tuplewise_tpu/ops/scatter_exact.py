"""Exact O(n d) scatter statistics — the feature-kernel analogue of the
rank-AUC fast path [VERDICT r3 next #7].

The scatter kernel h(x, x') = ||x - x'||^2 / 2 is a POLYNOMIAL in its
arguments, so its masked pair sum factorizes into first/second moments:

    sum_{ij} ma_i mb_j h(a_i, b_j)
      = [ (sum ma |a|^2)(sum mb) + (sum mb |b|^2)(sum ma) ] / 2
        - (sum ma a) . (sum mb b)

— no pair grid at all, O(n d) work and O(d) memory where the streamed
tile reduction pays O(n^2 d) MXU time (22.5 TF/s of it; RESULTS §1).
Id exclusion affects only the COUNT: cells with ids_a[i] == ids_b[j]
reference the SAME original row under this library's id discipline
(ids are original-row indices), so their h contribution is exactly 0
and only the pair count must drop them:

    count = (sum ma)(sum mb) - sum_v ca(v) cb(v)

with c.(v) the per-id multiplicities (swr resampling duplicates ids).
The duplicate term is computed ON DEVICE by a sort: identical ids form
runs, and sum r_k^2 = sum_i (2 * offset_in_run_i + 1).

This path serves the built-in scatter kernel only (pair_fn identity,
the builtin_triplet_spec discipline); generic feature kernels (no
polynomial structure) keep the tiled MXU reduction.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from tuplewise_tpu.ops.kernels import Kernel, scatter_kernel


def is_builtin_scatter(kernel: Kernel) -> bool:
    """True when ``kernel`` evaluates the built-in scatter h — by
    pair_fn identity, so a shadowing custom kernel never matches."""
    return (kernel.kind == "pair"
            and kernel.pair_fn is scatter_kernel.pair_fn)


def _dup_pair_count(ids: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """sum_v c(v)^2 over VALID entries: both grid sides hold the SAME
    (ids, mask) arrays in every one-sample call site, so the id-equal
    cell count is the sum of squared multiplicities. Invalid entries
    map to unique negative sentinels (runs of one), contributing
    exactly n_invalid, which is subtracted."""
    n = ids.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    keyed = jnp.where(mask > 0, ids.astype(jnp.int32), -(idx + 1))
    s = jnp.sort(keyed)
    boundary = jnp.concatenate(
        [jnp.ones(1, bool), s[1:] != s[:-1]]
    )
    start = lax.cummax(jnp.where(boundary, idx, 0))
    offset = idx - start                       # 0-based position in run
    total = jnp.sum(2 * offset + 1)            # sum over runs of r^2
    n_invalid = jnp.sum((mask <= 0).astype(jnp.int32))
    return (total - n_invalid).astype(jnp.float32)


def scatter_mesh_stats(
    a: jnp.ndarray,
    ma: jnp.ndarray,
    b: jnp.ndarray,
    mb: jnp.ndarray,
    *,
    axes,
    one_sample: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The moment form inside a shard_map body: per-shard partial
    moments, ONE O(d) psum, closed-form combine — the whole cross-shard
    scatter statistic without the N-step ppermute ring (the moments are
    linear, so sharding commutes with them).

    one_sample relies on the complete packing's contract that global
    ids are DISTINCT (original row indices): the only id-equal cells
    are the diagonal, so count drops psum(sum ma). Same (sum, count)
    as ring_pair_stats on the scatter kernel.
    """
    ca = lax.psum(jnp.sum(ma), axes)
    sq_a = lax.psum(jnp.sum(jnp.sum(a * a, axis=-1) * ma), axes)
    mom_a = lax.psum(jnp.sum(a * ma[:, None], axis=0), axes)
    if one_sample:
        cb, sq_b, mom_b = ca, sq_a, mom_a
    else:
        cb = lax.psum(jnp.sum(mb), axes)
        sq_b = lax.psum(jnp.sum(jnp.sum(b * b, axis=-1) * mb), axes)
        mom_b = lax.psum(jnp.sum(b * mb[:, None], axis=0), axes)
    total = 0.5 * (sq_a * cb + sq_b * ca) - jnp.dot(mom_a, mom_b)
    count = ca * cb - (ca if one_sample else 0.0)
    return total, count


def scatter_pair_stats(
    A: jnp.ndarray,
    B: jnp.ndarray,
    mask_a: Optional[jnp.ndarray] = None,
    mask_b: Optional[jnp.ndarray] = None,
    ids_a: Optional[jnp.ndarray] = None,
    ids_b: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum, count) of the masked scatter grid — exact, O(n d), same
    contract as ops.pair_tiles.pair_stats on the scatter kernel.

    When ids are passed, BOTH sides must carry the same (ids, mask)
    arrays (every one-sample call site does): the duplicate count then
    equals sum_v c(v)^2. Cross-array id joins are not needed anywhere
    and are not supported.
    """
    dt = A.dtype
    ma = jnp.ones(A.shape[0], dt) if mask_a is None else mask_a
    mb = jnp.ones(B.shape[0], dt) if mask_b is None else mask_b
    ca, cb = jnp.sum(ma), jnp.sum(mb)
    sq_a = jnp.sum(jnp.sum(A * A, axis=-1) * ma)
    sq_b = jnp.sum(jnp.sum(B * B, axis=-1) * mb)
    mom_a = jnp.sum(A * ma[:, None], axis=0)
    mom_b = jnp.sum(B * mb[:, None], axis=0)
    total = 0.5 * (sq_a * cb + sq_b * ca) - jnp.dot(mom_a, mom_b)
    count = ca * cb
    if ids_a is not None:
        count = count - _dup_pair_count(
            jnp.asarray(ids_a), ma
        ).astype(dt)
    return total.astype(dt), count.astype(dt)
