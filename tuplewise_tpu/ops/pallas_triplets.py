"""Pallas-rate degree-3 reductions via distance factorization
[VERDICT r3 next #3 — the last hot loop without a hand-tiled path].

The built-in triplet kernels (ops.kernels) depend on the three points
ONLY through the two anchor distances:

    h(a, p, n) = g( d(a,p) - d(a,n) ),       d = squared euclidean
    indicator: g(t) = 1{t < -margin}    hinge: g(t) = max(0, margin+t)

so the O(n^3 d) triple loop factorizes into O(n^2 d) MXU distance
matmuls + an O(n^3) SCALAR pair reduction per anchor — the same trick
as the native C++ engine's distance-reuse loop
(native/pair_sum.cpp::triplet_stats_native), mapped to TPU:

1. anchors stream in chunks; per chunk the two distance matrices
   D_ap [C, P] and D_an [C, K] come from one |a|^2/|b|^2/a@b.T
   assembly each (MXU work);
2. per anchor row, sum_{j,k} g(D_ap[j] - D_an[k]) is EXACTLY the
   masked pair-sum problem on score vectors (D_ap[i], D_an[i]) with
   the combine g as a diff kernel — the hand-tiled
   `pallas_masked_pair_sum` runs it under `jax.vmap` over the chunk,
   per-anchor j-masks carrying the ids_x != ids_p exclusion.

No new Pallas kernel: the pair kernel's sublane x lane layout, SMEM
Kahan cells, and vmap batching are reused as-is. Only the two built-in
triplet kernels qualify (identity dispatch on triplet_fn, margin read
off the function default — the cpp_backend discipline); custom triplet
kernels keep the XLA tile path (ops.pair_tiles.triplet_stats).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tuplewise_tpu.ops.kernels import Kernel


@functools.lru_cache(maxsize=None)
def _combine_kernel(base_fn, margin: float, indicator: bool) -> Kernel:
    """The scalar combine g as a registered-shape diff Kernel, cached so
    the jitted pair kernels see one static object per (fn, margin)."""
    if indicator:
        def g(d, xp):
            return xp.where(d < -margin, 1.0, 0.0)
    else:
        def g(d, xp):
            return xp.maximum(0.0, margin + d)
    return Kernel(
        name=f"_triplet_combine_{'ind' if indicator else 'hinge'}_{margin}",
        degree=2, two_sample=True, kind="diff", diff_fn=g,
        higher_is_better=indicator,
    )


def triplet_combine_kernel(kernel: Kernel) -> Optional[Kernel]:
    """The distance-difference combine for a built-in triplet kernel,
    or None when the kernel does not factorize (custom triplet_fn).
    Identity dispatch + margin come from the shared builtin table
    (ops.kernels.builtin_triplet_spec)."""
    from tuplewise_tpu.ops.kernels import builtin_triplet_spec

    spec = builtin_triplet_spec(kernel)
    if spec is None:
        return None
    kind, margin = spec
    return _combine_kernel(kernel.triplet_fn, margin, kind == "indicator")


def _sqdist_matrix(a, b):
    """[C, m] squared euclidean distances via the MXU contraction.
    Precision.HIGHEST: the default TPU matmul rounds operands to bf16,
    whose ~1e-3 relative distance error flips indicator decisions on
    near-ties — parity with the exact-f32 XLA tile scan requires the
    full-precision (3-pass) MXU mode; the contraction is O(n^2 d) of
    an O(n^3) computation, so the 3x matmul cost is invisible."""
    an = jnp.sum(a * a, axis=-1)
    bn = jnp.sum(b * b, axis=-1)
    cross = jnp.dot(a, b.T, precision=lax.Precision.HIGHEST)
    return an[:, None] + bn[None, :] - 2.0 * cross


def pallas_triplet_stats(
    kernel: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    mask_x: Optional[jnp.ndarray] = None,
    mask_y: Optional[jnp.ndarray] = None,
    ids_x: Optional[jnp.ndarray] = None,
    *,
    positives: Optional[jnp.ndarray] = None,
    mask_p: Optional[jnp.ndarray] = None,
    ids_p: Optional[jnp.ndarray] = None,
    anchor_chunk: int = 512,
    tile_p: int = 512,
    tile_k: int = 4096,
    interpret: bool = False,
):
    # defaults measured on v5e at n=4096, d=32: 3.51e11 triplets/s
    # (XLA tile scan: 1.0e11); wider k-tiles (8192) drop to 2.5e11
    """(sum, count) of h(x_i, p_j, y_k) over ids_x[i] != ids_p[j] — the
    same contract as ops.pair_tiles.triplet_stats, at pair-kernel rate.

    Raises ValueError for kernels that don't factorize; callers
    (ring._triplet_block, backends) check triplet_combine_kernel first
    and fall back to the XLA path.
    """
    combine = triplet_combine_kernel(kernel)
    if combine is None:
        raise ValueError(
            f"triplet kernel {kernel.name!r} has no distance "
            "factorization; use pair_tiles.triplet_stats"
        )
    from tuplewise_tpu.ops.pair_tiles import _pad_axis0
    from tuplewise_tpu.ops.pallas_pairs import pallas_masked_pair_sum

    dtype = X.dtype
    mx = jnp.ones(X.shape[0], dtype) if mask_x is None else mask_x
    my = jnp.ones(Y.shape[0], dtype) if mask_y is None else mask_y
    ix = (jnp.arange(X.shape[0]) if ids_x is None else ids_x
          ).astype(jnp.int32)
    if positives is None:
        positives, mp_, ip = X, mx, ix
    else:
        mp_ = (jnp.ones(positives.shape[0], dtype)
               if mask_p is None else mask_p)
        ip = (jnp.arange(positives.shape[0]) if ids_p is None else ids_p
              ).astype(jnp.int32)

    # clamp the measured-best shapes down for small inputs: the pair
    # kernel pads every side up to a full tile, so tiles far beyond the
    # data would spend almost all lanes on zero-mask padding (the same
    # rule as mesh_mc._clamp_preferred; interpret-mode tests at n~50
    # would otherwise emulate 512x4096 grids of padding)
    def _clamp(t, m, floor):
        while t >= 2 * m and t > floor:
            t //= 2
        return t

    C = _clamp(anchor_chunk, X.shape[0], 8)
    tile_p = _clamp(tile_p, positives.shape[0], 8)
    tile_k = _clamp(tile_k, Y.shape[0], 128)
    Xc = _pad_axis0(X, C).reshape(-1, C, X.shape[-1])
    mxc = _pad_axis0(mx, C).reshape(-1, C)
    # padded anchors must not collide with any positive id: ids are
    # nonnegative, so -1 never matches
    ixc = _pad_axis0(ix + 1, C).reshape(-1, C) - 1

    def per_anchor(dap, dan, mj):
        s = pallas_masked_pair_sum(
            dap, dan, mj, my, kernel=combine,
            tile_a=tile_p, tile_b=tile_k, interpret=interpret,
        )
        return s, jnp.sum(mj) * jnp.sum(my)

    def chunk_stats(args):
        a, ma, ia = args
        dap = _sqdist_matrix(a, positives)          # [C, P] MXU
        dan = _sqdist_matrix(a, Y)                  # [C, K] MXU
        mj = (mp_[None, :]
              * (ia[:, None] != ip[None, :]).astype(dtype))  # [C, P]
        s, c = jax.vmap(per_anchor)(dap, dan, mj)
        return jnp.sum(s * ma), jnp.sum(c * ma)

    # lax.map over anchor chunks bounds the live distance matrices at
    # [C, max(P, K)] while the vmapped pair kernel fills the chip
    s, c = lax.map(chunk_stats, (Xc, mxc, ixc))
    return jnp.sum(s).astype(dtype), jnp.sum(c).astype(dtype)


def triplet_stats_best(
    kernel: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    *,
    impl: str = "xla",
    interpret: Optional[bool] = None,
    tile: int = 128,
    **kw,
):
    """The shared dispatch every degree-3 call site uses (ring blocks,
    backends, harness bodies): the Pallas distance factorization when
    impl="pallas" and the kernel factorizes, the checkpointed XLA tile
    scan otherwise. Same (sum, count) contract either way."""
    if impl == "pallas" and triplet_combine_kernel(kernel) is not None:
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        return pallas_triplet_stats(kernel, X, Y, interpret=interpret, **kw)
    from tuplewise_tpu.ops import pair_tiles

    return pair_tiles.triplet_stats(kernel, X, Y, tile=tile, **kw)
