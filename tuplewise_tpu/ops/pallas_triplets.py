"""Pallas-rate degree-3 reductions via distance factorization
[VERDICT r3 next #3 — the last hot loop without a hand-tiled path].

The built-in triplet kernels (ops.kernels) depend on the three points
ONLY through the two anchor distances:

    h(a, p, n) = g( d(a,p) - d(a,n) ),       d = squared euclidean
    indicator: g(t) = 1{t < -margin}    hinge: g(t) = max(0, margin+t)

so the O(n^3 d) triple loop factorizes into O(n^2 d) MXU distance
matmuls + an O(n^3) SCALAR pair reduction per anchor — the same trick
as the native C++ engine's distance-reuse loop
(native/pair_sum.cpp::triplet_stats_native), mapped to TPU:

1. anchors stream in chunks; per chunk the two distance matrices
   D_pa [P, C] (anchors in LANES — each anchor's positive distances
   are a natural (8, 128)-tiled column) and D_an [C, K] come from one
   |a|^2/|b|^2/a@b.T assembly each (MXU work);
2. the BATCHED pair kernel (`_batched_pair_sum_kernel`, r5) reduces
   sum_{j,k} g(D_pa[j,c] - D_an[c,k]) for every anchor c of the chunk
   in ONE grid (C, P/Tp, K/Tk) traversal — the same sublane x lane
   broadcast and Kahan cells as the pair kernels, with per-anchor
   j-masks carrying the ids_x != ids_p exclusion. (The r4 design
   vmapped the masked PAIR kernel per anchor, which reshaped each
   distance row to a [P, 1] column whose unit lane dim padded 128x in
   HBM — 2 x 8 GB of HLO temp at C=1024, P=16384; the batched layout
   removed that wall and lifted n=16384 from 6.2e11 to ~9e11
   triplets/s.)

Only the two built-in triplet kernels qualify (identity dispatch on
triplet_fn, margin read off the function default — the cpp_backend
discipline); custom triplet kernels keep the XLA tile path
(ops.pair_tiles.triplet_stats).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from tuplewise_tpu.ops.kernels import Kernel


@functools.lru_cache(maxsize=None)
def _combine_kernel(base_fn, margin: float, indicator: bool) -> Kernel:
    """The scalar combine g as a registered-shape diff Kernel, cached so
    the jitted pair kernels see one static object per (fn, margin)."""
    if indicator:
        def g(d, xp):
            return xp.where(d < -margin, 1.0, 0.0)
    else:
        def g(d, xp):
            return xp.maximum(0.0, margin + d)
    return Kernel(
        name=f"_triplet_combine_{'ind' if indicator else 'hinge'}_{margin}",
        degree=2, two_sample=True, kind="diff", diff_fn=g,
        higher_is_better=indicator,
    )


def triplet_combine_kernel(kernel: Kernel) -> Optional[Kernel]:
    """The distance-difference combine for a built-in triplet kernel,
    or None when the kernel does not factorize (custom triplet_fn).
    Identity dispatch + margin come from the shared builtin table
    (ops.kernels.builtin_triplet_spec)."""
    from tuplewise_tpu.ops.kernels import builtin_triplet_spec

    spec = builtin_triplet_spec(kernel)
    if spec is None:
        return None
    kind, margin = spec
    return _combine_kernel(kernel.triplet_fn, margin, kind == "indicator")


# positive/negative-dim segment bound for one batched-kernel call: a P
# or K of 65536 reproducibly crashes the v5e TPU worker (r5; 32768
# sustains ~1e12 tr/s), and the grid partition is exact — module-level
# so tests can shrink it to pin the segmented path's parity
_SEG = 32768


def _sqdist_matrix(a, b):
    """[C, m] squared euclidean distances via the MXU contraction.
    Precision.HIGHEST: the default TPU matmul rounds operands to bf16,
    whose ~1e-3 relative distance error flips indicator decisions on
    near-ties — parity with the exact-f32 XLA tile scan requires the
    full-precision (3-pass) MXU mode; the contraction is O(n^2 d) of
    an O(n^3) computation, so the 3x matmul cost is invisible."""
    an = jnp.sum(a * a, axis=-1)
    bn = jnp.sum(b * b, axis=-1)
    cross = jnp.dot(a, b.T, precision=lax.Precision.HIGHEST)
    return an[:, None] + bn[None, :] - 2.0 * cross


def preferred_anchor_chunk(n_pos: int, n_neg: int) -> int:
    """HBM-aware anchor chunk for the factorized path [VERDICT r4 next
    #4]: the live per-chunk distance matrices D_pa [P, C] and D_an
    [C, K] cost C * (P + K) * 4 bytes f32 (natural (8, 128) tiling —
    the r4 per-anchor vmap layout padded a unit lane dim 128x and
    OOM'd 16 GB HBM at C=1024, P=16384; the batched kernel removed
    that). Two measured regimes on v5e (tp=1024; the committed grid is
    results/triplet_scaling.jsonl, produced through this dispatch):
    small grids (max(P, K) <= 8192) take C=1024 — fewer chunk-assembly
    passes lift n=4096 d=32 to 3.97e11 tr/s (C=256 ran ~25% slower in
    the r5 tuning probes); larger grids take C=256 (n=16384 d=16 at
    1.01e12, n=32768 d=32 at 1.05e12), shrinking further only to bound
    the matrices + remat copies inside ~2 GB."""
    if max(n_pos, n_neg) <= 8192:
        return 1024
    budget = 2 * (1 << 30)
    cap = budget // ((n_pos + n_neg) * 4 + 1)
    c = 256
    while c > 8 and c > cap:
        c //= 2
    return c


def preferred_triplet_tile_k(n_neg: int) -> int:
    """Measured-best negative-lane tile on v5e: 8192 lanes win once K
    amortizes them (9.8e11 vs 9.3e11 tr/s at K=16384); smaller K keeps
    4096 (8192 loses ~4% at K=4096 to padding/pipeline drain)."""
    return 8192 if n_neg >= 16384 else 4096


def _batched_pair_sum_kernel(a_ref, b_ref, ma_ref, mb_ref, o_ref, *, g):
    """One anchor chunk's sum_{j,k} g(D_pa[j,c] - D_an[c,k]) * mj * mk
    for every anchor c, in ONE grid (P/Tp, C, K/Tk) traversal:

    * a_ref/ma_ref [Tp, C]: a full row block of the [P, C] distance /
      mask matrices (anchors in LANES — natural (8, 128) tiling; the
      r4 per-anchor vmap reshaped rows to [P, 1] columns whose unit
      lane dim padded 128x in HBM). Anchor c's column is extracted
      in-kernel by a one-hot lane reduction (Mosaic cannot prove a
      width-1 dynamic lane slice 128-aligned) — Tp*C VPU work per
      step, ~C/Tk of the main reduction; the block index ignores c,
      so the fetch is elided across the (c, j) sweep;
    * b_ref [1, Tk]: anchor c's negative-distance block from the
      FLATTENED [1, C*K] layout (block c*gk + j) — a [C, K] block of
      (1, Tk) would be an illegal Mosaic shape (second-to-last dim 1
      neither divisible by 8 nor the full C);
    * o_ref [2, C]: lane-per-anchor (sum, compensation) accumulator,
      resident for the WHOLE grid (constant index map). The Kahan add
      touches only lane c by masking: other lanes add an exact 0 to
      the sum and keep their compensation untouched.
    """
    c = pl.program_id(1)
    first = (pl.program_id(0) == 0) & (c == 0) & (pl.program_id(2) == 0)

    @pl.when(first)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    onehot = (lax.broadcasted_iota(
        jnp.int32, (1, a_ref.shape[1]), 1) == c).astype(jnp.float32)
    a_col = jnp.sum(a_ref[:, :] * onehot, axis=1, keepdims=True)
    ma_col = jnp.sum(ma_ref[:, :] * onehot, axis=1, keepdims=True)
    d = a_col - b_ref[:, :]                             # [Tp, Tk]
    row = jnp.sum(g(d) * mb_ref[:, :], axis=1, keepdims=True)
    x = jnp.sum(row * ma_col)
    s = o_ref[0:1, :]                                   # [1, C]
    comp = o_ref[1:2, :]
    m = lax.broadcasted_iota(jnp.int32, s.shape, 1) == c
    y = jnp.where(m, x - comp, 0.0)
    t = s + y                                           # exact off-lane
    o_ref[1:2, :] = jnp.where(m, (t - s) - y, comp)
    o_ref[0:1, :] = t


def _batched_masked_pair_sum(dpaT, dan, mjT, mk, *, combine: Kernel,
                             tile_p: int, tile_k: int,
                             interpret: bool):
    """[C] per-anchor masked pair sums over the [P] x [K] grids.
    dpaT: [P, C] positive distances (anchors in lanes), dan: [C, K]
    negative distances, mjT: [P, C] per-anchor positive masks,
    mk: [K] negative mask. P and K must be tile multiples (callers
    pad with zero-mask rows)."""
    P, C = dpaT.shape
    K = dan.shape[1]
    gp, gk = P // tile_p, K // tile_k
    out = pl.pallas_call(
        functools.partial(
            _batched_pair_sum_kernel,
            g=lambda d: combine.diff(d, jnp),
        ),
        out_shape=jax.ShapeDtypeStruct((2, C), jnp.float32),
        grid=(gp, C, gk),
        in_specs=[
            pl.BlockSpec((tile_p, C), lambda i, c, j: (i, 0)),
            pl.BlockSpec((1, tile_k), lambda i, c, j: (0, c * gk + j)),
            pl.BlockSpec((tile_p, C), lambda i, c, j: (i, 0)),
            pl.BlockSpec((1, tile_k), lambda i, c, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((2, C), lambda i, c, j: (0, 0)),
        interpret=interpret,
    )(dpaT, dan.reshape(1, C * K), mjT, mk.reshape(1, K))
    # true per-anchor sum folds in the compensation lane
    return out[0, :] - out[1, :]


def pallas_triplet_stats(
    kernel: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    mask_x: Optional[jnp.ndarray] = None,
    mask_y: Optional[jnp.ndarray] = None,
    ids_x: Optional[jnp.ndarray] = None,
    *,
    positives: Optional[jnp.ndarray] = None,
    mask_p: Optional[jnp.ndarray] = None,
    ids_p: Optional[jnp.ndarray] = None,
    anchor_chunk: int = 0,
    tile_p: int = 1024,
    tile_k: int = 0,
    interpret: bool = False,
):
    # anchor_chunk=0 / tile_k=0 resolve via the preferred_* dispatch
    # (HBM-aware chunk; K-dependent lane tile) — regression-tested in
    # tests/test_pallas_and_rank.py
    """(sum, count) of h(x_i, p_j, y_k) over ids_x[i] != ids_p[j] — the
    same contract as ops.pair_tiles.triplet_stats, at pair-kernel rate.

    Raises ValueError for kernels that don't factorize; callers
    (ring._triplet_block, backends) check triplet_combine_kernel first
    and fall back to the XLA path.
    """
    combine = triplet_combine_kernel(kernel)
    if combine is None:
        raise ValueError(
            f"triplet kernel {kernel.name!r} has no distance "
            "factorization; use pair_tiles.triplet_stats"
        )
    from tuplewise_tpu.ops.pair_tiles import _pad_axis0

    dtype = X.dtype
    mx = jnp.ones(X.shape[0], dtype) if mask_x is None else mask_x
    my = jnp.ones(Y.shape[0], dtype) if mask_y is None else mask_y
    ix = (jnp.arange(X.shape[0]) if ids_x is None else ids_x
          ).astype(jnp.int32)
    if positives is None:
        positives, mp_, ip = X, mx, ix
    else:
        mp_ = (jnp.ones(positives.shape[0], dtype)
               if mask_p is None else mask_p)
        ip = (jnp.arange(positives.shape[0]) if ids_p is None else ids_p
              ).astype(jnp.int32)

    # Segment the positive/negative dims at _SEG (see its comment):
    # the grid partition is EXACT (per-anchor sums and counts are
    # additive over P x K tiles; only the O(n^2 d) dan assembly is
    # recomputed per positive segment, invisible against the O(n^3)
    # combine).
    if positives.shape[0] > _SEG or Y.shape[0] > _SEG:
        s_tot = jnp.zeros((), jnp.float32)
        c_tot = jnp.zeros((), jnp.float32)
        for p0 in range(0, positives.shape[0], _SEG):
            p1 = min(p0 + _SEG, positives.shape[0])
            for k0 in range(0, Y.shape[0], _SEG):
                k1 = min(k0 + _SEG, Y.shape[0])
                s, c = pallas_triplet_stats(
                    kernel, X, Y[k0:k1], mask_x=mx, mask_y=my[k0:k1],
                    ids_x=ix, positives=positives[p0:p1],
                    mask_p=mp_[p0:p1], ids_p=ip[p0:p1],
                    anchor_chunk=anchor_chunk, tile_p=tile_p,
                    tile_k=tile_k, interpret=interpret,
                )
                s_tot = s_tot + s.astype(jnp.float32)
                c_tot = c_tot + c.astype(jnp.float32)
        return s_tot.astype(dtype), c_tot.astype(dtype)

    # clamp the measured-best shapes down for small inputs: the batched
    # kernel pads P/K up to tile multiples, so tiles far beyond the
    # data would spend almost all lanes on zero-mask padding (the same
    # rule as mesh_mc._clamp_preferred; interpret-mode tests at n~50
    # would otherwise emulate 512x4096 grids of padding)
    def _clamp(t, m, floor):
        while t >= 2 * m and t > floor:
            t //= 2
        return t

    if not anchor_chunk:
        anchor_chunk = preferred_anchor_chunk(
            positives.shape[0], Y.shape[0]
        )
    if not tile_k:
        tile_k = preferred_triplet_tile_k(Y.shape[0])
    C = _clamp(anchor_chunk, X.shape[0], 8)
    tile_p = _clamp(tile_p, positives.shape[0], 8)
    tile_k = _clamp(tile_k, Y.shape[0], 128)
    Xc = _pad_axis0(X, C).reshape(-1, C, X.shape[-1])
    mxc = _pad_axis0(mx, C).reshape(-1, C)
    # padded anchors must not collide with any positive id: ids are
    # nonnegative, so -1 never matches
    ixc = _pad_axis0(ix + 1, C).reshape(-1, C) - 1
    # pad positives/negatives ONCE to tile multiples with zero masks:
    # inside the chunk loop every shape is then tile-exact
    pos_p, mp_p = _pad_axis0(positives, tile_p), _pad_axis0(mp_, tile_p)
    ip_p = _pad_axis0(ip + 1, tile_p) - 1
    Y_p, my_p = _pad_axis0(Y, tile_k), _pad_axis0(my, tile_k)
    my_row = my_p.astype(jnp.float32)

    def chunk_stats(args):
        a, ma, ia = args
        # anchors in LANES: D_pa arrives [P, C] (its per-anchor columns
        # are natural (8, 128) blocks for the batched kernel), D_an
        # [C, K] — both one MXU assembly each
        dpaT = _sqdist_matrix(pos_p, a)             # [P, C] MXU
        dan = _sqdist_matrix(a, Y_p)                # [C, K] MXU
        mjT = (mp_p[:, None]
               * (ip_p[:, None] != ia[None, :]).astype(dtype))  # [P, C]
        s_anchor = _batched_masked_pair_sum(
            dpaT, dan, mjT.astype(jnp.float32), my_row,
            combine=combine, tile_p=tile_p, tile_k=tile_k,
            interpret=interpret,
        )
        cnt = jnp.sum(mjT, axis=0) * jnp.sum(my)    # [C]
        return (jnp.sum(s_anchor * ma, dtype=jnp.float32),
                jnp.sum(cnt * ma, dtype=jnp.float32))

    # lax.map over anchor chunks bounds the live distance matrices at
    # C * (P + K) floats while the batched kernel fills the chip
    s, c = lax.map(chunk_stats, (Xc, mxc, ixc))
    return jnp.sum(s).astype(dtype), jnp.sum(c).astype(dtype)


def triplet_stats_best(
    kernel: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    *,
    impl: str = "xla",
    interpret: Optional[bool] = None,
    tile: int = 128,
    **kw,
):
    """The shared dispatch every degree-3 call site uses (ring blocks,
    backends, harness bodies): the Pallas distance factorization when
    impl="pallas" and the kernel factorizes, the checkpointed XLA tile
    scan otherwise. Same (sum, count) contract either way."""
    if impl == "pallas" and triplet_combine_kernel(kernel) is not None:
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        return pallas_triplet_stats(kernel, X, Y, interpret=interpret, **kw)
    from tuplewise_tpu.ops import pair_tiles

    return pair_tiles.triplet_stats(kernel, X, Y, tile=tile, **kw)
