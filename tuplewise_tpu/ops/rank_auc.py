"""O(n log n) exact complete AUC on device — the special-case fast path.

The AUC U-statistic has closed-form rank structure (Mann-Whitney): with
midranks for ties,

    U_n = ( sum of pos midranks - n1 (n1 + 1) / 2 ) / (n1 n2)

so the complete statistic needs one sort + two binary searches instead
of streaming n1*n2 kernel evaluations: at n=10^7 that's ~10^8 work
instead of 10^14 pairs. Mirrors models.metrics.auc_score (the NumPy
oracle); exact for the "auc" kernel only — general kernels use the
tiled reductions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def rank_auc(pos_scores: jnp.ndarray, neg_scores: jnp.ndarray) -> jnp.ndarray:
    """AUC = P(s_pos > s_neg) + 0.5 P(s_pos = s_neg), cancellation-free.

    Formulated per POSITIVE against the sorted negatives: each positive
    contributes (count_less + 0.5 * count_equal) / n2, a value in [0, 1],
    and the AUC is the mean of those fractions. No giant-midrank
    subtraction appears anywhere, so f32 stays accurate (~n * eps
    relative over the mean) at any n1/n2 scale or imbalance — unlike the
    classical rank-sum formula, which subtracts two O(n^2)-magnitude
    terms and loses 3-4 decimals in f32 at n ~ 1e7.
    """
    pos = pos_scores.ravel()
    neg = jnp.sort(neg_scores.ravel())
    n2 = neg.shape[0]
    less = jnp.searchsorted(neg, pos, side="left")
    leq = jnp.searchsorted(neg, pos, side="right")
    frac = (less.astype(jnp.float32)
            + 0.5 * (leq - less).astype(jnp.float32)) / n2
    return jnp.mean(frac, dtype=jnp.float32)
