"""Pallas dispatch-mode resolution — the ONE copy of the env-override
semantics [ISSUE 10 satellite].

Two subsystems run Pallas kernels behind an opt-in/auto gate:

* the **harness hot loops** (``harness.variance`` / ``harness.mesh_mc``
  / ``ops.pair_tiles``) — auto-on on TPU, forced through interpret mode
  on CPU for parity tests via ``TUPLEWISE_HARNESS_PALLAS``;
* the **serving count kernel** (``ops.pallas_counts`` behind
  ``ServingConfig.count_kernel``) — opt-in per config, overridable via
  ``TUPLEWISE_SERVING_PALLAS``.

Both overrides share one value grammar (``interpret`` | ``off`` |
unset/``auto``) and one resolution rule, implemented here exactly once.
``resolve_pallas_mode`` used to live in ``ops.pallas_pairs`` (which
re-exports it for its existing harness call sites); the serving twin
layers the explicit opt-in on top of the same resolver instead of
growing a second copy of the env semantics.
"""

from __future__ import annotations

import os

HARNESS_ENV = "TUPLEWISE_HARNESS_PALLAS"
SERVING_ENV = "TUPLEWISE_SERVING_PALLAS"


def resolve_pallas_mode(platform: str, env: str = HARNESS_ENV):
    """(use_pallas, interpret) for a hot loop executing on ``platform``,
    honoring ``env`` = ``interpret`` | ``off`` | unset (auto): interpret
    forces the kernel through the Pallas interpreter (CPU parity runs),
    off disables it everywhere, auto uses it exactly on TPU."""
    mode = os.environ.get(env, "auto")
    interpret = mode == "interpret"
    return interpret or (mode != "off" and platform == "tpu"), interpret


def resolve_serving_counts_mode(platform: str, enabled: bool):
    """(use_kernel, interpret) for the serving count kernel [ISSUE 10].

    The kernel is opt-in (``enabled`` = ``ServingConfig.count_kernel``,
    default off) and ``TUPLEWISE_SERVING_PALLAS`` overrides through the
    same grammar as the harness env: ``off`` wins over the config flag
    (kill switch), ``interpret`` force-enables in interpret mode even
    off-TPU (how the existing parity/chaos/recovery suites run with the
    kernel on), and unset/auto honors the config flag — executing
    natively on TPU, through the interpreter anywhere else (counts are
    integers, so interpreted results are bit-identical, just slow).
    """
    mode = os.environ.get(SERVING_ENV, "auto")
    if mode == "off":
        return False, False
    if mode == "interpret":
        return True, True
    if not enabled:
        return False, False
    return True, platform != "tpu"
