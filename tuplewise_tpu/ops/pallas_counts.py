"""Pallas TPU kernel for the serving count hot loop [ISSUE 10].

The serving index's per-micro-batch device work is integer rank
counting: for each query q, ``less = #{v in R : v < q}`` and
``leq = #{v in R : v <= q}`` where R is a SIGNED union of sorted runs —
base + consolidated delta runs (+1) minus the tombstone multiset (−1).
The XLA path dispatches a ``searchsorted`` pair per run and folds the
tombstone on the host; this kernel fuses the whole thing into ONE
Pallas invocation per device: every run streams through VMEM once, the
signed combination accumulates in-kernel into one small integer block,
and only that block crosses back for the psum.

**Rank by comparison counting.** A binary search is the wrong shape for
the TPU vector unit (log-depth data-dependent gathers); the VPU-native
lowering of searchsorted is the comparison count

    less[q] = sum_i 1{run_i < q},    leq[q] = sum_i 1{run_i <= q}

computed as a [run-tile, query-tile] broadcast compare + sublane
reduction — the pair-grid pattern ``ops.pallas_pairs`` already runs at
~7e11 cells/s/chip, with integer accumulation instead of Kahan floats.
Equality with ``searchsorted`` is exact (integers; counting does not
even need sortedness), so kernel-vs-XLA parity is bit-exact by
construction. +inf padding contributes 0 to both counts for finite
queries, exactly as in the padded searchsorted path. The O(cap) work
per query tile (vs O(log cap)) is the standard trade: the runs stream
through VMEM once per micro-batch at full VPU width, with no
data-dependent addressing for Mosaic to choke on.

Two variants share the layout [ISSUE 10 tentpole]:

* **flat-run** (``flat_signed_count_fn`` / ``sharded_signed_count_fn``)
  — the single-tenant index: k runs with per-run sign and query-set
  assignment, TWO query sets in one invocation (insert queries vs the
  neg side's runs AND vs the pos side's runs ride one dispatch), one
  ``[4, q_bucket]`` int32 result (less/leq per query set).
  Runs enter as [cap, 1] sublane columns, queries as [1, qb] lane rows
  — the ``pallas_pairs`` orientation.
* **tenant-axis** (``tenant_signed_count_fn`` /
  ``tenant_signed_count_local_fn``) — the fleet packs: ``[S, T_bucket,
  cap]`` per class, per-tenant query blocks, one ``[4, q_bucket,
  T_bucket]`` result. Queries enter TRANSPOSED (``[qb, T]``, query axis
  on sublanes) so the per-tenant outer compare needs no in-kernel
  transpose — pack rows stay on lanes, query columns on sublanes.

Compile shapes follow the existing ``(T_bucket, cap, q_bucket)``
power-of-two bucket ladders in every argument, so the compile cache is
invariant to live tenant count and run occupancy. CPU execution uses
interpret mode (``pallas_guide``: interpret=True), which is how CI and
the parity suites run it; dispatch-mode resolution (config opt-in +
``TUPLEWISE_SERVING_PALLAS`` override) lives in ``ops.pallas_modes``.

The dispatchers with XLA fallback live in
``parallel.sharded_counts`` (``signed_pair_counts`` /
``tenant_pack_counts``); this module holds only the kernel builders.
"""

from __future__ import annotations

import functools

# run-axis and query-axis tile caps: a [tile_r, tile_q] int32 compare
# block tops out at 1024*1024*4 B = 4 MiB live VMEM — comfortable under
# double buffering, and every bucket-ladder cap (powers of two >= 256)
# is a multiple of the clamped tile
_TILE_R = 1024
_TILE_Q = 1024

# test hook [ISSUE 10 satellite]: the dispatchers in
# parallel.sharded_counts raise before touching the kernel when set,
# exercising the automatic XLA fallback exactly as a Mosaic lowering
# failure would
FORCE_FAIL = False


def _run_tiles(caps):
    """Per-run (tile, n_tiles): tile = min(cap, _TILE_R) divides cap
    because both are powers of two >= 256."""
    tiles = []
    for c in caps:
        t = min(c, _TILE_R)
        tiles.append((t, c // t))
    return tiles


# --------------------------------------------------------------------- #
# flat-run variant (single-tenant index)                                 #
# --------------------------------------------------------------------- #

def _flat_kernel(*refs, k, signs, assign, tiles, tile_q):
    """One grid step: accumulate each run's signed (less, leq) lane
    counts for this query tile into the resident [4, q_bucket] int32
    block (rows 0/1 = query set a, rows 2/3 = set b). Runs shorter
    than the grid park on their last tile under a false ``pl.when``."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    run_refs = refs[:k]
    qa_ref, qb_ref, out_ref = refs[k], refs[k + 1], refs[k + 2]
    i, j = pl.program_id(0), pl.program_id(1)
    sl = pl.ds(j * tile_q, tile_q)

    @pl.when(i == 0)
    def _init():
        out_ref[:, sl] = jnp.zeros((4, tile_q), jnp.int32)

    for r in range(k):
        q_ref = qa_ref if assign[r] == 0 else qb_ref
        row = 2 * assign[r]

        def _acc(ref=run_refs[r], q_ref=q_ref, row=row, s=signs[r]):
            col = ref[:, :]                       # [tile_r, 1] sublanes
            q = q_ref[:, :]                       # [1, tile_q] lanes
            less = jnp.sum((col < q).astype(jnp.int32),
                           axis=0, keepdims=True)
            leq = jnp.sum((col <= q).astype(jnp.int32),
                          axis=0, keepdims=True)
            out_ref[row:row + 1, sl] = out_ref[row:row + 1, sl] + s * less
            out_ref[row + 1:row + 2, sl] = (
                out_ref[row + 1:row + 2, sl] + s * leq)

        pl.when(i < tiles[r][1])(_acc)


def _flat_call(caps, signs, assign, q_bucket, interpret):
    """Unjitted builder: fn(run_cols_1d, qa_1d, qb_1d) -> [4, qb] i32.
    Runs are +inf-padded 1-D arrays of length caps[r]."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    k = len(caps)
    tiles = tuple(_run_tiles(caps))
    tile_q = min(q_bucket, _TILE_Q)
    gi = max((n for _, n in tiles), default=1)
    gj = q_bucket // tile_q
    in_specs = [
        pl.BlockSpec((t, 1), (lambda i, j, n=n: (jnp.minimum(i, n - 1), 0)))
        for t, n in tiles
    ]
    in_specs += [pl.BlockSpec((1, tile_q), lambda i, j: (0, j))] * 2

    def call(runs, qa, qb):
        cols = [r.reshape(-1, 1) for r in runs]
        return pl.pallas_call(
            functools.partial(_flat_kernel, k=k, signs=signs,
                              assign=assign, tiles=tiles, tile_q=tile_q),
            out_shape=jax.ShapeDtypeStruct((4, q_bucket), jnp.int32),
            grid=(gi, gj),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((4, q_bucket), lambda i, j: (0, 0)),
            interpret=interpret,
        )(*cols, qa.reshape(1, -1), qb.reshape(1, -1))

    return call


@functools.lru_cache(maxsize=None)
def flat_signed_count_fn(caps, signs, assign, q_bucket: int,
                         interpret: bool):
    """Jitted single-device fused count: ``(runs tuple of [cap_r]
    padded sorted arrays, qa [qb], qb [qb]) -> [4, qb] int32`` — rows
    (less_a, leq_a, less_b, leq_b), each run weighted by its sign and
    counted against its assigned query set. Cache key = the bucket
    ladder alone."""
    import jax

    call = _flat_call(caps, signs, assign, q_bucket, interpret)
    return jax.jit(lambda runs, qa, qb: call(runs, qa, qb))


@functools.lru_cache(maxsize=None)
def sharded_signed_count_fn(mesh, caps, signs, assign, q_bucket: int,
                            interpret: bool):
    """Mesh twin of :func:`flat_signed_count_fn`: runs are placed
    ``[S, cap_r]`` row shards, queries replicated; ONE kernel
    invocation per device, ONE psum of the [4, qb] integer block —
    the whole per-micro-batch count in one collective [ISSUE 10]."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    k = len(caps)
    call = _flat_call(caps, signs, assign, q_bucket, interpret)

    def body(runs, qa, qb):
        out = call(tuple(r[0] for r in runs), qa, qb)
        return lax.psum(out, axes)

    @jax.jit
    def f(runs, qa, qb):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=((P(axes),) * k, P(), P()), out_specs=P(),
            check_vma=False,
        )(runs, qa, qb)

    return f


# --------------------------------------------------------------------- #
# tenant-axis variant (fleet packs)                                      #
# --------------------------------------------------------------------- #

def _tenant_kernel(neg_ref, pos_ref, qn_ref, qp_ref, out_ref, *,
                   tiles_n, tiles_p, tile_q, lead):
    """One (tenant, query-tile, run-tile) grid step: tenant t's pack
    rows (lanes) against its transposed query column (sublanes), both
    class sides in the same pass. ``lead`` marks the mesh layout's
    leading device axis on the pack blocks."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    c = pl.program_id(2)

    @pl.when(c == 0)
    def _init():
        out_ref[:, :, :] = jnp.zeros_like(out_ref)

    for ref, q_ref, row, tiles in ((neg_ref, qn_ref, 0, tiles_n),
                                   (pos_ref, qp_ref, 2, tiles_p)):
        def _acc(ref=ref, q_ref=q_ref, row=row):
            vals = ref[0, 0, :] if lead else ref[0, :]   # [tile_c] lanes
            q = q_ref[:, :]                              # [tile_q, 1]
            less = jnp.sum((vals[None, :] < q).astype(jnp.int32),
                           axis=1, keepdims=True)        # [tile_q, 1]
            leq = jnp.sum((vals[None, :] <= q).astype(jnp.int32),
                          axis=1, keepdims=True)
            out_ref[row:row + 1, :, :] = (
                out_ref[row:row + 1, :, :] + less[None])
            out_ref[row + 1:row + 2, :, :] = (
                out_ref[row + 1:row + 2, :, :] + leq[None])

        pl.when(c < tiles[1])(_acc)


def _tenant_call(t_bucket, cap_pos, cap_neg, q_bucket, interpret, lead):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    (tn, gn), = _run_tiles((cap_neg,))
    (tp, gp), = _run_tiles((cap_pos,))
    tile_q = min(q_bucket, _TILE_Q)
    gj = q_bucket // tile_q
    gc = max(gn, gp)

    def pack_spec(tile_c, n):
        if lead:
            return pl.BlockSpec(
                (1, 1, tile_c),
                lambda t, j, c, n=n: (0, t, jnp.minimum(c, n - 1)))
        return pl.BlockSpec(
            (1, tile_c), lambda t, j, c, n=n: (t, jnp.minimum(c, n - 1)))

    def call(pos, neg, qn_t, qp_t):
        return pl.pallas_call(
            functools.partial(_tenant_kernel, tiles_n=(tn, gn),
                              tiles_p=(tp, gp), tile_q=tile_q,
                              lead=lead),
            out_shape=jax.ShapeDtypeStruct(
                (4, q_bucket, t_bucket), jnp.int32),
            grid=(t_bucket, gj, gc),
            in_specs=[
                pack_spec(tn, gn),
                pack_spec(tp, gp),
                pl.BlockSpec((tile_q, 1), lambda t, j, c: (j, t)),
                pl.BlockSpec((tile_q, 1), lambda t, j, c: (j, t)),
            ],
            out_specs=pl.BlockSpec((4, tile_q, 1),
                                   lambda t, j, c: (0, j, t)),
            interpret=interpret,
        )(neg, pos, qn_t, qp_t)

    return call


@functools.lru_cache(maxsize=None)
def tenant_signed_count_local_fn(t_bucket: int, cap_pos: int,
                                 cap_neg: int, q_bucket: int,
                                 interpret: bool):
    """Jitted single-device fleet count kernel: ``(pos_pack [T, cap_p],
    neg_pack [T, cap_n], qn_t [qb, T], qp_t [qb, T]) -> [4, qb, T]``
    int32 — rows (less_n, leq_n, less_p, leq_p), one invocation for
    the whole coalesced multi-tenant micro-batch."""
    import jax

    call = _tenant_call(t_bucket, cap_pos, cap_neg, q_bucket,
                        interpret, lead=False)
    return jax.jit(call)


@functools.lru_cache(maxsize=None)
def tenant_signed_count_fn(mesh, t_bucket: int, cap_pos: int,
                           cap_neg: int, q_bucket: int,
                           interpret: bool):
    """Mesh twin: packs are placed ``[S, T, cap]`` shards; ONE kernel
    invocation per device + ONE psum of the [4, qb, T] integer block."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    call = _tenant_call(t_bucket, cap_pos, cap_neg, q_bucket,
                        interpret, lead=True)

    def body(pos, neg, qn_t, qp_t):
        return lax.psum(call(pos, neg, qn_t, qp_t), axes)

    @jax.jit
    def f(pos, neg, qn_t, qp_t):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axes), P(axes), P(), P()),
            out_specs=P(), check_vma=False,
        )(pos, neg, qn_t, qp_t)

    return f


def kernel_cache_sizes() -> dict:
    """Live compile-cache entry counts per kernel family — what the
    bucket-ladder boundedness tests pin [ISSUE 10 satellite]."""
    return {
        "flat": flat_signed_count_fn.cache_info().currsize,
        "flat_sharded": sharded_signed_count_fn.cache_info().currsize,
        "tenant_local": tenant_signed_count_local_fn.cache_info().currsize,
        "tenant_sharded": tenant_signed_count_fn.cache_info().currsize,
    }
