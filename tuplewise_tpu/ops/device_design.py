"""On-device tuple-sampling designs for the LEARNING path
[SURVEY §1.2 item 4; VERDICT r3 next #6].

The estimation side draws its distinct-tuple designs on the host
(parallel.partition.draw_pair_design) — fine for M Monte-Carlo reps,
impossible for a trainer whose steps live inside one jitted `lax.scan`.
This module is the TPU-native equivalent: fixed-shape, sort-based,
O(K log K) per draw, usable per step per worker under shard_map/vmap.

Construction (all shapes static):

  swr        B i.i.d. uniform grid draws — the existing behavior.
  swor       overdraw K with replacement such that the distinct count
             D >= B with ~8-sigma headroom (K solves
             G(1 - e^{-K/G}) = B + 8 sqrt(B), the coupon-collector
             expectation), lexicographically sort (i, j) to mark first
             occurrences, then uniformly subselect EXACTLY B of the D
             distinct tuples by sorting on random keys (+inf for
             duplicates). Each B-subset of the grid is equally likely,
             conditional on D >= B — the same design as the host
             sampler up to the astronomically rare D < B shortfall,
             which the weight mask prices correctly (renormalized mean,
             never a wrong estimate).
  bernoulli  realized size K_real ~ Binomial(G, B/G) (normal
             approximation — exact to float tolerance for the G >= 10^4
             grids the budget regime uses), then the swor machinery
             keeps the first min(K_real, D, L) selected tuples.

Returns (i, j, w): [L] index arrays plus a {0,1} weight mask; consumers
compute sum(vals * w) / sum(w). L = B for swr/swor and B + 8 sqrt(B)
for bernoulli, so every design compiles once per (B, grid) shape.

Why sort-based dedup and not linearized `jnp.unique`: the per-worker
grid m1*m2 reaches 4e11 at production block sizes — linearizing
overflows int32 and this library never enables x64; lexicographic
two-key `lax.sort` needs neither.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _overdraw(grid: int, budget: int) -> int:
    """Static with-replacement draw count K such that the expected
    distinct count G(1 - e^{-K/G}) covers budget + 8 sqrt(budget).
    Callers bound budget <= 0.8 grid, so the coverage fraction stays
    below ~0.95 and K below ~3 G — the coupon-collector blow-up near
    full coverage (K ~ G ln G at budget = G) never engages."""
    target = min(budget + 8.0 * math.sqrt(budget) + 8.0, 0.95 * grid)
    frac = target / grid
    k = -grid * math.log1p(-frac)
    return max(budget, int(math.ceil(k)))


def _distinct_design(key, dims, budget: int, design: str, what: str):
    """(cols, w): ``budget``-sized distinct-tuple draw from the product
    grid prod(dims), in ENCODED coordinates (off-diagonal encodings are
    the callers' business). ONE implementation of the
    overdraw → multi-key-lex-sort dedup → uniform-subselect machinery
    for every arity: `lax.sort(num_keys=len(dims))` generalizes the
    dedup, so no grid linearization (int32 overflow) at any degree.

    Key-split discipline (STABLE — committed rows reproduce these
    draws): split(key, len(dims) + 2) = per-column keys, the bernoulli
    size key, the subselection key, in that order.
    """
    import functools as _ft

    grid = math.prod(dims)
    if budget > 0.8 * grid:
        # near-full-grid distinct sampling needs coupon-collector
        # overdraw (K ~ G ln G) and the exactly-B contract degrades to
        # a probabilistic shortfall; at these fractions the COMPLETE
        # estimator is cheaper anyway — the host samplers
        # (parallel.partition) cover budgets up to G.
        raise ValueError(
            f"cannot draw {budget} distinct {what} from a {grid} grid "
            "on device (> 0.8 * grid); use the complete estimator or "
            "the host sampler"
        )
    from tuplewise_tpu.parallel.partition import design_pad_len

    L = min(design_pad_len(budget, design), grid)
    K = _overdraw(grid, L)
    *kcols, kb, kr = jax.random.split(key, len(dims) + 2)
    cols = [jax.random.randint(kc, (K,), 0, d)
            for kc, d in zip(kcols, dims)]
    # pass 1: lexicographic sort marks first occurrences
    cols_s = lax.sort(tuple(cols), num_keys=len(dims))
    dup = _ft.reduce(
        lambda a, c: a & (c == jnp.roll(c, 1)), cols_s,
        jnp.ones(K, bool),
    )
    dup = dup.at[0].set(False)
    # pass 2: uniform subselection — distinct entries sort by a random
    # key, duplicates to the back (+inf), take the first L slots
    rnd = jax.random.uniform(kr, (K,))
    sel_key = jnp.where(dup, jnp.inf, rnd)
    sorted2 = lax.sort((sel_key, *cols_s, dup), num_keys=1)
    outs = [c[:L] for c in sorted2[1:-1]]
    valid = ~sorted2[-1][:L]
    if design == "swor":
        take = jnp.asarray(L, jnp.float32)
    else:
        p = budget / grid
        sd = math.sqrt(grid * p * (1.0 - p))
        draw = jnp.round(
            budget + sd * jax.random.normal(kb, (), jnp.float32)
        )
        take = jnp.clip(draw, 1.0, float(L))
    w = (valid & (jnp.arange(L) < take)).astype(jnp.float32)
    return outs, w


def _check_design(design: str) -> None:
    if design not in ("swr", "swor", "bernoulli"):
        raise ValueError(
            f"unknown sampling design {design!r}; "
            "choose 'swr', 'swor', or 'bernoulli'"
        )


def draw_pair_design_device(
    key,
    n1: int,
    n2: int,
    n_pairs: int,
    design: str = "swr",
    *,
    one_sample: bool = False,
):
    """(i, j, w) sampling the n1 x n2 grid under ``design`` — the
    device-side mirror of parallel.partition.draw_pair_design.

    one_sample encodes the off-diagonal of an (n1 x n1) grid with
    n2 = n1 - 1 columns, exactly like the host sampler: dedup happens
    in encoded (pre-shift) coordinates, the returned j is shifted past
    i for direct indexing.
    """
    from tuplewise_tpu.ops.pair_tiles import sample_pair_indices

    if design == "swr":
        i, j = sample_pair_indices(key, n1, n2 + (1 if one_sample else 0),
                                   n_pairs, one_sample)
        return i, j, jnp.ones(n_pairs, jnp.float32)
    _check_design(design)
    (i_f, j_f), w = _distinct_design(
        key, (n1, n2), n_pairs, design, "tuples"
    )
    if one_sample:
        j_f = jnp.where(j_f >= i_f, j_f + 1, j_f)
    return i_f, j_f, w


def draw_triplet_design_device(
    key,
    n1: int,
    n2: int,
    n_triplets: int,
    design: str = "swr",
):
    """(i, j, k, w) sampling the off-diagonal triple grid
    {i != j in [0, n1)} x [0, n2) under ``design`` — the degree-3
    mirror of draw_pair_design_device for the triplet trainer's
    per-step budgets [SURVEY §1.2 item 4 at degree 3]. The positive
    index j is encoded off-diagonal (n1 - 1 columns) during dedup and
    shifted past i on return, exactly like the host sampler."""
    if design == "swr":
        ki, kj, kk = jax.random.split(key, 3)
        i = jax.random.randint(ki, (n_triplets,), 0, n1)
        j = jax.random.randint(kj, (n_triplets,), 0, n1 - 1)
        j = jnp.where(j >= i, j + 1, j)
        k = jax.random.randint(kk, (n_triplets,), 0, n2)
        return i, j, k, jnp.ones(n_triplets, jnp.float32)
    _check_design(design)
    (i_f, j_f, k_f), w = _distinct_design(
        key, (n1, n1 - 1, n2), n_triplets, design, "triples"
    )
    j_f = jnp.where(j_f >= i_f, j_f + 1, j_f)
    return i_f, j_f, k_f, w
