"""On-device tuple-sampling designs for the LEARNING path
[SURVEY §1.2 item 4; VERDICT r3 next #6].

The estimation side draws its distinct-tuple designs on the host
(parallel.partition.draw_pair_design) — fine for M Monte-Carlo reps,
impossible for a trainer whose steps live inside one jitted `lax.scan`.
This module is the TPU-native equivalent: fixed-shape, sort-based,
O(K log K) per draw, usable per step per worker under shard_map/vmap.

Construction (all shapes static):

  swr        B i.i.d. uniform grid draws — the existing behavior.
  swor       overdraw K with replacement such that the distinct count
             D >= B with ~8-sigma headroom (K solves
             G(1 - e^{-K/G}) = B + 8 sqrt(B), the coupon-collector
             expectation), lexicographically sort (i, j) to mark first
             occurrences, then uniformly subselect EXACTLY B of the D
             distinct tuples by sorting on random keys (+inf for
             duplicates). Each B-subset of the grid is equally likely,
             conditional on D >= B — the same design as the host
             sampler up to the astronomically rare D < B shortfall,
             which the weight mask prices correctly (renormalized mean,
             never a wrong estimate).
  bernoulli  realized size K_real ~ Binomial(G, B/G) (normal
             approximation — exact to float tolerance for the G >= 10^4
             grids the budget regime uses), then the swor machinery
             keeps the first min(K_real, D, L) selected tuples.

Returns (i, j, w): [L] index arrays plus a {0,1} weight mask; consumers
compute sum(vals * w) / sum(w). L = B for swr/swor and B + 8 sqrt(B)
for bernoulli, so every design compiles once per (B, grid) shape.

Why sort-based dedup and not linearized `jnp.unique`: the per-worker
grid m1*m2 reaches 4e11 at production block sizes — linearizing
overflows int32 and this library never enables x64; lexicographic
two-key `lax.sort` needs neither.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _overdraw(grid: int, budget: int) -> int:
    """Static with-replacement draw count K such that the expected
    distinct count G(1 - e^{-K/G}) covers budget + 8 sqrt(budget).
    Callers bound budget <= 0.8 grid, so the coverage fraction stays
    below ~0.95 and K below ~3 G — the coupon-collector blow-up near
    full coverage (K ~ G ln G at budget = G) never engages."""
    target = min(budget + 8.0 * math.sqrt(budget) + 8.0, 0.95 * grid)
    frac = target / grid
    k = -grid * math.log1p(-frac)
    return max(budget, int(math.ceil(k)))


def draw_pair_design_device(
    key,
    n1: int,
    n2: int,
    n_pairs: int,
    design: str = "swr",
    *,
    one_sample: bool = False,
):
    """(i, j, w) sampling the n1 x n2 grid under ``design`` — the
    device-side mirror of parallel.partition.draw_pair_design.

    one_sample encodes the off-diagonal of an (n1 x n1) grid with
    n2 = n1 - 1 columns, exactly like the host sampler: dedup happens
    in encoded (pre-shift) coordinates, the returned j is shifted past
    i for direct indexing.
    """
    from tuplewise_tpu.ops.pair_tiles import sample_pair_indices

    grid = n1 * n2
    if design == "swr":
        i, j = sample_pair_indices(key, n1, n2 + (1 if one_sample else 0),
                                   n_pairs, one_sample)
        return i, j, jnp.ones(n_pairs, jnp.float32)
    if design not in ("swor", "bernoulli"):
        raise ValueError(
            f"unknown sampling design {design!r}; "
            "choose 'swr', 'swor', or 'bernoulli'"
        )
    if n_pairs > 0.8 * grid:
        # near-full-grid distinct sampling needs coupon-collector
        # overdraw (K ~ G ln G) and the exactly-B contract degrades to
        # a probabilistic shortfall; at these fractions the COMPLETE
        # estimator is cheaper anyway — the host sampler
        # (parallel.partition.draw_pair_design) covers B up to G.
        raise ValueError(
            f"cannot draw {n_pairs} distinct tuples from a {grid} grid "
            "on device (> 0.8 * grid); use the complete estimator or "
            "the host sampler"
        )
    from tuplewise_tpu.parallel.partition import design_pad_len

    L = min(design_pad_len(n_pairs, design), grid)
    K = _overdraw(grid, L)
    ki, kj, kk, kr = jax.random.split(key, 4)
    i = jax.random.randint(ki, (K,), 0, n1)
    j = jax.random.randint(kj, (K,), 0, n2)  # encoded (pre-shift) col
    # pass 1: lexicographic sort on (i, j) marks first occurrences
    i_s, j_s = lax.sort((i, j), num_keys=2)
    dup = (i_s == jnp.roll(i_s, 1)) & (j_s == jnp.roll(j_s, 1))
    dup = dup.at[0].set(False)
    # pass 2: uniform subselection — distinct entries sort by a random
    # key, duplicates to the back (+inf), take the first L slots
    rnd = jax.random.uniform(kr, (K,))
    sel_key = jnp.where(dup, jnp.inf, rnd)
    _, i_f, j_f, dup_f = lax.sort((sel_key, i_s, j_s, dup), num_keys=1)
    i_f, j_f, valid = i_f[:L], j_f[:L], ~dup_f[:L]
    if design == "swor":
        take = jnp.asarray(L, jnp.float32)
    else:
        p = n_pairs / grid
        sd = math.sqrt(grid * p * (1.0 - p))
        draw = jnp.round(
            n_pairs + sd * jax.random.normal(kk, (), jnp.float32)
        )
        take = jnp.clip(draw, 1.0, float(L))
    w = (valid & (jnp.arange(L) < take)).astype(jnp.float32)
    if one_sample:
        j_f = jnp.where(j_f >= i_f, j_f + 1, j_f)
    return i_f, j_f, w
