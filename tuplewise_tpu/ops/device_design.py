"""On-device tuple-sampling designs for the LEARNING path
[SURVEY §1.2 item 4; VERDICT r3 next #6].

The estimation side draws its distinct-tuple designs on the host
(parallel.partition.draw_pair_design) — fine for M Monte-Carlo reps,
impossible for a trainer whose steps live inside one jitted `lax.scan`.
This module is the TPU-native equivalent: fixed-shape, sort-based,
O(K log K) per draw, usable per step per worker under shard_map/vmap.

Construction (all shapes static):

  swr        B i.i.d. uniform grid draws — the existing behavior.
  swor       overdraw K with replacement such that the distinct count
             D >= B with ~8-sigma headroom (K solves
             G(1 - e^{-K/G}) = B + 8 sqrt(B), the coupon-collector
             expectation), lexicographically sort (i, j) to mark first
             occurrences, then uniformly subselect EXACTLY B of the D
             distinct tuples by sorting on random keys (+inf for
             duplicates). Each B-subset of the grid is equally likely,
             conditional on D >= B — the same design as the host
             sampler up to the astronomically rare D < B shortfall,
             which the weight mask prices correctly (renormalized mean,
             never a wrong estimate).
  bernoulli  realized size K_real ~ Binomial(G, B/G) — drawn EXACTLY
             for G <= _EXACT_BINOMIAL_MAX_G by reducing G device
             Bernoulli bits (a true Binomial draw, 0 included: a small
             grid at a small rate realizes an EMPTY design ~(1-p)^G of
             the time, and consumers price that as a zero-weight step,
             see below) [VERDICT r4 next #2]; the normal approximation
             serves only grids ABOVE that threshold, safely inside its
             documented G >= 10^4 validity bound. Either way the swor
             machinery keeps the first min(K_real, D, L) selected
             tuples.

Returns (i, j, w): [L] index arrays plus a {0,1} weight mask.
LEARNING consumers compute sum(vals * w) / max(sum(w), 1) — the max
prices an empty bernoulli realization as a zero-loss, zero-gradient
step instead of NaN. ESTIMATION consumers (jax/mesh backends, both
harness runners) pass ``floor_one=True`` instead: bernoulli's realized
size clamps at >= 1, the host oracle's documented semantics ("floored
at 1 so the estimator stays defined") — a mean over an empty tuple set
has no value to price. L = B for swr/swor and B + 8 sqrt(B) for
bernoulli, so every design compiles once per (B, grid) shape.

Why sort-based dedup and not linearized `jnp.unique`: the per-worker
grid m1*m2 reaches 4e11 at production block sizes — linearizing
overflows int32 and this library never enables x64; lexicographic
two-key `lax.sort` needs neither.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

# bernoulli realized-size threshold: at or below this grid size the
# Binomial draw is EXACT (G reduced Bernoulli bits, O(G) per draw —
# negligible against the O(K log K) dedup sort); above it the normal
# approximation runs, always inside its documented G >= 10^4 bound
# [VERDICT r4 next #2]
_EXACT_BINOMIAL_MAX_G = 65536


def _overdraw(grid: int, budget: int) -> int:
    """Static with-replacement draw count K such that the expected
    distinct count G(1 - e^{-K/G}) covers budget + 8 sqrt(budget).
    Callers bound budget <= 0.8 grid, so the coverage fraction stays
    below ~0.95 and K below ~3 G — the coupon-collector blow-up near
    full coverage (K ~ G ln G at budget = G) never engages."""
    target = min(budget + 8.0 * math.sqrt(budget) + 8.0, 0.95 * grid)
    frac = target / grid
    k = -grid * math.log1p(-frac)
    return max(budget, int(math.ceil(k)))


def _distinct_design(key, dims, budget: int, design: str, what: str,
                     floor_one: bool = False):
    """(cols, w): ``budget``-sized distinct-tuple draw from the product
    grid prod(dims), in ENCODED coordinates (off-diagonal encodings are
    the callers' business). ONE implementation of the
    overdraw → multi-key-lex-sort dedup → uniform-subselect machinery
    for every arity: `lax.sort(num_keys=len(dims))` generalizes the
    dedup, so no grid linearization (int32 overflow) at any degree.

    Key-split discipline (STABLE — committed rows reproduce these
    draws): split(key, len(dims) + 2) = per-column keys, the bernoulli
    size key, the subselection key, in that order.
    """
    import functools as _ft

    grid = math.prod(dims)
    if budget > 0.8 * grid:
        # near-full-grid distinct sampling needs coupon-collector
        # overdraw (K ~ G ln G) and the exactly-B contract degrades to
        # a probabilistic shortfall; at these fractions the COMPLETE
        # estimator is cheaper anyway — the host samplers
        # (parallel.partition) cover budgets up to G.
        raise ValueError(
            f"cannot draw {budget} distinct {what} from a {grid} grid "
            "on device (> 0.8 * grid); use the complete estimator or "
            "the host sampler"
        )
    from tuplewise_tpu.parallel.partition import design_pad_len

    L = min(design_pad_len(budget, design), grid)
    K = _overdraw(grid, L)
    *kcols, kb, kr = jax.random.split(key, len(dims) + 2)
    cols = [jax.random.randint(kc, (K,), 0, d)
            for kc, d in zip(kcols, dims)]
    # pass 1: lexicographic sort marks first occurrences
    cols_s = lax.sort(tuple(cols), num_keys=len(dims))
    dup = _ft.reduce(
        lambda a, c: a & (c == jnp.roll(c, 1)), cols_s,
        jnp.ones(K, bool),
    )
    dup = dup.at[0].set(False)
    # pass 2: uniform subselection — distinct entries sort by a random
    # key, duplicates to the back (+inf), take the first L slots
    rnd = jax.random.uniform(kr, (K,))
    sel_key = jnp.where(dup, jnp.inf, rnd)
    sorted2 = lax.sort((sel_key, *cols_s, dup), num_keys=1)
    outs = [c[:L] for c in sorted2[1:-1]]
    valid = ~sorted2[-1][:L]
    if design == "swor":
        take = jnp.asarray(L, jnp.float32)
    else:
        p = budget / grid
        if grid <= _EXACT_BINOMIAL_MAX_G:
            # EXACT Binomial(G, p): reduce G device Bernoulli bits
            # [VERDICT r4 next #2]. Zero is a legitimate realization
            # ((1-p)^G ~ 1% at G=16, p=1/4) — consumers divide by
            # max(sum(w), 1), so an empty design is a zero-weight
            # step, never NaN.
            bits = jax.random.uniform(kb, (grid,)) < p
            draw = jnp.sum(bits).astype(jnp.float32)
        else:
            # normal approximation — only ever reached at
            # G > _EXACT_BINOMIAL_MAX_G, inside the documented
            # G >= 10^4 validity bound (TV error O(1/sqrt(G p (1-p)))
            draw = jnp.round(
                budget
                + math.sqrt(grid * p * (1.0 - p))
                * jax.random.normal(kb, (), jnp.float32)
            )
        # floor_one mirrors the host oracle's documented estimation
        # semantics ("floored at 1 so the estimator stays defined",
        # parallel.partition.draw_pair_design); the learning consumers
        # keep the TRUE draw (0 included — a zero-weight step)
        take = jnp.clip(draw, 1.0 if floor_one else 0.0, float(L))
    w = (valid & (jnp.arange(L) < take)).astype(jnp.float32)
    return outs, w


def _check_design(design: str) -> None:
    if design not in ("swr", "swor", "bernoulli"):
        raise ValueError(
            f"unknown sampling design {design!r}; "
            "choose 'swr', 'swor', or 'bernoulli'"
        )


def draw_pair_design_device(
    key,
    n1: int,
    n2: int,
    n_pairs: int,
    design: str = "swr",
    *,
    one_sample: bool = False,
    floor_one: bool = False,
):
    """(i, j, w) sampling the n1 x n2 grid under ``design`` — the
    device-side mirror of parallel.partition.draw_pair_design.

    one_sample encodes the off-diagonal of an (n1 x n1) grid with
    n2 = n1 - 1 columns, exactly like the host sampler: dedup happens
    in encoded (pre-shift) coordinates, the returned j is shifted past
    i for direct indexing.

    floor_one: clamp bernoulli's realized size at >= 1 — the host
    oracle's ESTIMATION semantics (a mean over an empty tuple set is
    undefined, so the estimator-side callers keep it defined); the
    learning consumers leave it False and price an empty draw as a
    zero-weight step.
    """
    from tuplewise_tpu.ops.pair_tiles import sample_pair_indices

    if design == "swr":
        i, j = sample_pair_indices(key, n1, n2 + (1 if one_sample else 0),
                                   n_pairs, one_sample)
        return i, j, jnp.ones(n_pairs, jnp.float32)
    _check_design(design)
    (i_f, j_f), w = _distinct_design(
        key, (n1, n2), n_pairs, design, "tuples", floor_one=floor_one
    )
    if one_sample:
        j_f = jnp.where(j_f >= i_f, j_f + 1, j_f)
    return i_f, j_f, w


def draw_triplet_design_device(
    key,
    n1: int,
    n2: int,
    n_triplets: int,
    design: str = "swr",
    *,
    floor_one: bool = False,
):
    """(i, j, k, w) sampling the off-diagonal triple grid
    {i != j in [0, n1)} x [0, n2) under ``design`` — the degree-3
    mirror of draw_pair_design_device for the triplet trainer's
    per-step budgets [SURVEY §1.2 item 4 at degree 3]. The positive
    index j is encoded off-diagonal (n1 - 1 columns) during dedup and
    shifted past i on return, exactly like the host sampler.
    ``floor_one``: see draw_pair_design_device."""
    if design == "swr":
        ki, kj, kk = jax.random.split(key, 3)
        i = jax.random.randint(ki, (n_triplets,), 0, n1)
        j = jax.random.randint(kj, (n_triplets,), 0, n1 - 1)
        j = jnp.where(j >= i, j + 1, j)
        k = jax.random.randint(kk, (n_triplets,), 0, n2)
        return i, j, k, jnp.ones(n_triplets, jnp.float32)
    _check_design(design)
    (i_f, j_f, k_f), w = _distinct_design(
        key, (n1, n1 - 1, n2), n_triplets, design, "triples",
        floor_one=floor_one,
    )
    j_f = jnp.where(j_f >= i_f, j_f + 1, j_f)
    return i_f, j_f, k_f, w


def shard_design_blocks(cols, w, n_shards: int, dtype=None):
    """Pad a [L] device draw to n_shards * per and shape [N, per]
    worker blocks + weight mask — the ONE copy of the mesh sharding
    helper used by backends.mesh_backend and harness.mesh_mc (a
    padding/weight change must hit both consumers at once)."""
    L = cols[0].shape[0]
    per = -(-L // n_shards)
    pad = n_shards * per - L
    out = [jnp.pad(c, (0, pad)).reshape(n_shards, per) for c in cols]
    wp = jnp.pad(w, (0, pad)).reshape(n_shards, per)
    out.append(wp if dtype is None else wp.astype(dtype))
    return out
