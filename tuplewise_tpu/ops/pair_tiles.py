"""Tiled tuplewise reductions for XLA — the hot loop of the framework.

The complete U-statistic at n=10^7 touches ~10^14 pairs; the pair grid is
NEVER materialized [SURVEY §7 "Hard parts"]. Instead inputs are padded to
tile multiples and reduced with nested `lax.scan` over (tile_a x tile_b)
blocks: per-step memory is one block, per-step compute is a dense
vectorized kernel evaluation (elementwise VPU work for score kernels, an
MXU matmul for feature kernels via a @ b.T inside sqdist).

Reductions are mask- and id-aware:
* masks make padded/stratified packings exact (renormalize by the true
  pair count inside the reduction [SURVEY §7 "Proportional sharding"]);
* ids exclude coincident original indices, which keeps one-sample
  statistics unbiased under with-replacement repartitioning (same
  discipline as the NumPy oracle backend).

Numerics: TPUs have no native float64 (and mixing f64 accumulators with
MXU dots crashes this toolchain's compiler), so scalar accumulators use
KAHAN-COMPENSATED float32 for kernel sums — an indicator kernel summed
over >2^24 pairs would silently lose increments in plain f32 — and a
split int32 (lo, hi) base-2^24 counter for pair counts, exact to 2^55
pairs with no int64/float64 anywhere (this library does NOT touch the
global x64 flag). Tile bodies are wrapped in `jax.checkpoint`, so
`jax.grad` through a pair reduction re-streams tiles instead of storing
the grid [SURVEY §7 "Hard parts"].
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_COUNT_RADIX = 1 << 24  # tile counts must stay below this for exactness


def _pad_axis0(x: jnp.ndarray, tile: int) -> jnp.ndarray:
    n = x.shape[0]
    rem = (-n) % tile
    if rem == 0:
        return x
    pad = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _tiles(x: jnp.ndarray, tile: int) -> jnp.ndarray:
    """[n, ...] -> [n_tiles, tile, ...] (zero-padded)."""
    x = _pad_axis0(x, tile)
    return x.reshape((x.shape[0] // tile, tile) + x.shape[1:])


def _kahan_add(s, comp, x):
    """One compensated-summation step; linear, hence cleanly differentiable."""
    y = x - comp
    t = s + y
    comp = (t - s) - y
    return t, comp


def _acc_init(dtype):
    return (
        jnp.zeros((), dtype),           # kahan sum
        jnp.zeros((), dtype),           # kahan compensation
        jnp.zeros((), jnp.int32),       # count low digit (base 2^24)
        jnp.zeros((), jnp.int32),       # count high digit
    )


def _acc_update(carry, tile_sum, tile_count):
    """tile_count is int32 < 2^24; the (lo, hi) pair stays exact to 2^55."""
    s, comp, lo, hi = carry
    s, comp = _kahan_add(s, comp, tile_sum)
    lo = lo + tile_count
    carry_digit = lo >> 24
    return (s, comp, lo - (carry_digit << 24), hi + carry_digit)


def _acc_final(carry) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum, count) with count reconstructed in the sum's dtype.

    The reconstruction rounds to f32 (relative error ~1e-7 past 2^24
    pairs) — negligible against the f32 storage of the sum itself.
    """
    s, comp, lo, hi = carry
    # comp = (t - s) - y holds the NEGATIVE of the lost low-order bits
    total = s - comp
    count = hi.astype(s.dtype) * s.dtype.type(_COUNT_RADIX) + lo.astype(s.dtype)
    return total, count


def pair_stats(
    kernel,
    A: jnp.ndarray,
    B: jnp.ndarray,
    mask_a: Optional[jnp.ndarray] = None,
    mask_b: Optional[jnp.ndarray] = None,
    ids_a: Optional[jnp.ndarray] = None,
    ids_b: Optional[jnp.ndarray] = None,
    *,
    tile_a: int = 1024,
    tile_b: int = 1024,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum, count) of h over the masked A x B grid, streamed in tiles.

    Args:
      A, B: [n1(, d)], [n2(, d)] score vectors or feature matrices.
      mask_a/mask_b: optional {0,1} float validity masks.
      ids_a/ids_b: optional int original-index arrays; grid cells with
        ids_a[i] == ids_b[j] are excluded (one-sample diagonal and
        with-replacement duplicates).

    Returns (weighted_sum, count), both scalars in A's dtype; the caller
    divides. Differentiable w.r.t. A/B (tiles checkpointed).
    """
    if tile_a * tile_b >= _COUNT_RADIX:
        raise ValueError(
            f"tile_a*tile_b = {tile_a * tile_b} must be < 2^24 "
            "for exact pair counting"
        )
    use_ids = ids_a is not None
    dtype = A.dtype
    # Fast path: no masks/ids and no padding needed -> the weight grid is
    # all-ones. Skipping the mask multiply + count reduction saves ~1/3
    # of the per-pair VPU work in the common complete-U case.
    unweighted = (
        mask_a is None and mask_b is None and not use_ids
        and A.shape[0] % tile_a == 0 and B.shape[0] % tile_b == 0
    )
    ma = jnp.ones(A.shape[0], dtype) if mask_a is None else mask_a
    mb = jnp.ones(B.shape[0], dtype) if mask_b is None else mask_b

    a_t, ma_t = _tiles(A, tile_a), _tiles(ma, tile_a)
    b_t, mb_t = _tiles(B, tile_b), _tiles(mb, tile_b)
    if use_ids:
        ia_t = _tiles(ids_a.astype(jnp.int32), tile_a)
        ib_t = _tiles(ids_b.astype(jnp.int32), tile_b)
    else:  # dummies keep the scan signature static
        ia_t = jnp.zeros(a_t.shape[:2], jnp.int32)
        ib_t = jnp.zeros(b_t.shape[:2], jnp.int32)

    @jax.checkpoint
    def tile_term(a, ma_, ia, b, mb_, ib):
        vals = kernel.pair_matrix(a, b, jnp)
        if unweighted:
            return (
                jnp.sum(vals, dtype=dtype),
                jnp.asarray(tile_a * tile_b, jnp.int32),
            )
        w = ma_[:, None] * mb_[None, :]
        if use_ids:
            w = w * (ia[:, None] != ib[None, :]).astype(dtype)
        tile_sum = jnp.sum(vals * w, dtype=dtype)
        tile_count = jnp.sum(w > 0, dtype=jnp.int32)
        return tile_sum, tile_count

    def inner(carry, xs_b, a, ma_, ia):
        b, mb_, ib = xs_b
        ds, dc = tile_term(a, ma_, ia, b, mb_, ib)
        return _acc_update(carry, ds, dc), None

    def outer(carry, xs_a):
        a, ma_, ia = xs_a
        out, _ = lax.scan(
            functools.partial(inner, a=a, ma_=ma_, ia=ia),
            carry,
            (b_t, mb_t, ib_t),
        )
        return out, None

    carry, _ = lax.scan(outer, _acc_init(dtype), (a_t, ma_t, ia_t))
    return _acc_final(carry)


def pair_mean(kernel, A, B, **kw) -> jnp.ndarray:
    s, c = pair_stats(kernel, A, B, **kw)
    return s / c.astype(s.dtype)


def triplet_stats(
    kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    mask_x: Optional[jnp.ndarray] = None,
    mask_y: Optional[jnp.ndarray] = None,
    ids_x: Optional[jnp.ndarray] = None,
    *,
    positives: Optional[jnp.ndarray] = None,
    mask_p: Optional[jnp.ndarray] = None,
    ids_p: Optional[jnp.ndarray] = None,
    tile: int = 128,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum, count) of h(x_i, p_j, y_k) over ids_x[i] != ids_p[j], all k.

    By default positives = X (the within-sample degree-(2,1) statistic);
    the ring backend passes a *visiting* positives block instead, so the
    same reduction serves single-device and cross-shard paths.

    Triple-nested tile scan; per-step block is [tile, tile, tile]
    (default 128^3 = 2M values). Complete degree-3 runs only at small n
    [SURVEY §7 step 7]; the incomplete sampler is the scalable path.
    """
    if tile**3 >= _COUNT_RADIX:
        raise ValueError(
            f"tile^3 = {tile**3} must be < 2^24 for exact tuple counting"
        )
    dtype = X.dtype
    mx = jnp.ones(X.shape[0], dtype) if mask_x is None else mask_x
    my = jnp.ones(Y.shape[0], dtype) if mask_y is None else mask_y
    ix = (jnp.arange(X.shape[0]) if ids_x is None else ids_x).astype(jnp.int32)
    if positives is None:
        positives, mp_, ip = X, mx, ix
    else:
        mp_ = jnp.ones(positives.shape[0], dtype) if mask_p is None else mask_p
        ip = (jnp.arange(positives.shape[0]) if ids_p is None else ids_p
              ).astype(jnp.int32)

    x_t, mx_t, ix_t = _tiles(X, tile), _tiles(mx, tile), _tiles(ix, tile)
    p_all_t, mp_all_t, ip_all_t = (
        _tiles(positives, tile), _tiles(mp_, tile), _tiles(ip, tile)
    )
    y_t, my_t = _tiles(Y, tile), _tiles(my, tile)

    @jax.checkpoint
    def tile_term(a, ma_, ia, p, mp_, ip, yk, mk_):
        # [ta, tp, tk] block: anchors x positives x negatives
        vals = kernel.triplet_values(
            a[:, None, None, :], p[None, :, None, :], yk[None, None, :, :], jnp
        )
        w = (
            ma_[:, None, None]
            * mp_[None, :, None]
            * mk_[None, None, :]
            * (ia[:, None, None] != ip[None, :, None]).astype(dtype)
        )
        return (
            jnp.sum(vals * w, dtype=dtype),
            jnp.sum(w > 0, dtype=jnp.int32),
        )

    def scan_k(carry, xs_k, a, ma_, ia, p, mp_, ip):
        yk, mk_ = xs_k
        ds, dc = tile_term(a, ma_, ia, p, mp_, ip, yk, mk_)
        return _acc_update(carry, ds, dc), None

    def scan_j(carry, xs_j, a, ma_, ia):
        p, mp2, ip2 = xs_j
        out, _ = lax.scan(
            functools.partial(scan_k, a=a, ma_=ma_, ia=ia, p=p, mp_=mp2, ip=ip2),
            carry,
            (y_t, my_t),
        )
        return out, None

    def scan_i(carry, xs_i):
        a, ma_, ia = xs_i
        out, _ = lax.scan(
            functools.partial(scan_j, a=a, ma_=ma_, ia=ia),
            carry,
            (p_all_t, mp_all_t, ip_all_t),
        )
        return out, None

    carry, _ = lax.scan(scan_i, _acc_init(dtype), (x_t, mx_t, ix_t))
    return _acc_final(carry)


# ---------------------------------------------------------------------------
# Incomplete (sampled) statistics [SURVEY §4.3]
# ---------------------------------------------------------------------------

def sample_pair_indices(key, n1: int, n2: int, n_pairs: int, one_sample: bool):
    """B tuple indices drawn uniformly with replacement from the grid;
    one-sample draws j from the off-diagonal (j != i) via the shift trick."""
    ki, kj = jax.random.split(key)
    i = jax.random.randint(ki, (n_pairs,), 0, n1)
    if one_sample:
        j = jax.random.randint(kj, (n_pairs,), 0, n2 - 1)
        j = jnp.where(j >= i, j + 1, j)
    else:
        j = jax.random.randint(kj, (n_pairs,), 0, n2)
    return i, j


def incomplete_pair_mean(kernel, key, A, B, n_pairs: int, one_sample: bool):
    i, j = sample_pair_indices(key, A.shape[0], B.shape[0], n_pairs, one_sample)
    vals = kernel.pair_elementwise(A[i], B[j], jnp)
    return jnp.mean(vals, dtype=A.dtype)


def incomplete_triplet_mean(kernel, key, X, Y, n_pairs: int):
    k1, k2 = jax.random.split(key)
    i, j = sample_pair_indices(k1, X.shape[0], X.shape[0], n_pairs, True)
    k = jax.random.randint(k2, (n_pairs,), 0, Y.shape[0])
    vals = kernel.triplet_values(X[i], X[j], Y[k], jnp)
    return jnp.mean(vals, dtype=X.dtype)


# --------------------------------------------------------------------- #
# Analytic pairwise-loss gradient: streamed g' row/col reductions        #
# --------------------------------------------------------------------- #

def pair_grad_sums(kernel, s1, s2, *, tile_a: int = 1024,
                   tile_b: int = 1024):
    """(row, col) sums of g'(s1_i - s2_j) over the full grid, streamed.

    row[i] = sum_j g'(d_ij), col[j] = sum_i g'(d_ij) — the score
    cotangents of the mean pairwise loss up to 1/count and the d-sign.
    One forward-style traversal of the grid (both reductions per tile);
    no autodiff, no tile recompute. Padded rows/cols are masked out by
    static index masks, so any sizes are accepted.
    """
    gp = kernel.diff_grad_fn
    if gp is None:
        raise ValueError(
            f"kernel {kernel.name!r} has no diff_grad_fn"
        )
    n1, n2 = s1.shape[0], s2.shape[0]
    a_t = _tiles(s1, tile_a)                      # [g1, ta]
    b_t = _tiles(s2, tile_b)                      # [g2, tb]
    rm_t = _tiles(
        (jnp.arange(a_t.size) < n1).astype(s1.dtype), tile_a
    )
    cm_t = _tiles(
        (jnp.arange(b_t.size) < n2).astype(s2.dtype), tile_b
    )
    g2 = b_t.shape[0]

    def outer(col_acc, a_rm):
        a_tile, rm = a_rm

        def inner(carry, jb):
            row_acc, col_acc = carry
            j, b_tile, cm = jb
            t = gp(a_tile[:, None] - b_tile[None, :], jnp)
            t = t * rm[:, None] * cm[None, :]
            row_acc = row_acc + jnp.sum(t, axis=1)
            col_acc = lax.dynamic_update_slice(
                col_acc,
                lax.dynamic_slice(col_acc, (j * tile_b,), (tile_b,))
                + jnp.sum(t, axis=0),
                (j * tile_b,),
            )
            return (row_acc, col_acc), None

        (row_tile, col_acc), _ = lax.scan(
            inner,
            (jnp.zeros(tile_a, s1.dtype), col_acc),
            (jnp.arange(g2), b_t, cm_t),
        )
        return col_acc, row_tile

    col, rows = lax.scan(
        outer, jnp.zeros(b_t.size, s2.dtype), (a_t, rm_t)
    )
    return rows.reshape(-1)[:n1], col[:n2]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 3, 4))
def diff_pair_mean(kernel, s1, s2, tile_a, tile_b):
    """mean of g(s1_i - s2_j), differentiable via the ANALYTIC g'
    streaming pass (pair_grad_sums) instead of autodiff through the
    checkpointed tile scan — the backward pass costs one grid
    traversal, not a recompute-plus-transpose per tile (~100x on the
    learner's all-pairs gradient at n=10^5). Value is identical to
    pair_mean; gradients match jax.grad of the dense mean (hinge: up
    to the measure-zero kink at d == 1)."""
    s, c = pair_stats(kernel, s1, s2, tile_a=tile_a, tile_b=tile_b)
    return s / c.astype(s.dtype)


def _use_fused_pallas(kernel, s1, s2):
    """True when the ONE-PASS fused Pallas loss+grad kernel serves this
    platform and shape [VERDICT r3 next #2]: the col accumulator holds
    the padded b side resident in VMEM (so huge n2 stays off), and the
    per-row-block loss cells bound n1 by the SMEM budget (the two-pass
    pallas_pair_grad_sums backward covers larger n1 — no SMEM cells).
    TUPLEWISE_HARNESS_PALLAS=interpret|off overrides, as in the
    harness hot loops."""
    import jax

    from tuplewise_tpu.ops.pallas_pairs import (
        FUSED_TILE_A, MAX_ROW_BLOCKS, resolve_pallas_mode,
    )

    use_pallas, interpret = resolve_pallas_mode(
        jax.devices()[0].platform
    )
    return (
        use_pallas and kernel.diff_grad_fn is not None
        and s2.shape[0] <= 1_000_000  # ~4 MB VMEM col bound
        # SMEM loss-cell budget at the fused kernel's own row tile
        and -(-s1.shape[0] // FUSED_TILE_A) <= MAX_ROW_BLOCKS,
        interpret,
    )


def _diff_pair_mean_fwd(kernel, s1, s2, tile_a, tile_b):
    fused, interpret = _use_fused_pallas(kernel, s1, s2)
    if fused:
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_loss_grad

        s, row, col = pallas_pair_loss_grad(
            s1, s2, kernel=kernel, interpret=interpret
        )
        cnt = float(s1.shape[0] * s2.shape[0])
        # residuals ARE the gradient reductions: the backward costs
        # O(n) scaling, the whole step touches the grid once
        return (s / cnt).astype(s1.dtype), (
            (row.astype(s1.dtype), col.astype(s2.dtype)), None
        )
    s, c = pair_stats(kernel, s1, s2, tile_a=tile_a, tile_b=tile_b)
    return s / c.astype(s.dtype), (None, (s1, s2))


def grad_sums_best(kernel, s1, s2, tile_a: int = 1024, tile_b: int = 1024):
    """(row, col) g' sums via the fastest path for this platform/shape:
    the one-pass Pallas grad kernel when it serves (TPU or forced
    interpret, analytic g', n2 within the VMEM-resident col bound — its
    row output is per-block VMEM, so no n1 SMEM-cell budget applies),
    the XLA streamed scan otherwise. Outputs in the inputs' dtypes."""
    import jax

    from tuplewise_tpu.ops.pallas_pairs import resolve_pallas_mode

    use_pallas, interpret = resolve_pallas_mode(
        jax.devices()[0].platform
    )
    if (use_pallas and kernel.diff_grad_fn is not None
            and s2.shape[0] <= 1_000_000):
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_grad_sums

        row, col = pallas_pair_grad_sums(
            s1, s2, kernel=kernel, interpret=interpret
        )
    else:
        row, col = pair_grad_sums(
            kernel, s1, s2, tile_a=tile_a, tile_b=tile_b
        )
    return row.astype(s1.dtype), col.astype(s2.dtype)


def _diff_pair_mean_bwd(kernel, tile_a, tile_b, res, ct):
    precomputed, data = res
    if precomputed is not None:
        row, col = precomputed
    else:
        # n1 too large for the fused kernel's SMEM loss cells (or no
        # Pallas at all): the best grad-only pass covers the backward
        s1, s2 = data
        row, col = grad_sums_best(
            kernel, s1, s2, tile_a=tile_a, tile_b=tile_b
        )
    # python float, not int: the pair count can exceed int32 inside jit
    inv = ct / float(row.shape[0] * col.shape[0])
    # d/ds1_i = +mean_j g'; d/ds2_j carries the -1 from d = s1 - s2
    return inv * row, -inv * col


diff_pair_mean.defvjp(_diff_pair_mean_fwd, _diff_pair_mean_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 3, 4))
def diff_pair_mean_loss_free(kernel, s1, s2, tile_a, tile_b):
    """Gradient-only sibling of diff_pair_mean [VERDICT r4 next #1]:
    the VALUE is NaN (never computed — callers use this only on steps
    whose loss is not recorded), the GRADIENT is bit-identical to
    diff_pair_mean's. The forward pass runs grad_sums_best directly —
    one g'-only grid traversal (pallas_pair_grad_sums, 6.7e11 g'-pairs/s
    at the trainer headline shape) instead of the fused loss+grad pass
    (4.34e11, whose g-body evaluation costs ~35% of the step for a value
    the trainer would discard)."""
    return jnp.full((), jnp.nan, s1.dtype)


def _diff_pair_mean_lf_fwd(kernel, s1, s2, tile_a, tile_b):
    row, col = grad_sums_best(kernel, s1, s2, tile_a=tile_a, tile_b=tile_b)
    return jnp.full((), jnp.nan, s1.dtype), (row, col)


def _diff_pair_mean_lf_bwd(kernel, tile_a, tile_b, res, ct):
    row, col = res
    inv = ct / float(row.shape[0] * col.shape[0])
    return inv * row, -inv * col


diff_pair_mean_loss_free.defvjp(_diff_pair_mean_lf_fwd, _diff_pair_mean_lf_bwd)


def pair_mean_for_grad(kernel, s1, s2, *, tile_a: int = 1024,
                       tile_b: int = 1024):
    """pair mean with the best available gradient path: analytic
    streamed g' when the kernel declares one, autodiff through the
    checkpointed tiles otherwise."""
    if kernel.kind == "diff" and kernel.diff_grad_fn is not None:
        return diff_pair_mean(kernel, s1, s2, tile_a, tile_b)
    return pair_mean(kernel, s1, s2, tile_a=tile_a, tile_b=tile_b)
