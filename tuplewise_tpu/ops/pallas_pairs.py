"""Pallas TPU kernel for the score-difference pair-sum hot loop.

The complete-U inner loop for diff kernels is elementwise VPU work over
the [n1, n2] difference grid. This kernel controls the layout explicitly:
the resident score block enters as a COLUMN [Ta, 1] (sublanes) and the
visiting block as a ROW [1, Tb] (lanes), so the broadcasted subtraction
is the natural sublane x lane outer pattern, computed tile-by-tile in
VMEM. Partial sums accumulate per ROW-BLOCK into a [g1, 2] SMEM
(sum, Kahan compensation) cell revisited across the sequential inner
grid (O(n1/Ta) scalars, never the O(n1*n2/(Ta*Tb)) per-cell grid), and
the row partials tree-reduce outside.

The g(d) body comes from the Kernel's own diff_fn (ops.kernels) — no
duplicated surrogate definitions. Used for unmasked complete statistics;
masked, id-aware, and differentiating callers use ops.pair_tiles (XLA).
CPU test execution uses interpret mode [pallas_guide: interpret=True].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tuplewise_tpu.ops.kernels import Kernel


def _pair_sum_kernel(a_ref, b_ref, o_ref, *, g):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[i, 0] = 0.0
        o_ref[i, 1] = 0.0

    # [Ta, 1] - [1, Tb] -> [Ta, Tb] sublane x lane broadcast
    d = a_ref[:, :] - b_ref[:, :]
    x = jnp.sum(g(d))
    # Kahan-compensated add into the (sum, comp) SMEM cell: a row-block
    # accumulator spans tile_a * n2 pairs (~1e10 at n=1e7), where plain
    # f32 += would round away ~tile-sized increments — the same numerics
    # contract as pair_tiles._kahan_add.
    y = x - o_ref[i, 1]
    t = o_ref[i, 0] + y
    o_ref[i, 1] = (t - o_ref[i, 0]) - y
    o_ref[i, 0] = t


@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_pair_sum(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 256,
    tile_b: int = 4096,
    interpret: bool = False,
):
    """Sum of g(s1_i - s2_j) over the full pair grid (no masks/ids).

    Requires a diff kernel and len(s1) % tile_a == len(s2) % tile_b == 0
    — callers (JaxBackend) fall back to the XLA path otherwise. Returns
    an f32 scalar; count is len(s1) * len(s2) by construction.
    """
    if kernel.kind != "diff":
        raise ValueError(
            f"pallas pair-sum handles diff kernels only, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    n1, n2 = s1.shape[0], s2.shape[0]
    if n1 % tile_a or n2 % tile_b:
        raise ValueError(
            f"sizes ({n1}, {n2}) must be multiples of tiles "
            f"({tile_a}, {tile_b})"
        )
    g1, g2 = n1 // tile_a, n2 // tile_b
    col = s1.reshape(n1, 1)
    row = s2.reshape(1, n2)
    partials = pl.pallas_call(
        functools.partial(
            _pair_sum_kernel, g=lambda d: kernel.diff(d, jnp)
        ),
        out_shape=jax.ShapeDtypeStruct((g1, 2), jnp.float32),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (g1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=interpret,
    )(col, row)
    # tree-reduce the per-row-block partials, folding in each block's
    # residual: comp = (t - s) - y accumulates the NEGATIVE of the lost
    # low-order bits, so the true block sum is s - comp
    return jnp.sum(partials[:, 0] - partials[:, 1])
