"""Pallas TPU kernel for the score-difference pair-sum hot loop.

The complete-U inner loop for diff kernels is elementwise VPU work over
the [n1, n2] difference grid. This kernel controls the layout explicitly:
the resident score block enters as a COLUMN [Ta, 1] (sublanes) and the
visiting block as a ROW [1, Tb] (lanes), so the broadcasted subtraction
is the natural sublane x lane outer pattern, computed tile-by-tile in
VMEM. Partial sums accumulate per ROW-BLOCK into a [g1, 2] SMEM
(sum, Kahan compensation) cell revisited across the sequential inner
grid (O(n1/Ta) scalars, never the O(n1*n2/(Ta*Tb)) per-cell grid), and
the row partials tree-reduce outside.

The g(d) body comes from the Kernel's own diff_fn (ops.kernels) — no
duplicated surrogate definitions. Two variants share the layout:

* ``pallas_pair_sum`` — unmasked complete statistics (sizes must be tile
  multiples); count is n1*n2 by construction.
* ``pallas_masked_pair_sum`` — mask-aware variant for the ring hot loop
  (parallel.ring): pads any size up to tile multiples with zero-mask
  rows, weights each pair by ma_i*mb_j inside the kernel, and lets the
  caller recover the pair count as sum(ma)*sum(mb). This is what makes
  the DISTRIBUTED estimator run at Pallas throughput instead of the XLA
  scan path [SURVEY §7 step 5 "wall-clock target"].

Id-aware and differentiating callers use ops.pair_tiles (XLA) — these
kernels define no custom VJP. CPU test execution uses interpret mode
[pallas_guide: interpret=True].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tuplewise_tpu.ops.kernels import Kernel


# SMEM budget for the [g1, 2] accumulator: each f32 cell pads to a
# 512-byte SMEM word against a 1 MiB SMEM window budget. Standalone
# calls accepted 1536 row blocks on v5e, but under vmap (the harness
# Monte-Carlo batches every hot loop) Mosaic double-buffers the output
# window and 1221 blocks failed with "allocation (size=1253376) would
# exceed memory (size=1048576)" — r4, northstar n=1e7 local stage. 896
# blocks x 2 cells x 512 B = 917 KiB fits single-buffered with margin;
# the kernels are grid-traversal-bound, so the smaller cap costs
# nothing measurable (n=5e6 complete re-measured at 7.4e11 pairs/s).
MAX_ROW_BLOCKS = 896


# the TUPLEWISE_HARNESS_PALLAS=interpret|off override semantics moved
# to ops.pallas_modes (ONE copy shared with the serving count kernel's
# TUPLEWISE_SERVING_PALLAS twin [ISSUE 10 satellite]); re-exported here
# for the existing harness call sites.
from tuplewise_tpu.ops.pallas_modes import resolve_pallas_mode  # noqa: F401


def preferred_pair_tiles(kernel: Kernel, m1: int, m2: int):
    """Measured-best (tile_a, tile_b) for the masked kernel on v5e.

    Cheap elementwise bodies (auc/hinge) run traversal-bound at wide
    lane tiles (2048x8192 ~= 7e11 pairs/s); transcendental bodies
    (logistic) lose ~40% at 8192 lanes to register pressure — 2048 is
    their sweet spot. Small inputs shrink to keep padding waste low.
    """
    ta = 2048 if m1 >= 2048 else 256
    if kernel.transcendental:
        return ta, 2048
    return ta, 8192 if m2 >= 8192 else 2048


def _pair_sum_kernel(a_ref, b_ref, o_ref, *, g):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[i, 0] = 0.0
        o_ref[i, 1] = 0.0

    # [Ta, 1] - [1, Tb] -> [Ta, Tb] sublane x lane broadcast
    d = a_ref[:, :] - b_ref[:, :]
    x = jnp.sum(g(d))
    # Kahan-compensated add into the (sum, comp) SMEM cell: a row-block
    # accumulator spans tile_a * n2 pairs (~1e10 at n=1e7), where plain
    # f32 += would round away ~tile-sized increments — the same numerics
    # contract as pair_tiles._kahan_add.
    y = x - o_ref[i, 1]
    t = o_ref[i, 0] + y
    o_ref[i, 1] = (t - o_ref[i, 0]) - y
    o_ref[i, 0] = t


@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_pair_sum(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 256,
    tile_b: int = 4096,
    interpret: bool = False,
):
    """Sum of g(s1_i - s2_j) over the full pair grid (no masks/ids).

    Requires a diff kernel and len(s1) % tile_a == len(s2) % tile_b == 0
    — callers (JaxBackend) fall back to the XLA path otherwise. Returns
    an f32 scalar; count is len(s1) * len(s2) by construction.
    """
    if kernel.kind != "diff":
        raise ValueError(
            f"pallas pair-sum handles diff kernels only, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    n1, n2 = s1.shape[0], s2.shape[0]
    if n1 % tile_a or n2 % tile_b:
        raise ValueError(
            f"sizes ({n1}, {n2}) must be multiples of tiles "
            f"({tile_a}, {tile_b})"
        )
    g1, g2 = n1 // tile_a, n2 // tile_b
    if g1 > MAX_ROW_BLOCKS:
        raise ValueError(
            f"n1={n1} with tile_a={tile_a} needs {g1} SMEM accumulator "
            f"cells (> the {MAX_ROW_BLOCKS} budget); raise tile_a or "
            f"use pallas_masked_pair_sum, which auto-grows its tile"
        )
    col = s1.reshape(n1, 1)
    row = s2.reshape(1, n2)
    partials = pl.pallas_call(
        functools.partial(
            _pair_sum_kernel, g=lambda d: kernel.diff(d, jnp)
        ),
        out_shape=jax.ShapeDtypeStruct((g1, 2), jnp.float32),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (g1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=interpret,
    )(col, row)
    # tree-reduce the per-row-block partials, folding in each block's
    # residual: comp = (t - s) - y accumulates the NEGATIVE of the lost
    # low-order bits, so the true block sum is s - comp
    return jnp.sum(partials[:, 0] - partials[:, 1])


def pallas_pair_sum_any(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 0,
    tile_b: int = 0,
    interpret: bool = False,
):
    """Sum of g(s1_i - s2_j) over the full grid at ARBITRARY sizes —
    every row valid (no masks/ids), count = len(s1) * len(s2).
    tile_a/tile_b default (0) to preferred_pair_tiles for the kernel —
    transcendental bodies MUST keep the narrower lane tile (8192-lane
    unmasked tiles spill past VMEM for logistic, see Kernel docstring).

    Interior/edge decomposition [VERDICT r3 next #1]: the largest
    tile-divisible interior runs the UNMASKED kernel, and the two thin
    edge strips (trailing rows x interior cols, all rows x trailing
    cols) take the masked kernel with all-ones masks. At the n=10^7
    headline scale (n_pos = 5e6, 5e6 % 128 = 64) the masked kernel's
    per-tile mask multiply used to tax 100% of the grid for <0.1% of
    padded cells; here it taxes only the strips. The three partials are
    each internally Kahan-compensated f32; their 3-term host-side sum
    adds no meaningful rounding. Value equals pair_stats' sum on the
    same data (tests/test_pallas_and_rank.py parity cases).
    """
    n1, n2 = s1.shape[0], s2.shape[0]
    pa, pb = preferred_pair_tiles(kernel, n1, n2)
    ta, tile_b = tile_a or pa, tile_b or pb
    ta = min(ta, 2048)  # sublane-tile envelope, see _masked_rows
    if kernel.transcendental:
        tile_b = min(tile_b, 2048)  # unmasked VMEM spill guard
    n1i, n2i = (n1 // ta) * ta, (n2 // tile_b) * tile_b

    def masked_rows(a, b, tb):
        """Masked sum over ALL of a x b, row-SEGMENTED so neither the
        SMEM accumulator (896-row-block budget, double-buffered under
        the harness vmap) nor the VMEM scoped limit is exceeded:
        growing tile_a instead measured fine standalone but an
        8192-sublane masked tile OOMs scoped VMEM by 3.6 MB under vmap
        (r4, n=1e7 northstar). Segments keep tile_a <= 2048."""
        ta_m = 2048 if a.shape[0] >= 2048 else 256
        seg = MAX_ROW_BLOCKS * ta_m
        parts = jnp.zeros((), jnp.float32)
        for r0 in range(0, a.shape[0], seg):
            ar = a[r0:min(r0 + seg, a.shape[0])]
            parts = parts + pallas_masked_pair_sum(
                ar, b, jnp.ones(ar.shape[0], a.dtype),
                jnp.ones(b.shape[0], b.dtype),
                kernel=kernel, tile_a=ta_m, tile_b=tb,
                interpret=interpret,
            )
        return parts

    if n1i == 0 or n2i == 0:  # no interior: thin inputs, masked path
        return masked_rows(s1, s2, min(tile_b, 2048))
    # Interior rows run in segments of MAX_ROW_BLOCKS * ta, keeping the
    # measured-best tile_a instead of doubling it to fit the SMEM
    # accumulator budget: at n=5e6, ta=2048 segmented sustains 7.4e11
    # pairs/s on v5e where a single ta=4096 call reaches 6.3e11 — wider
    # sublane tiles lose more to pipeline drain than a second kernel
    # launch costs.
    seg = MAX_ROW_BLOCKS * ta
    total = jnp.zeros((), jnp.float32)
    for r0 in range(0, n1i, seg):
        r1 = min(r0 + seg, n1i)  # multiple of ta: both ends are
        total = total + pallas_pair_sum(
            s1[r0:r1], s2[:n2i], kernel=kernel,
            tile_a=ta, tile_b=tile_b, interpret=interpret,
        )
    if n2 > n2i:  # right strip: ALL rows x trailing cols
        total = total + masked_rows(s1, s2[n2i:], 2048)
    if n1 > n1i:  # bottom strip: trailing rows x interior cols
        total = total + masked_rows(s1[n1i:], s2[:n2i], min(tile_b, 8192))
    return total


def _masked_pair_sum_kernel(a_ref, b_ref, ma_ref, mb_ref, o_ref, *, g):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[i, 0] = 0.0
        o_ref[i, 1] = 0.0

    # [Ta, 1] - [1, Tb] -> [Ta, Tb] sublane x lane broadcast. The b-mask
    # applies inside the lane reduction and the a-mask on the resulting
    # [Ta, 1] column, so only ONE full-tile intermediate (g(d) * mb) is
    # ever live — a second [Ta, Tb] weight grid spills registers past
    # VMEM at lane-wide tiles, and a per-tile fully-valid branch
    # (pl.when) measured SLOWER than the straight multiply (it breaks
    # Mosaic's grid pipelining), so every tile takes the weighted path:
    # ~85% of the unmasked kernel's throughput at n=2^20.
    d = a_ref[:, :] - b_ref[:, :]
    row = jnp.sum(g(d) * mb_ref[:, :], axis=1, keepdims=True)
    x = jnp.sum(row * ma_ref[:, :])
    y = x - o_ref[i, 1]
    t = o_ref[i, 0] + y
    o_ref[i, 1] = (t - o_ref[i, 0]) - y
    o_ref[i, 0] = t




@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_masked_pair_sum(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    m1: jnp.ndarray,
    m2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 256,
    tile_b: int = 2048,
    interpret: bool = False,
):
    """Weighted sum of g(s1_i - s2_j) * m1_i * m2_j over the pair grid.

    Any sizes accepted: inputs are zero-padded to tile multiples, and a
    zero mask makes padded rows/cols weightless, so the value equals the
    XLA pair_stats sum on the unpadded data (same Kahan contract). The
    matching pair count is sum(m1) * sum(m2) — callers compute it with
    two O(n) reductions; it is not returned here.
    """
    if kernel.kind != "diff":
        raise ValueError(
            f"pallas pair-sum handles diff kernels only, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    from tuplewise_tpu.ops.pair_tiles import _pad_axis0

    # The [g1, 2] per-row-block accumulator lives in SMEM (1 MiB, and
    # each f32 cell pads to a 512-byte word there): cap the row-block
    # count by growing tile_a for huge n1 — at n1=5e6 the default 2048
    # tile would need g1=2442 > the 1536-cell budget and Mosaic
    # refuses the allocation. Padding waste stays <= one tile_a.
    while -(-s1.shape[0] // tile_a) > MAX_ROW_BLOCKS:
        tile_a *= 2

    s1, m1 = _pad_axis0(s1, tile_a), _pad_axis0(m1, tile_a)
    s2, m2 = _pad_axis0(s2, tile_b), _pad_axis0(m2, tile_b)
    n1, n2 = s1.shape[0], s2.shape[0]
    g1, g2 = n1 // tile_a, n2 // tile_b
    partials = pl.pallas_call(
        functools.partial(
            _masked_pair_sum_kernel, g=lambda d: kernel.diff(d, jnp)
        ),
        out_shape=jax.ShapeDtypeStruct((g1, 2), jnp.float32),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (g1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=interpret,
    )(
        s1.reshape(n1, 1), s2.reshape(1, n2),
        m1.reshape(n1, 1), m2.reshape(1, n2),
    )
    return jnp.sum(partials[:, 0] - partials[:, 1])


# --------------------------------------------------------------------- #
# Analytic-gradient kernel: row/col g' sums in ONE grid traversal        #
# [VERDICT r3 next #2 — the trainer's backward hot loop]                 #
# --------------------------------------------------------------------- #

def _pair_grad_kernel(a_ref, b_ref, ma_ref, mb_ref, row_ref, col_ref,
                      *, gp, tile_b):
    """row[i] = sum_j g'(a_i - b_j) * mb_j (masked by ma_i),
    col[j] = sum_i g'(a_i - b_j) * ma_i (masked by mb_j), both
    accumulated across the (i, j) grid in one pass:

    * the row block [Ta, 1] rides the standard consecutive-revisit
      accumulation (block i is live for the whole inner j sweep);
    * the col accumulator is the FULL [1, n2p] lane vector with a
      constant index map — resident in VMEM for the entire grid (every
      revisit is consecutive), updated at tile-aligned dynamic lane
      offsets. This is what makes one pass possible: a (1, Tb)@j col
      block would be revisited non-consecutively (j cycles once per i),
      which Pallas does not guarantee to re-fetch.
    """
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init_row():
        row_ref[:, :] = jnp.zeros_like(row_ref)

    @pl.when((i == 0) & (j == 0))
    def _init_col():
        col_ref[:, :] = jnp.zeros_like(col_ref)

    t = gp(a_ref[:, :] - b_ref[:, :]) * mb_ref[:, :]   # [Ta, Tb]
    row_ref[:, :] += jnp.sum(t, axis=1, keepdims=True) * ma_ref[:, :]
    # the a-masked column reduction as an MXU contraction: [1, Ta] @
    # [Ta, Tb] keeps ONE full tile live (a second t * ma intermediate
    # spilled scoped VMEM at >=4096-lane tiles) and uses the otherwise
    # idle MXU for the reduction
    colpart = jnp.dot(ma_ref[:, :].T, t,
                      preferred_element_type=jnp.float32)
    sl = pl.ds(j * tile_b, tile_b)
    col_ref[:, sl] = col_ref[:, sl] + colpart


# the fused kernel's row tile — ONE constant shared with the dispatch
# gate (pair_tiles._use_fused_pallas derives its n1 bound from it)
FUSED_TILE_A = 1024


def _fused_loss_grad_kernel(a_ref, b_ref, ma_ref, mb_ref,
                            loss_ref, row_ref, col_ref, *, g, gp, tile_b):
    """One grid pass computing the masked loss sum (Kahan SMEM cells,
    as in the pair kernel) AND both g' gradient reductions (as in
    _pair_grad_kernel) — a full pairwise-SGD step touches the grid
    ONCE instead of once forward (XLA scan) + once backward."""
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init_row():
        row_ref[:, :] = jnp.zeros_like(row_ref)
        loss_ref[i, 0] = 0.0
        loss_ref[i, 1] = 0.0

    @pl.when((i == 0) & (j == 0))
    def _init_col():
        col_ref[:, :] = jnp.zeros_like(col_ref)

    d = a_ref[:, :] - b_ref[:, :]
    t = gp(d) * mb_ref[:, :]
    row_ref[:, :] += jnp.sum(t, axis=1, keepdims=True) * ma_ref[:, :]
    # MXU contraction, as in _pair_grad_kernel: one live tile
    colpart = jnp.dot(ma_ref[:, :].T, t,
                      preferred_element_type=jnp.float32)
    sl = pl.ds(j * tile_b, tile_b)
    col_ref[:, sl] = col_ref[:, sl] + colpart
    gv = jnp.sum(g(d) * mb_ref[:, :], axis=1, keepdims=True)
    x = jnp.sum(gv * ma_ref[:, :])
    y = x - loss_ref[i, 1]
    t2 = loss_ref[i, 0] + y
    loss_ref[i, 1] = (t2 - loss_ref[i, 0]) - y
    loss_ref[i, 0] = t2


@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_pair_loss_grad(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = FUSED_TILE_A,
    tile_b: int = 2048,
    interpret: bool = False,
):
    """(loss_sum, row, col) over the full pair grid in ONE traversal —
    the trainer's whole hot loop [VERDICT r3 next #2]: loss_sum feeds
    diff_pair_mean's value, row/col are its VJP residuals, so forward
    + backward cost one grid pass total (the r3 design paid an XLA
    forward pass plus a backward pass). Any sizes (zero-mask padding);
    the [1, n2p] col accumulator is VMEM-resident, so the dispatch
    bounds n2 (see pair_tiles._use_fused_pallas)."""
    if kernel.diff_grad_fn is None:
        raise ValueError(f"kernel {kernel.name!r} has no diff_grad_fn")
    n1, n2 = s1.shape[0], s2.shape[0]
    from tuplewise_tpu.ops.pair_tiles import _pad_axis0

    tile_a = min(tile_a, 2048)
    dt = s1.dtype
    ma = _pad_axis0(jnp.ones(n1, dt), tile_a)
    mb = _pad_axis0(jnp.ones(n2, dt), tile_b)
    s1p, s2p = _pad_axis0(s1, tile_a), _pad_axis0(s2, tile_b)
    n1p, n2p = s1p.shape[0], s2p.shape[0]
    g1, g2 = n1p // tile_a, n2p // tile_b
    if g1 > MAX_ROW_BLOCKS:
        raise ValueError(
            f"n1={n1} at tile_a={tile_a} exceeds the {MAX_ROW_BLOCKS} "
            "SMEM loss-cell budget; raise tile_a or use the XLA path"
        )
    loss, row, col = pl.pallas_call(
        functools.partial(
            _fused_loss_grad_kernel,
            g=lambda d: kernel.diff(d, jnp),
            gp=lambda d: kernel.diff_grad_fn(d, jnp),
            tile_b=tile_b,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((g1, 2), jnp.float32),
            jax.ShapeDtypeStruct((n1p, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, n2p), jnp.float32),
        ),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=(
            pl.BlockSpec(
                (g1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, n2p), lambda i, j: (0, 0)),
        ),
        interpret=interpret,
    )(
        s1p.reshape(n1p, 1), s2p.reshape(1, n2p),
        ma.reshape(n1p, 1), mb.reshape(1, n2p),
    )
    return (
        jnp.sum(loss[:, 0] - loss[:, 1]),
        row[:n1, 0],
        col[0, :n2],
    )


@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_pair_grad_sums(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 1024,
    tile_b: int = 2048,
    interpret: bool = False,
):
    """(row, col) g' sums over the full pair grid at arbitrary sizes —
    the Pallas replacement for ops.pair_tiles.pair_grad_sums' XLA scan
    in diff_pair_mean's backward [VERDICT r3 next #2].

    row[i] = sum_j g'(s1_i - s2_j), col[j] = sum_i g'(s1_i - s2_j),
    f32, one traversal of the grid (forward throughput, not
    recompute-plus-transpose). Inputs are zero-padded to tile multiples
    with zero-weight masks, so any sizes are accepted; padded entries
    are sliced off the outputs.

    The col accumulator keeps the padded [1, n2p] lane vector resident
    in VMEM for the whole grid, so n2 is bounded by the VMEM budget —
    callers at estimator scale (n2 >> 10^6) should stay on the XLA
    path; the trainer's n=5e5/class headline is ~2 MB.
    """
    if kernel.diff_grad_fn is None:
        raise ValueError(f"kernel {kernel.name!r} has no diff_grad_fn")
    n1, n2 = s1.shape[0], s2.shape[0]
    from tuplewise_tpu.ops.pair_tiles import _pad_axis0

    # no SMEM row-block budget here (the row output is a per-block VMEM
    # window, not an SMEM cell array), but the sublane tile stays in the
    # <=2048 envelope the masked kernel established under vmap
    tile_a = min(tile_a, 2048)
    dt = s1.dtype
    ma = _pad_axis0(jnp.ones(n1, dt), tile_a)
    mb = _pad_axis0(jnp.ones(n2, dt), tile_b)
    s1p, s2p = _pad_axis0(s1, tile_a), _pad_axis0(s2, tile_b)
    n1p, n2p = s1p.shape[0], s2p.shape[0]
    g1, g2 = n1p // tile_a, n2p // tile_b
    row, col = pl.pallas_call(
        functools.partial(
            _pair_grad_kernel,
            gp=lambda d: kernel.diff_grad_fn(d, jnp),
            tile_b=tile_b,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n1p, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, n2p), jnp.float32),
        ),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=(
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, n2p), lambda i, j: (0, 0)),
        ),
        interpret=interpret,
    )(
        s1p.reshape(n1p, 1), s2p.reshape(1, n2p),
        ma.reshape(n1p, 1), mb.reshape(1, n2p),
    )
    return row[:n1, 0], col[0, :n2]
