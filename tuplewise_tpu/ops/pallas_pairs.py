"""Pallas TPU kernel for the score-difference pair-sum hot loop.

The complete-U inner loop for diff kernels is elementwise VPU work over
the [n1, n2] difference grid. This kernel controls the layout explicitly:
the resident score block enters as a COLUMN [Ta, 1] (sublanes) and the
visiting block as a ROW [1, Tb] (lanes), so the broadcasted subtraction
is the natural sublane x lane outer pattern, computed tile-by-tile in
VMEM. Partial sums accumulate per ROW-BLOCK into a [g1, 2] SMEM
(sum, Kahan compensation) cell revisited across the sequential inner
grid (O(n1/Ta) scalars, never the O(n1*n2/(Ta*Tb)) per-cell grid), and
the row partials tree-reduce outside.

The g(d) body comes from the Kernel's own diff_fn (ops.kernels) — no
duplicated surrogate definitions. Two variants share the layout:

* ``pallas_pair_sum`` — unmasked complete statistics (sizes must be tile
  multiples); count is n1*n2 by construction.
* ``pallas_masked_pair_sum`` — mask-aware variant for the ring hot loop
  (parallel.ring): pads any size up to tile multiples with zero-mask
  rows, weights each pair by ma_i*mb_j inside the kernel, and lets the
  caller recover the pair count as sum(ma)*sum(mb). This is what makes
  the DISTRIBUTED estimator run at Pallas throughput instead of the XLA
  scan path [SURVEY §7 step 5 "wall-clock target"].

Id-aware and differentiating callers use ops.pair_tiles (XLA) — these
kernels define no custom VJP. CPU test execution uses interpret mode
[pallas_guide: interpret=True].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tuplewise_tpu.ops.kernels import Kernel


# SMEM budget for the [g1, 2] accumulator: each f32 cell pads to a
# 512-byte SMEM word, so 1 MiB holds 2048 cells = 1024 row blocks of
# 2 cells each; 1536 row blocks (3072 cells) was measured as the
# largest allocation Mosaic accepts on v5e (some SMEM is reserved by
# the runtime), kept as the hard cap with the safety margin already in
# the measurement.
MAX_ROW_BLOCKS = 1536


def resolve_pallas_mode(platform: str):
    """(use_pallas, interpret) for a harness hot loop executing on
    ``platform``, honoring TUPLEWISE_HARNESS_PALLAS=interpret|off —
    the single copy of the override semantics shared by
    harness.variance and harness.mesh_mc."""
    import os

    mode = os.environ.get("TUPLEWISE_HARNESS_PALLAS", "auto")
    interpret = mode == "interpret"
    return interpret or (mode != "off" and platform == "tpu"), interpret


def preferred_pair_tiles(kernel: Kernel, m1: int, m2: int):
    """Measured-best (tile_a, tile_b) for the masked kernel on v5e.

    Cheap elementwise bodies (auc/hinge) run traversal-bound at wide
    lane tiles (2048x8192 ~= 7e11 pairs/s); transcendental bodies
    (logistic) lose ~40% at 8192 lanes to register pressure — 2048 is
    their sweet spot. Small inputs shrink to keep padding waste low.
    """
    ta = 2048 if m1 >= 2048 else 256
    if kernel.transcendental:
        return ta, 2048
    return ta, 8192 if m2 >= 8192 else 2048


def _pair_sum_kernel(a_ref, b_ref, o_ref, *, g):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[i, 0] = 0.0
        o_ref[i, 1] = 0.0

    # [Ta, 1] - [1, Tb] -> [Ta, Tb] sublane x lane broadcast
    d = a_ref[:, :] - b_ref[:, :]
    x = jnp.sum(g(d))
    # Kahan-compensated add into the (sum, comp) SMEM cell: a row-block
    # accumulator spans tile_a * n2 pairs (~1e10 at n=1e7), where plain
    # f32 += would round away ~tile-sized increments — the same numerics
    # contract as pair_tiles._kahan_add.
    y = x - o_ref[i, 1]
    t = o_ref[i, 0] + y
    o_ref[i, 1] = (t - o_ref[i, 0]) - y
    o_ref[i, 0] = t


@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_pair_sum(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 256,
    tile_b: int = 4096,
    interpret: bool = False,
):
    """Sum of g(s1_i - s2_j) over the full pair grid (no masks/ids).

    Requires a diff kernel and len(s1) % tile_a == len(s2) % tile_b == 0
    — callers (JaxBackend) fall back to the XLA path otherwise. Returns
    an f32 scalar; count is len(s1) * len(s2) by construction.
    """
    if kernel.kind != "diff":
        raise ValueError(
            f"pallas pair-sum handles diff kernels only, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    n1, n2 = s1.shape[0], s2.shape[0]
    if n1 % tile_a or n2 % tile_b:
        raise ValueError(
            f"sizes ({n1}, {n2}) must be multiples of tiles "
            f"({tile_a}, {tile_b})"
        )
    g1, g2 = n1 // tile_a, n2 // tile_b
    if g1 > MAX_ROW_BLOCKS:
        raise ValueError(
            f"n1={n1} with tile_a={tile_a} needs {g1} SMEM accumulator "
            f"cells (> the {MAX_ROW_BLOCKS} budget); raise tile_a or "
            f"use pallas_masked_pair_sum, which auto-grows its tile"
        )
    col = s1.reshape(n1, 1)
    row = s2.reshape(1, n2)
    partials = pl.pallas_call(
        functools.partial(
            _pair_sum_kernel, g=lambda d: kernel.diff(d, jnp)
        ),
        out_shape=jax.ShapeDtypeStruct((g1, 2), jnp.float32),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (g1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=interpret,
    )(col, row)
    # tree-reduce the per-row-block partials, folding in each block's
    # residual: comp = (t - s) - y accumulates the NEGATIVE of the lost
    # low-order bits, so the true block sum is s - comp
    return jnp.sum(partials[:, 0] - partials[:, 1])


def _masked_pair_sum_kernel(a_ref, b_ref, ma_ref, mb_ref, o_ref, *, g):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[i, 0] = 0.0
        o_ref[i, 1] = 0.0

    # [Ta, 1] - [1, Tb] -> [Ta, Tb] sublane x lane broadcast. The b-mask
    # applies inside the lane reduction and the a-mask on the resulting
    # [Ta, 1] column, so only ONE full-tile intermediate (g(d) * mb) is
    # ever live — a second [Ta, Tb] weight grid spills registers past
    # VMEM at lane-wide tiles, and a per-tile fully-valid branch
    # (pl.when) measured SLOWER than the straight multiply (it breaks
    # Mosaic's grid pipelining), so every tile takes the weighted path:
    # ~85% of the unmasked kernel's throughput at n=2^20.
    d = a_ref[:, :] - b_ref[:, :]
    row = jnp.sum(g(d) * mb_ref[:, :], axis=1, keepdims=True)
    x = jnp.sum(row * ma_ref[:, :])
    y = x - o_ref[i, 1]
    t = o_ref[i, 0] + y
    o_ref[i, 1] = (t - o_ref[i, 0]) - y
    o_ref[i, 0] = t




@functools.partial(
    jax.jit, static_argnames=("kernel", "tile_a", "tile_b", "interpret")
)
def pallas_masked_pair_sum(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    m1: jnp.ndarray,
    m2: jnp.ndarray,
    *,
    kernel: Kernel,
    tile_a: int = 256,
    tile_b: int = 2048,
    interpret: bool = False,
):
    """Weighted sum of g(s1_i - s2_j) * m1_i * m2_j over the pair grid.

    Any sizes accepted: inputs are zero-padded to tile multiples, and a
    zero mask makes padded rows/cols weightless, so the value equals the
    XLA pair_stats sum on the unpadded data (same Kahan contract). The
    matching pair count is sum(m1) * sum(m2) — callers compute it with
    two O(n) reductions; it is not returned here.
    """
    if kernel.kind != "diff":
        raise ValueError(
            f"pallas pair-sum handles diff kernels only, got "
            f"{kernel.name!r} (kind={kernel.kind})"
        )
    from tuplewise_tpu.ops.pair_tiles import _pad_axis0

    # The [g1, 2] per-row-block accumulator lives in SMEM (1 MiB, and
    # each f32 cell pads to a 512-byte word there): cap the row-block
    # count by growing tile_a for huge n1 — at n1=5e6 the default 2048
    # tile would need g1=2442 > the 1536-cell budget and Mosaic
    # refuses the allocation. Padding waste stays <= one tile_a.
    while -(-s1.shape[0] // tile_a) > MAX_ROW_BLOCKS:
        tile_a *= 2

    s1, m1 = _pad_axis0(s1, tile_a), _pad_axis0(m1, tile_a)
    s2, m2 = _pad_axis0(s2, tile_b), _pad_axis0(m2, tile_b)
    n1, n2 = s1.shape[0], s2.shape[0]
    g1, g2 = n1 // tile_a, n2 // tile_b
    partials = pl.pallas_call(
        functools.partial(
            _masked_pair_sum_kernel, g=lambda d: kernel.diff(d, jnp)
        ),
        out_shape=jax.ShapeDtypeStruct((g1, 2), jnp.float32),
        grid=(g1, g2),
        in_specs=[
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tile_b), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (g1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=interpret,
    )(
        s1.reshape(n1, 1), s2.reshape(1, n2),
        m1.reshape(n1, 1), m2.reshape(1, n2),
    )
    return jnp.sum(partials[:, 0] - partials[:, 1])
