"""L1 — tuplewise kernels h.

The tuplewise functions at the heart of every U-statistic [SURVEY §1.1, §3].
Each kernel is a small pure function written against an array namespace
``xp`` (``numpy`` or ``jax.numpy``), so the exact same definition powers the
NumPy oracle backend and the JAX/TPU backends — this is the
"kernel-callable plugin" boundary named by the north star (BASELINE.json:5).

Kernel families
---------------
* **Score-difference kernels** (``kind="diff"``): two-sample degree-(1,1)
  kernels of the form ``h(x, y) = g(s(x) - s(y))`` on scalar scores —
  the AUC indicator and its hinge / logistic surrogates [SURVEY §1.1, §1.3].
  Everything downstream only ever needs ``g`` applied to a *difference
  matrix*, which is what lets the TPU path tile the pair computation
  instead of materializing it.
* **Pair feature kernels** (``kind="pair"``): general degree-2 kernels
  ``h(x_i, x_j)`` on feature vectors (e.g. within-cluster point scatter,
  the paper's one-sample example) [SURVEY §1.1].
* **Triplet kernels** (``kind="triplet"``): degree-3 metric-learning
  relative-similarity kernels ``h(anchor, positive, negative)``
  [SURVEY §1.1 "Degree-3", BASELINE config 4]. We frame them as
  degree-(2,1) two-sample statistics: (i, j) drawn without replacement
  from the same-class sample X, k from the other-class sample Y,
  ``h = penalty( d(x_i, y_k) - d(x_i, x_j) )``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

Array = Any  # numpy or jax.numpy ndarray


def _softplus(xp, v):
    """Numerically stable log(1 + exp(v))."""
    return xp.logaddexp(0.0, v)


@dataclasses.dataclass(frozen=True)
class Kernel:
    """A tuplewise kernel h, the plugin unit of the framework.

    Attributes:
      name: registry name.
      degree: number of sample points h consumes (2 or 3).
      two_sample: True for two-sample statistics (X vs Y, e.g. AUC);
        False for one-sample (pairs within a single sample).
      kind: "diff" (scalar-score difference kernels), "pair" (feature
        pair kernels), or "triplet" (degree-3 feature kernels).
      diff_fn: for kind="diff": ``g(d, xp)`` applied elementwise to a
        score-difference array ``d = s_i - s_j``.
      diff_grad_fn: optional analytic ``g'(d, xp)`` for diff kernels.
        When present, the learner's all-pairs gradient streams row/col
        reductions of g' in a single forward-style pass
        (ops.pair_tiles.diff_pair_mean) instead of autodiffing through
        the checkpointed tile scan — ~2 traversals of the grid total
        rather than recompute-plus-transpose per tile.
      pair_fn: for kind="pair": ``h(a, b, xp)`` mapping feature blocks
        ``a [m, d]``, ``b [k, d]`` to an ``[m, k]`` kernel matrix.
      triplet_fn: for kind="triplet": ``h(a, p, n, xp)`` mapping anchor /
        positive / negative feature blocks (broadcast-compatible leading
        axes) to kernel values.
      pair_elem_fn: for kind="pair": elementwise ``h(a_b, b_b, xp)`` on
        matched rows (the incomplete-sampling fast path).
      higher_is_better: metric orientation (True for AUC, False for losses).
      transcendental: the diff body uses exp/log-class ops. Pallas tile
        pickers shrink the lane tile for these — wide tiles inflate the
        transcendental chain's register live ranges (logistic at
        2048x8192 measured 40% slower than 2048x2048 on v5e, and the
        unmasked kernel variant spills past VMEM outright).
    """

    name: str
    degree: int
    two_sample: bool
    kind: str
    diff_fn: Optional[Callable[..., Array]] = None
    diff_grad_fn: Optional[Callable[..., Array]] = None
    pair_fn: Optional[Callable[..., Array]] = None
    triplet_fn: Optional[Callable[..., Array]] = None
    pair_elem_fn: Optional[Callable[..., Array]] = None
    higher_is_better: bool = True
    transcendental: bool = False

    # ---- evaluation helpers -------------------------------------------------
    def diff(self, d: Array, xp) -> Array:
        assert self.kind == "diff", self.name
        return self.diff_fn(d, xp)

    def pair_matrix(self, a: Array, b: Array, xp) -> Array:
        """Kernel matrix between blocks: [m, k]."""
        if self.kind == "diff":
            # a, b are 1-d score blocks.
            return self.diff_fn(a[:, None] - b[None, :], xp)
        assert self.kind == "pair", self.name
        return self.pair_fn(a, b, xp)

    def triplet_values(self, a: Array, p: Array, n: Array, xp) -> Array:
        assert self.kind == "triplet", self.name
        return self.triplet_fn(a, p, n, xp)

    def pair_elementwise(self, a: Array, b: Array, xp) -> Array:
        """h on matched tuples: a[t] paired with b[t] (incomplete sampling)."""
        if self.kind == "diff":
            return self.diff_fn(a - b, xp)
        assert self.kind == "pair" and self.pair_elem_fn is not None, self.name
        return self.pair_elem_fn(a, b, xp)


# ---------------------------------------------------------------------------
# Score-difference kernels (degree 2)
# ---------------------------------------------------------------------------

def _auc_g(d, xp):
    # h(x, y) = 1{s(x) > s(y)} + 0.5 * 1{s(x) = s(y)}   [SURVEY §1.1]
    return xp.where(d > 0, 1.0, 0.0) + 0.5 * xp.where(d == 0, 1.0, 0.0)


def _hinge_g(d, xp):
    # Pairwise hinge surrogate l(d) = max(0, 1 - d)      [SURVEY §1.3]
    return xp.maximum(0.0, 1.0 - d)


def _hinge_gp(d, xp):
    # dl/dd = -1{d < 1} (subgradient 0 at the kink)
    return xp.where(d < 1.0, -1.0, 0.0)


def _logistic_g(d, xp):
    # Pairwise logistic surrogate l(d) = log(1 + e^{-d}) [SURVEY §1.3]
    return _softplus(xp, -d)


def _logistic_gp(d, xp):
    # dl/dd = -sigmoid(-d) = -1 / (1 + e^{d})
    return -1.0 / (1.0 + xp.exp(d))


auc_kernel = Kernel(
    name="auc", degree=2, two_sample=True, kind="diff",
    diff_fn=_auc_g, higher_is_better=True,
)

hinge_kernel = Kernel(
    name="hinge", degree=2, two_sample=True, kind="diff",
    diff_fn=_hinge_g, diff_grad_fn=_hinge_gp, higher_is_better=False,
)

logistic_kernel = Kernel(
    name="logistic", degree=2, two_sample=True, kind="diff",
    diff_fn=_logistic_g, diff_grad_fn=_logistic_gp,
    higher_is_better=False, transcendental=True,
)


# ---------------------------------------------------------------------------
# Feature pair kernels (degree 2, one-sample)
# ---------------------------------------------------------------------------

def _sqdist_matrix(a, b, xp):
    """Squared euclidean distances between rows of a [m,d] and b [k,d]."""
    a2 = xp.sum(a * a, axis=-1)
    b2 = xp.sum(b * b, axis=-1)
    cross = a @ b.T
    d2 = a2[:, None] + b2[None, :] - 2.0 * cross
    return xp.maximum(d2, 0.0)


def _scatter_h(a, b, xp):
    # Within-cluster point scatter h(x, x') = ||x - x'||^2 / 2
    # (the paper's one-sample degree-2 example) [SURVEY §1.1].
    return 0.5 * _sqdist_matrix(a, b, xp)


def _scatter_h_elem(a, b, xp):
    diff = a - b
    return 0.5 * xp.sum(diff * diff, axis=-1)


scatter_kernel = Kernel(
    name="scatter", degree=2, two_sample=False, kind="pair",
    pair_fn=_scatter_h, pair_elem_fn=_scatter_h_elem, higher_is_better=False,
)


# ---------------------------------------------------------------------------
# Triplet kernels (degree 3) — metric-learning relative similarity
# ---------------------------------------------------------------------------

def _sqdist_vec(a, b, xp):
    diff = a - b
    return xp.sum(diff * diff, axis=-1)


def _triplet_indicator(a, p, n, xp, margin=0.0):
    # 1{ d(anchor, negative) > d(anchor, positive) + margin }
    return xp.where(
        _sqdist_vec(a, n, xp) > _sqdist_vec(a, p, xp) + margin, 1.0, 0.0
    )


def _triplet_hinge(a, p, n, xp, margin=1.0):
    # max(0, margin + d(anchor, positive) - d(anchor, negative))
    return xp.maximum(
        0.0, margin + _sqdist_vec(a, p, xp) - _sqdist_vec(a, n, xp)
    )


triplet_indicator_kernel = Kernel(
    name="triplet_indicator", degree=3, two_sample=True, kind="triplet",
    triplet_fn=_triplet_indicator, higher_is_better=True,
)

triplet_hinge_kernel = Kernel(
    name="triplet_hinge", degree=3, two_sample=True, kind="triplet",
    triplet_fn=_triplet_hinge, higher_is_better=False,
)


_REGISTRY = {
    k.name: k
    for k in [
        auc_kernel,
        hinge_kernel,
        logistic_kernel,
        scatter_kernel,
        triplet_indicator_kernel,
        triplet_hinge_kernel,
    ]
}


def get_kernel(name_or_kernel) -> Kernel:
    """Resolve a kernel by registry name, passing Kernel instances through."""
    if isinstance(name_or_kernel, Kernel):
        return name_or_kernel
    try:
        return _REGISTRY[name_or_kernel]
    except KeyError:
        raise KeyError(
            f"unknown kernel {name_or_kernel!r}; "
            f"available: {sorted(_REGISTRY)}"
        ) from None


def builtin_triplet_spec(kernel: Kernel):
    """("indicator" | "hinge", margin) when ``kernel`` IS one of the
    two built-in sqdist triplet kernels (triplet_fn identity, not name
    — a shadowing custom kernel must never match), else None. The
    margin comes off the function's own default, so the Python
    definition stays the single source of truth. Shared by every
    accelerated degree-3 path (native C++ engine, Pallas distance
    factorization) so the builtin table exists exactly once."""
    import inspect

    table = {
        triplet_indicator_kernel.triplet_fn: "indicator",
        triplet_hinge_kernel.triplet_fn: "hinge",
    }
    kind = table.get(kernel.triplet_fn)
    if kind is None:
        return None
    margin = inspect.signature(
        kernel.triplet_fn
    ).parameters["margin"].default
    return kind, float(margin)


def register_kernel(kernel: Kernel) -> Kernel:
    """Register a user-defined kernel (the plugin entry point)."""
    _REGISTRY[kernel.name] = kernel
    return kernel
