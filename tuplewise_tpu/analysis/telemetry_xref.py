"""Pass 3 — telemetry cross-reference [ISSUE 12].

The doctor, the SLO engine, the report builder and the perf gate
consume metric names, flight-event kinds and bench-row fields by
STRING MATCH — a typo'd producer ships silently and the consumer just
sees nothing. This pass closes the namespace:

* **producers** — every ``registry.counter/gauge/histogram("name")``
  (f-strings become glob patterns), every ``flight.record("kind")`` /
  ``_flight_event("kind")``, every span name
  (``tracer.start`` / ``maybe_span`` / ``record_span``), and every
  string dict key written into bench/replay result rows.
* **consumers** — string literals in the consumer modules
  (obs/doctor, obs/slo, obs/report, scripts/perf_gate, serving/control)
  appearing in *consuming positions*: the accessor helpers
  (``_v`` / ``_sum_v`` / ``_metric_value`` / ``_g`` / ``_p_ms``),
  ``m.get("...")`` / ``metrics.get("...")``, ``"..." in metrics``,
  declared consumer sequences (``_RECOVERY_COUNTERS``), SLO spec
  literals (``"metric"`` / ``"errors"`` / ``"total"`` values),
  flight-kind positions (``by_kind.get`` / ``_after`` /
  ``e["kind"] == "..."``), and the perf-gate stage table's dotted
  value paths.
* **docs** — backticked telemetry-shaped tokens in README/DESIGN
  (suffixes ``_total`` / ``_s`` / ``_live``, or ``name{label=...}``
  forms) must name a real producer.

Rules: ``telemetry-consumed-unproduced`` (code consumer with no
producer), ``doc-telemetry-unknown`` (documented name with no
producer), ``telemetry-type-conflict`` (one name registered as two
different metric types), ``metric-direct-construction`` (a
Counter/Gauge/Histogram built outside the registry's create-or-return
helpers — the duplicate-registration race the registry exists to
prevent [ISSUE 12 satellite]).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted, glob_match, literal_str,
    name_or_glob,
)

_METRIC_METHODS = {"counter": "counter", "gauge": "gauge",
                   "histogram": "histogram"}
# accessor -> index of the metric-name argument
_METRIC_ACCESSORS = {"_v": 1, "_sum_v": 1, "_metric_value": 1,
                     "_g": 0, "_p_ms": 1}
_FLIGHT_ACCESSORS = {"_after": 0}
_GET_RECEIVERS = {"m", "metrics"}
_KIND_RECEIVERS = {"by_kind", "kinds"}
_SPEC_KEYS = {"metric", "total"}
_SPEC_LIST_KEYS = {"errors"}
# spec-literal extraction only applies where dict literals ARE specs;
# the controller builds signal payloads whose "metric" values are
# derived names (tenant_insert_rate), not registry reads
_SPEC_LITERAL_FILES = ("tuplewise_tpu/obs/slo.py",
                       "tuplewise_tpu/obs/doctor.py")
_CONSUMER_SEQUENCES = {"_RECOVERY_COUNTERS"}

_DEFAULT_CONSUMERS = (
    "tuplewise_tpu/obs/doctor.py",
    "tuplewise_tpu/obs/slo.py",
    "tuplewise_tpu/obs/report.py",
    "tuplewise_tpu/serving/control.py",
    "scripts/perf_gate.py",
)

_DOC_SUFFIXES = ("_total", "_s", "_live")
_NAME_RE = re.compile(r"^[a-z][a-z0-9_.]*$")
_BACKTICK_RE = re.compile(r"`([^`\n]+)`")


def _strip_labels(name: str) -> str:
    i = name.find("{")
    return name[:i] if i >= 0 else name


def collect_producers(ms: ModuleSet
                      ) -> Tuple[Dict[str, Set[str]], Set[str],
                                 Set[str], Set[str]]:
    """(metric name -> {types}, flight kinds, span names, row keys).
    Names from f-strings land as glob patterns (contain ``*``)."""
    metrics: Dict[str, Set[str]] = {}
    flights: Set[str] = set()
    spans: Set[str] = set()
    row_keys: Set[str] = set()
    for path, mi in ms.modules.items():
        is_fixture = path.startswith("tests/")
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    s = literal_str(k) if k is not None else None
                    if s is not None:
                        row_keys.add(s)
            # out["kernel_calls_per_batch"] = ... — subscript writes
            # produce row fields just like dict literals do; augmented
            # writes (out["n"] += 1) and .setdefault("k", ...) too
            # [ISSUE 13 satellite: PR 12 triage precision fix]
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        s = literal_str(t.slice)
                        if s is not None:
                            row_keys.add(s)
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Subscript):
                s = literal_str(node.target.slice)
                if s is not None:
                    row_keys.add(s)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "setdefault" and node.args:
                s = literal_str(node.args[0])
                if s is not None:
                    row_keys.add(s)
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn is None or is_fixture:
                continue
            leaf = cn.split(".")[-1]
            if leaf in _METRIC_METHODS and node.args:
                name = name_or_glob(node.args[0])
                if name is not None:
                    metrics.setdefault(name, set()).add(
                        _METRIC_METHODS[leaf])
            elif leaf == "record" and node.args \
                    and not cn.endswith("record_span"):
                k = name_or_glob(node.args[0])
                if k is not None:
                    flights.add(k)
            elif leaf == "_flight_event" and node.args:
                k = name_or_glob(node.args[0])
                if k is not None:
                    flights.add(k)
            elif leaf in ("record_span", "start", "maybe_span"):
                # tracer.start("name") / maybe_span(tracer, "name")
                idx = 1 if leaf == "maybe_span" else 0
                if len(node.args) > idx:
                    s = name_or_glob(node.args[idx])
                    if s is not None:
                        spans.add(s)
    return metrics, flights, spans, row_keys


def collect_consumers(ms: ModuleSet, consumer_paths
                      ) -> Tuple[List[Tuple[str, int, str]],
                                 List[Tuple[str, int, str]],
                                 List[Tuple[str, int, str]]]:
    """(metric consumers, flight-kind consumers, row-field consumers)
    as (path, line, name) triples."""
    m_cons: List[Tuple[str, int, str]] = []
    f_cons: List[Tuple[str, int, str]] = []
    r_cons: List[Tuple[str, int, str]] = []
    for path in consumer_paths:
        mi = ms.modules.get(path)
        if mi is None:
            continue
        is_gate = path.endswith("perf_gate.py")
        for node in ast.walk(mi.tree):
            # accessor calls
            if isinstance(node, ast.Call):
                cn = call_name(node)
                leaf = cn.split(".")[-1] if cn else ""
                recv = cn.rsplit(".", 1)[0] if cn and "." in cn else ""
                if cn in _METRIC_ACCESSORS:
                    idx = _METRIC_ACCESSORS[cn]
                    if idx < len(node.args):
                        s = literal_str(node.args[idx])
                        if s is not None:
                            m_cons.append((path, node.lineno,
                                           _strip_labels(s)))
                elif leaf == "get" and recv in _GET_RECEIVERS \
                        and node.args:
                    s = literal_str(node.args[0])
                    if s is not None:
                        m_cons.append((path, node.lineno,
                                       _strip_labels(s)))
                elif leaf == "get" and recv in _KIND_RECEIVERS \
                        and node.args:
                    s = literal_str(node.args[0])
                    if s is not None:
                        f_cons.append((path, node.lineno, s))
                elif cn in _FLIGHT_ACCESSORS and node.args:
                    s = literal_str(node.args[_FLIGHT_ACCESSORS[cn]])
                    if s is not None:
                        f_cons.append((path, node.lineno, s))
            # "name" in metrics
            elif isinstance(node, ast.Compare) and node.ops:
                if isinstance(node.ops[0], ast.In) \
                        and isinstance(node.comparators[0], ast.Name) \
                        and node.comparators[0].id in _GET_RECEIVERS:
                    s = literal_str(node.left)
                    if s is not None:
                        m_cons.append((path, node.lineno,
                                       _strip_labels(s)))
                # e["kind"] == "batcher_restart" / base == "..."
                elif isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                    lhs, rhs = node.left, node.comparators[0]
                    sub = lhs if isinstance(lhs, ast.Subscript) else (
                        rhs if isinstance(rhs, ast.Subscript) else None)
                    lit = literal_str(rhs) or literal_str(lhs)
                    if sub is not None and lit is not None:
                        key = literal_str(sub.slice)
                        if key == "kind":
                            f_cons.append((path, node.lineno, lit))
                        elif key == "name":
                            pass    # span-name comparisons: info only
                    elif lit is not None and isinstance(lhs, ast.Name) \
                            and lhs.id == "base":
                        m_cons.append((path, node.lineno,
                                       _strip_labels(lit)))
            # declared consumer sequences (tuple-of-strings constants)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) \
                        and t.id in _CONSUMER_SEQUENCES \
                        and isinstance(node.value, (ast.Tuple,
                                                    ast.List)):
                    for el in node.value.elts:
                        s = literal_str(el)
                        if s is not None:
                            m_cons.append((path, node.lineno, s))
            # SLO spec literals: {"metric": "x", "errors": [...]}
            elif isinstance(node, ast.Dict) \
                    and path in _SPEC_LITERAL_FILES:
                for k, v in zip(node.keys, node.values):
                    ks = literal_str(k) if k is not None else None
                    if ks in _SPEC_KEYS:
                        s = literal_str(v)
                        if s is not None:
                            m_cons.append((path, v.lineno,
                                           _strip_labels(s)))
                    elif ks in _SPEC_LIST_KEYS and isinstance(
                            v, (ast.Tuple, ast.List)):
                        for el in v.elts:
                            s = literal_str(el)
                            if s is not None:
                                m_cons.append((path, el.lineno,
                                               _strip_labels(s)))
        # perf gate: _STAGE_METRICS dotted value paths + stage names
        if is_gate:
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == "_STAGE_METRICS" \
                        and isinstance(node.value, ast.Dict):
                    for k, v in zip(node.value.keys, node.value.values):
                        s = literal_str(k)
                        if s is not None:
                            r_cons.append((path, k.lineno,
                                           f"stage:{s}"))
                        for el in ast.walk(v):
                            if isinstance(el, ast.Tuple) \
                                    and len(el.elts) == 3:
                                fld = literal_str(el.elts[2])
                                if fld is not None:
                                    last = fld.split(".")[-1]
                                    if not last.isdigit():
                                        r_cons.append(
                                            (path, el.lineno, last))
    return m_cons, f_cons, r_cons


def doc_tokens(ms: ModuleSet) -> List[Tuple[str, str]]:
    """Backticked telemetry-shaped tokens in the doc files."""
    out = []
    for path, text in ms.texts.items():
        for tok in _BACKTICK_RE.findall(text):
            base = _strip_labels(tok.strip())
            if not _NAME_RE.match(base):
                continue
            if "{" in tok and "=" in tok:
                out.append((path, base))
            elif base.endswith(_DOC_SUFFIXES) and "_" in base \
                    and "." not in base:
                out.append((path, base))
    return out


def _produced(name: str, metrics: Dict[str, Set[str]]) -> bool:
    if name in metrics:
        return True
    pats = [p for p in metrics if "*" in p]
    return glob_match(name, pats)


def run(ms: ModuleSet, consumer_paths=_DEFAULT_CONSUMERS
        ) -> List[Finding]:
    metrics, flights, spans, row_keys = collect_producers(ms)
    m_cons, f_cons, r_cons = collect_consumers(ms, consumer_paths)
    findings: List[Finding] = []

    for path, line, name in m_cons:
        if not _produced(name, metrics):
            findings.append(Finding(
                "telemetry-consumed-unproduced", path, line, name,
                f"metric {name!r} is consumed here but no code "
                "registers it (typo or dead consumer — doctor/SLO "
                "would silently see nothing)"))
    for path, line, kind in f_cons:
        if kind not in flights and not glob_match(
                kind, [p for p in flights if "*" in p]):
            findings.append(Finding(
                "telemetry-consumed-unproduced", path, line,
                f"flight:{kind}",
                f"flight-event kind {kind!r} is consumed here but "
                "never recorded by any producer"))
    for path, line, field in r_cons:
        if field.startswith("stage:"):
            stage = field[len("stage:"):]
            if stage not in row_keys and not any(
                    stage == v for v in _stage_values(ms)):
                findings.append(Finding(
                    "telemetry-consumed-unproduced", path, line,
                    field,
                    f"perf-gate stage {stage!r} never appears as a "
                    "result-row stage value"))
        elif field not in row_keys:
            findings.append(Finding(
                "telemetry-consumed-unproduced", path, line, field,
                f"perf-gate row field {field!r} never appears as a "
                "result-row key in any producer — the gate check "
                "passes vacuously"))

    known = set(flights) | row_keys | _config_fields(ms) \
        | _param_names(ms) | _attr_names(ms)
    for path, base in doc_tokens(ms):
        if not _produced(base, metrics) and base not in known:
            findings.append(Finding(
                "doc-telemetry-unknown", path, 0, base,
                f"{path} documents telemetry name {base!r} but no "
                "code produces it (not a metric, flight kind, result-"
                "row key, or parameter either)"))

    # type conflicts: one name, two metric types
    for name, types in sorted(metrics.items()):
        if len(types) > 1:
            findings.append(Finding(
                "telemetry-type-conflict", "<registry>", 0, name,
                f"metric {name!r} registered as multiple types "
                f"({'/'.join(sorted(types))}) — the registry raises "
                "at runtime on whichever call site loses the race"))

    # direct construction outside the registry [ISSUE 12 satellite]
    for path, mi in ms.modules.items():
        if path.endswith("utils/profiling.py") \
                or path.startswith("tests/"):
            continue
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn in ("Counter", "Gauge", "Histogram") \
                        and node.args \
                        and literal_str(node.args[0]) is not None:
                    # the import TABLE decides (the profiling module
                    # need not be in the analyzed corpus — fixtures)
                    target = mi.imports.get(cn, "")
                    if target.startswith(
                            "tuplewise_tpu.utils.profiling:"):
                        findings.append(Finding(
                            "metric-direct-construction", path,
                            node.lineno,
                            f"{cn}:{literal_str(node.args[0])}",
                            f"{cn}({literal_str(node.args[0])!r}) "
                            "constructed directly — metrics must come "
                            "from the registry's create-or-return "
                            "helpers so concurrent registration can't "
                            "produce twin series"))
    return findings


def _stage_values(ms: ModuleSet) -> Set[str]:
    """Every literal value assigned to a "stage" dict key anywhere —
    the stage names result rows are tagged with."""
    out: Set[str] = set()
    for path, mi in ms.modules.items():
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if k is not None and literal_str(k) == "stage":
                        s = literal_str(v)
                        if s is not None:
                            out.add(s)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "stage":
                        s = literal_str(kw.value)
                        if s is not None:
                            out.add(s)
    return out


def _param_names(ms: ModuleSet) -> Set[str]:
    """Function parameter and property names across the corpus — docs
    legitimately backtick those (``timeout_s``, ``retries_total``)."""
    out: Set[str] = set()
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            node = fi.node
            args = getattr(node, "args", None)
            if args is None:
                continue
            for a in (args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                out.add(a.arg)
            out.add(getattr(node, "name", ""))
    return out


def _attr_names(ms: ModuleSet) -> Set[str]:
    """Instance-attribute names assigned anywhere (``self.x = ...``):
    docs legitimately backtick object state (``n_evicted``,
    ``retry_backoff_s``) that is neither a metric nor a config field
    [ISSUE 13 satellite: PR 12 triage precision fix]."""
    out: Set[str] = set()
    for path, mi in ms.modules.items():
        for node in ast.walk(mi.tree):
            targets = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = (node.target,)
            for t in targets:
                d = dotted(t)
                if d and d.startswith("self.") \
                        and "." not in d[len("self."):]:
                    out.add(d[len("self."):])
    return out


def _config_fields(ms: ModuleSet) -> Set[str]:
    """Dataclass field names across the corpus — doc tokens ending in
    ``_s`` are often config knobs, not metrics; exclude them."""
    from tuplewise_tpu.analysis.config_drift import dataclass_fields

    out: Set[str] = set()
    for fields in dataclass_fields(ms).values():
        out.update(f for f, _ in fields)
    out.update({"retry_after_s", "window_s", "ts_mono", "t_wall",
                "dur_s", "t0_s", "self_s", "total_s", "build_s",
                "waited_s", "t_mono", "duration_s"})
    return out
