"""Pass 2 — traced-code purity [ISSUE 12].

The integer-exactness and determinism contracts DESIGN §15 states in
prose, enforced: inside any function reached by ``jax.jit`` /
``pl.pallas_call`` / ``jax.shard_map`` (decorator, wrapper call, or
kernel argument), and everything those functions call within the
corpus, forbid:

* wall-clock reads (``time.time`` / ``perf_counter`` / ``monotonic``,
  ``datetime.now``) — a traced timestamp is a constant baked at trace
  time, silently wrong forever after (rule ``traced-wall-clock``);
* unseeded host RNG (``np.random.*``, ``random.*``) — traced once,
  then replayed as a constant; determinism AND statistics break (rule
  ``traced-host-rng``; ``jax.random`` with explicit keys is the
  sanctioned path, see utils/rng);
* host ``float()`` coercion — the exact integer count path must never
  detour through host floats (rule ``traced-float-coercion``);
* implicit device syncs: ``.item()``, ``np.asarray`` on traced values,
  ``.block_until_ready()`` — a sync inside traced code either fails to
  trace or serializes the very dispatch the kernel fuses (rule
  ``traced-device-sync``).

Reachability is a fixpoint over the corpus call graph from the traced
roots; only confidently-resolved calls (local defs, imported repo
functions) are followed, so the pass under-approximates rather than
spraying false positives.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name,
)

_JIT_WRAPPERS = {"jax.jit", "jit", "ensure_jit"}
_SHMAP_WRAPPERS = {"jax.shard_map", "shard_map", "jax.experimental."
                   "shard_map.shard_map"}
_PALLAS = {"pl.pallas_call", "pallas_call"}

_WALL_CLOCK = {"time.time", "time.perf_counter", "time.monotonic",
               "time.time_ns", "time.perf_counter_ns",
               "datetime.now", "datetime.datetime.now",
               "datetime.utcnow"}
_SYNC_LEAVES = {"item", "block_until_ready"}


def _is_jit_deco(node: ast.AST) -> bool:
    from tuplewise_tpu.analysis.core import dotted

    if isinstance(node, (ast.Name, ast.Attribute)):
        return dotted(node) in _JIT_WRAPPERS
    if isinstance(node, ast.Call):
        cn = call_name(node)
        if cn in _JIT_WRAPPERS:
            return True
        # @partial(jax.jit, static_argnames=...)
        if cn in ("partial", "functools.partial") and node.args:
            return dotted(node.args[0]) in _JIT_WRAPPERS
    return False


def _func_arg_names(call: ast.Call, positions) -> List[str]:
    out = []
    for i in positions:
        if i < len(call.args):
            a = call.args[i]
            if isinstance(a, ast.Name):
                out.append(a.id)
    return out


def run(ms: ModuleSet) -> List[Finding]:
    # 1) collect every function node with a stable key, plus lambdas
    #    passed to tracing wrappers (lambdas are scanned in place)
    funcs: Dict[Tuple[str, str], ast.AST] = {}
    by_name: Dict[str, List[Tuple[str, str]]] = {}
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            funcs[(path, fi.qualname)] = fi.node
            by_name.setdefault(fi.qualname.split(".")[-1], []).append(
                (path, fi.qualname))

    roots: Set[Tuple[str, str]] = set()
    lambda_roots: List[Tuple[str, ast.Lambda]] = []

    def local_lookup(path: str, name: str
                     ) -> Optional[Tuple[str, str]]:
        mi = ms.modules[path]
        # prefer a def in the same module (any nesting), else resolve
        # the import, else give up
        cands = [k for k in by_name.get(name, ()) if k[0] == path]
        if cands:
            return cands[0]
        resolved = ms.resolve_import(mi, name)
        if resolved is not None:
            tpath, sym = resolved
            cands = [k for k in by_name.get(sym or name, ())
                     if k[0] == tpath]
            if cands:
                return cands[0]
        return None

    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            for deco in getattr(fi.node, "decorator_list", ()):
                if _is_jit_deco(deco):
                    roots.add((path, fi.qualname))
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            targets: List[ast.AST] = []
            if cn in _JIT_WRAPPERS:
                targets = node.args[:1]
            elif cn in _SHMAP_WRAPPERS or cn in _PALLAS:
                targets = node.args[:1]
            for t in targets:
                if isinstance(t, ast.Lambda):
                    lambda_roots.append((path, t))
                elif isinstance(t, ast.Name):
                    k = local_lookup(path, t.id)
                    if k is not None:
                        roots.add(k)

    # 2) call graph over confidently-resolved calls
    calls: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    for (path, qn), node in funcs.items():
        out: Set[Tuple[str, str]] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                cn = call_name(sub)
                if cn and "." not in cn:
                    k = local_lookup(path, cn)
                    if k is not None and k != (path, qn):
                        out.add(k)
        calls[(path, qn)] = out

    reached: Set[Tuple[str, str]] = set()
    frontier = list(roots)
    while frontier:
        k = frontier.pop()
        if k in reached:
            continue
        reached.add(k)
        frontier.extend(calls.get(k, ()))

    # 3) scan reached bodies (and traced lambdas) for impurities
    findings: List[Finding] = []

    def scan(path: str, qn: str, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            cn = call_name(sub)
            if cn is None:
                continue
            leaf = cn.split(".")[-1]
            if cn in _WALL_CLOCK:
                findings.append(Finding(
                    "traced-wall-clock", path, sub.lineno,
                    f"{qn}::{cn}",
                    f"wall-clock read {cn}() inside traced code "
                    f"({qn}): traces bake it into the compiled "
                    "program as a constant"))
            elif cn.startswith("np.random.") \
                    or cn.startswith("numpy.random.") \
                    or cn.startswith("random."):
                findings.append(Finding(
                    "traced-host-rng", path, sub.lineno,
                    f"{qn}::{cn}",
                    f"host RNG {cn}() inside traced code ({qn}): "
                    "traced once then replayed as a constant; use "
                    "jax.random with an explicit key"))
            elif cn == "float":
                findings.append(Finding(
                    "traced-float-coercion", path, sub.lineno,
                    f"{qn}::float",
                    f"host float() coercion inside traced code "
                    f"({qn}): the integer-exact count path must not "
                    "detour through host floats (DESIGN §15)"))
            elif leaf in _SYNC_LEAVES or cn in ("np.asarray",
                                                "numpy.asarray"):
                findings.append(Finding(
                    "traced-device-sync", path, sub.lineno,
                    f"{qn}::{leaf}",
                    f"implicit device sync {cn}() inside traced code "
                    f"({qn})"))

    for (path, qn) in sorted(reached):
        scan(path, qn, funcs[(path, qn)])
    for path, lam in lambda_roots:
        scan(path, f"<lambda@{lam.lineno}>", lam)

    # dedupe by fingerprint
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            out.append(f)
    return out
