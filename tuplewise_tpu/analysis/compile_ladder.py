"""Pass 4 — compile-ladder discipline [ISSUE 12].

The XLA/Pallas compile-cache stays bounded ONLY because every shape a
jitted count function is built for comes off the power-of-two bucket
ladders ((T_bucket, cap, q_bucket) — DESIGN §8/§15). The jit factories
are the chokepoint: every ``@functools.lru_cache`` function whose
returned callable is jitted (``_jit_count_fn``, ``sharded_count_fn``,
``delta_append_fn``, ``_merge_*_fn``, ...) keys its cache — and the
compiled-shape universe — on its integer arguments.

Rule ``ladder-raw-shape``: at any call site of such a factory, a
shape-determining argument whose expression derives directly from
``len(...)`` / ``.shape`` / ``.size`` without passing through a bucket
helper (``next_bucket`` / ``_next_bucket`` / ``_t_bucket``) compiles
one program per distinct live size — unbounded cache growth and a
recompile storm under churn. One level of local assignment is chased:
``qb = len(q)`` then ``f(qb)`` is flagged; ``qb = next_bucket(len(q))``
is clean.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted,
)

_BUCKET_HELPERS = {"next_bucket", "_next_bucket", "_t_bucket",
                   "self._t_bucket"}


def _is_lru(node: ast.AST) -> bool:
    for deco in getattr(node, "decorator_list", ()):
        d = deco
        if isinstance(d, ast.Call):
            d = d.func
        name = dotted(d)
        if name in ("functools.lru_cache", "lru_cache",
                    "functools.cache", "cache"):
            return True
    return False


def ladder_factories(ms: ModuleSet) -> Dict[str, Set[int]]:
    """{factory name: shape-arg positions} — every lru_cache'd
    function in the corpus; the cache key IS the compile-shape key, so
    every non-mesh positional argument is shape-determining."""
    out: Dict[str, Set[int]] = {}
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            node = fi.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not _is_lru(node):
                continue
            positions = set()
            for i, arg in enumerate(node.args.args):
                if arg.arg in ("mesh", "self", "cls", "dtype",
                               "kernel", "interpret"):
                    continue
                positions.add(i)
            if positions:
                out[node.name] = positions
    return out


def _raw_shape(expr: ast.AST) -> Optional[str]:
    """The offending sub-expression when ``expr`` derives a raw size,
    ignoring anything wrapped in a bucket helper."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in _BUCKET_HELPERS or (
                    cn and cn.split(".")[-1] in _BUCKET_HELPERS):
                # prune: children of a bucket call are sanctioned.
                # ast.walk can't prune, so check containment instead.
                sanctioned = set(ast.walk(node))
                return _raw_shape_outside(expr, sanctioned)
    return _raw_shape_outside(expr, set())


def _raw_shape_outside(expr: ast.AST, sanctioned) -> Optional[str]:
    for node in ast.walk(expr):
        if node in sanctioned:
            continue
        if isinstance(node, ast.Call) and call_name(node) == "len":
            return "len(...)"
        if isinstance(node, ast.Attribute) and node.attr in ("shape",
                                                             "size"):
            return f".{node.attr}"
    return None


def run(ms: ModuleSet) -> List[Finding]:
    factories = ladder_factories(ms)
    findings: List[Finding] = []
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            # local one-level assignment map: name -> value expr
            assigns: Dict[str, ast.AST] = {}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    assigns[node.targets[0].id] = node.value
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                leaf = cn.split(".")[-1] if cn else None
                if leaf not in factories:
                    continue
                # skip the factory's own definition module self-call?
                # no — a raw-size call inside the defining module is
                # exactly as wrong as anywhere else.
                for i, arg in enumerate(node.args):
                    if i not in factories[leaf]:
                        continue
                    expr = arg
                    label = ast.dump(arg)[:0]  # unused; keep expr
                    if isinstance(arg, ast.Name) \
                            and arg.id in assigns:
                        expr = assigns[arg.id]
                    bad = _raw_shape(expr)
                    if bad is not None:
                        findings.append(Finding(
                            "ladder-raw-shape", path, node.lineno,
                            f"{fi.qualname}::{leaf}:{i}",
                            f"{fi.qualname} passes a raw {bad}-derived"
                            f" size as shape arg {i} of {leaf}() — "
                            "shape-determining values must come off "
                            "the bucket ladder (next_bucket) or XLA "
                            "compiles one program per live size"))
    return findings
