"""Pass 4 — compile-ladder discipline [ISSUE 12, flow-sensitive
rework ISSUE 13].

The XLA/Pallas compile-cache stays bounded ONLY because every shape a
jitted count function is built for comes off the power-of-two bucket
ladders ((T_bucket, cap, q_bucket) — DESIGN §8/§15). The jit factories
are the chokepoint: every ``@functools.lru_cache`` function whose
returned callable is jitted (``_jit_count_fn``, ``sharded_count_fn``,
``delta_append_fn``, ``_merge_*_fn``, ...) keys its cache — and the
compiled-shape universe — on its integer arguments.

Rule ``ladder-raw-shape``: at any call site of such a factory, a
shape-determining argument whose value derives from ``len(...)`` /
``.shape`` / ``.size`` of an arbitrary array compiles one program per
distinct live size — unbounded cache growth and a recompile storm
under churn.

PR 12's version chased ONE local assignment; this version evaluates
the argument on the shared dataflow substrate (``analysis.dataflow``)
with a ladder lattice:

* ``next_bucket`` / ``_next_bucket`` / ``_t_bucket`` /
  ``tenant_bucket`` results are **bucketed**, as are integer
  constants and min/max/arithmetic over bucketed values;
* arrays allocated with bucketed dimensions (``np.full(bb, ...)``,
  ``np.zeros((t_bucket, qb))``) — and arrays RETURNED by a
  ladder-compiled ``*_fn(...)(...)`` factory call, whose shapes are
  ladder-derived by induction — are **ladder arrays**: their
  ``len()`` / ``.shape`` / ``.size`` reads are the ladder value
  itself, not a raw size;
* the chase is interprocedural: parameters take the JOIN of their
  resolved call-site values (a query block every caller pads to its
  bucket proves the callee's ``.shape`` read clean), and constructor
  fields flow through NamedTuples (``plan.pos`` is the
  ``next_bucket``-padded array ``plan_major_merge`` built).

This is precision the PR 12 waivers papered over: the
``sharded_major_merge`` / ``tenant_pack_counts`` bucketed-shape
entries are gone from ``waivers.toml`` because the checker now PROVES
them on-ladder [ISSUE 13 satellite].
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted,
)
from tuplewise_tpu.analysis import dataflow

_BUCKET_HELPERS = {"next_bucket", "_next_bucket", "_t_bucket",
                   "tenant_bucket", "self._t_bucket"}

# lattice values (hashable strings; dataflow.Domain contract)
BUCKETED = "bucketed"        # on the ladder (or a plain constant)
RAW = "raw"                  # derived from an arbitrary len/.shape
ARR_LADDER = "arr_ladder"    # array with ladder-derived dimensions
SHAPE_LADDER = "shape_ladder"  # .shape of a ladder array
SHAPE_RAW = "shape_raw"      # .shape of anything else

_ALLOC_LEAVES = {"zeros", "full", "empty", "ones"}


def _is_lru(node: ast.AST) -> bool:
    for deco in getattr(node, "decorator_list", ()):
        d = deco
        if isinstance(d, ast.Call):
            d = d.func
        name = dotted(d)
        if name in ("functools.lru_cache", "lru_cache",
                    "functools.cache", "cache"):
            return True
    return False


class LadderDomain(dataflow.Domain):
    """raw dominates (a maybe-raw shape is a finding); bucketed and
    constants are interchangeable for cache-boundedness."""

    top = None

    def join(self, a, b):
        if a == b:
            return a
        if a is None or b is None:
            return None if RAW not in (a, b) else RAW
        if RAW in (a, b) or SHAPE_RAW in (a, b):
            return RAW
        if {a, b} <= {BUCKETED, ARR_LADDER, SHAPE_LADDER}:
            return BUCKETED if BUCKETED in (a, b) else a
        return None

    def const(self, value):
        if isinstance(value, bool):
            return None
        if isinstance(value, int):
            return BUCKETED     # a literal shape is one cache entry
        return None

    def call(self, cn, node, argvals, kwvals, recv=None):
        leaf = cn.split(".")[-1] if cn else None
        if cn in _BUCKET_HELPERS or leaf in _BUCKET_HELPERS:
            return BUCKETED
        if cn == "len":
            v = argvals[0] if argvals else None
            return BUCKETED if v == ARR_LADDER else RAW
        if cn == "int" or leaf in ("int32", "int64"):
            return argvals[0] if argvals else None
        if cn in ("min", "max"):
            out = BUCKETED
            for v in argvals:
                if v == RAW:
                    return RAW
                if v is None:
                    out = None
            return out
        if leaf in _ALLOC_LEAVES:
            shape = argvals[0] if argvals else None
            if isinstance(shape, dataflow.Seq):
                vals = set(shape.elts)
                if vals <= {BUCKETED}:
                    return ARR_LADDER
                if RAW in vals:
                    return None
                return None
            if shape == BUCKETED:
                return ARR_LADDER
            return None
        if leaf in ("concatenate", "stack", "hstack", "sort",
                    "ascontiguousarray"):
            v = argvals[0] if argvals else None
            if isinstance(v, dataflow.Seq):
                if set(v.elts) <= {ARR_LADDER}:
                    return ARR_LADDER
                return None
            return v if v in (ARR_LADDER,) else None
        if leaf in ("asarray", "copy", "ravel", "reshape",
                    "astype"):
            if recv in (ARR_LADDER,):
                return recv
            v = argvals[0] if argvals else None
            return v if v == ARR_LADDER else None
        # calling the value a ladder factory returned: the result of a
        # ladder-compiled program has ladder shapes by induction
        if cn is None and isinstance(node.func, ast.Call):
            inner = call_name(node.func)
            if inner and inner.split(".")[-1].endswith("_fn"):
                return ARR_LADDER
        return None

    def attribute(self, base, attr):
        if attr in ("shape",):
            return SHAPE_LADDER if base == ARR_LADDER else SHAPE_RAW
        if attr in ("size",):
            return BUCKETED if base == ARR_LADDER else RAW
        return None

    def subscript(self, base, index):
        if base == SHAPE_LADDER:
            return BUCKETED
        if base == SHAPE_RAW:
            return RAW
        if base == ARR_LADDER:
            return None
        return None

    def binop(self, op, left, right):
        if RAW in (left, right):
            return RAW
        if left is None or right is None:
            return None
        if BUCKETED in (left, right):
            return BUCKETED
        return None

    def sequence(self, node, elts):
        return self.top


def ladder_factories(ms: ModuleSet) -> Dict[str, Set[int]]:
    """{factory name: shape-arg positions} — every lru_cache'd
    function in the corpus; the cache key IS the compile-shape key, so
    every non-mesh positional argument is shape-determining."""
    out: Dict[str, Set[int]] = {}
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            node = fi.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not _is_lru(node):
                continue
            positions = set()
            for i, arg in enumerate(node.args.args):
                if arg.arg in ("mesh", "self", "cls", "dtype",
                               "kernel", "interpret"):
                    continue
                positions.add(i)
            if positions:
                out[node.name] = positions
    return out


def _raw_label(expr: ast.AST) -> str:
    """Human label of the offending derivation for the message."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_name(node) == "len":
            return "len(...)"
        if isinstance(node, ast.Attribute) and node.attr in ("shape",
                                                             "size"):
            return f".{node.attr}"
    return "len(...)/.shape"


def run(ms: ModuleSet) -> List[Finding]:
    factories = ladder_factories(ms)
    engine = dataflow.Engine(ms, LadderDomain())
    findings: List[Finding] = []

    for key, node in engine.graph.functions.items():
        path, cls, qual = key
        if not any(isinstance(sub, ast.Call)
                   and (lambda c: c and c.split(".")[-1]
                        in factories)(call_name(sub))
                   for sub in ast.walk(node)):
            continue
        calls: List[tuple] = []

        def hook(walker, st, _calls=calls):
            for sub in ast.walk(st):
                if not isinstance(sub, ast.Call):
                    continue
                cn = call_name(sub)
                leaf = cn.split(".")[-1] if cn else None
                if leaf not in factories:
                    continue
                for i, arg in enumerate(sub.args):
                    if i not in factories[leaf]:
                        continue
                    val = walker.eval(arg)
                    if val == RAW:
                        _calls.append((leaf, i, arg, sub.lineno))

        engine.trace_function(key, hook)
        for leaf, i, arg, lineno in calls:
            findings.append(Finding(
                "ladder-raw-shape", path, lineno,
                f"{qual}::{leaf}:{i}",
                f"{qual} passes a raw {_raw_label(arg)}-derived"
                f" size as shape arg {i} of {leaf}() — "
                "shape-determining values must come off "
                "the bucket ladder (next_bucket) or XLA "
                "compiles one program per live size"))

    # dedupe by fingerprint (a loop can hit the same site twice)
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            out.append(f)
    return out
