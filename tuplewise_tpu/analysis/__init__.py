"""Static invariant checkers for the serving stack [ISSUE 12].

Eleven PRs of conventions no tool enforced — lock discipline across the
thread roles, the (T_bucket, cap, q_bucket) compile-shape ladder,
integer-exact signed-multiset math, and a telemetry namespace consumed
by doctor/SLO/perf-gate by string match — become five AST-based passes
(stdlib ``ast`` only, no new deps), exposed as ``tuplewise check`` and
a fail-mode CI leg (``scripts/analysis_gate.py``):

* ``lock-order``      — static lock-acquisition graph, acquisition-
                        order cycles, locks held across blocking ops
                        (device dispatch, unbounded queue put/get,
                        fsync, ``Future.result``).
* ``traced-purity``   — inside code reached by ``jax.jit`` /
                        ``pallas_call`` / ``shard_map``: no wall-clock
                        reads, no unseeded host RNG, no ``float()``
                        coercions, no ``.item()`` / device syncs.
* ``telemetry-xref``  — every metric / flight-event kind / span name
                        consumed by doctor / SLO / report / perf-gate
                        (or documented) must have a matching producer.
* ``compile-ladder``  — shape-determining args into the jitted/Pallas
                        count factories must pass through the bucket
                        helpers, never raw ``len()``-derived values.
* ``config-drift``    — ServingConfig/TenancyConfig/ControllerConfig
                        fields <-> CLI flags <-> README/DESIGN mentions
                        must agree.

The flow-sensitive dataflow tier [ISSUE 13] rides on
``analysis/dataflow.py`` (interprocedural call graph + forward
abstract interpretation — the replacement for the one-assignment
chase):

* ``races``           — RacerD-style guard inference: per thread role
                        (batcher/compactor/reaper/...), attributes
                        reachable from >= 2 roles that are accessed
                        unguarded or under inconsistent locks, with
                        the access-site evidence chain.
* ``exactness``       — int-lattice proof that no float taints a
                        wins2 accumulator, plus the int32 overflow
                        certificate (worst-case bounds at the
                        compile-ladder maxima, diffed in CI against
                        the committed exactness_bounds.toml).

The host-cost + lifecycle tier [ISSUE 15] ratchets the one-dispatch
serving-core refactor:

* ``hotpath``         — abstract cost certification of everything
                        reachable from the request-path roots:
                        allocations / ctors / np allocations /
                        attribute hops / locks / device dispatches,
                        classified O(1)/O(tenants)/O(events); the
                        certificate is diffed in CI against the
                        committed hotpath_budget.toml — growth fails
                        naming root + site + budget line, shrinkage
                        ratchets the budget down.
* ``lifecycle``       — exception-flow + resource lifecycle: every
                        Future resolves on every path (leak /
                        double-resolve / close-drain rules, with the
                        pre-PR-8 and pre-PR-11 holes as regression
                        fixtures), Thread/Timer daemon-or-join, file
                        handles close on exception paths, and every
                        typed serving error is wire-handled,
                        doctor-visible, and documented.

Findings are suppressible ONLY via the committed, per-finding-justified
waiver file (``analysis/waivers.toml``); each waiver absorbs a bounded
count of findings, so NEW violations fail even where old waived ones
exist (the ratchet). The shared module graph also emits an import-cycle
report (fail on new top-level cycles) and a warn-only dead-public-
symbol list. DESIGN §17 documents the rule catalogue and waiver policy.
"""

from tuplewise_tpu.analysis.core import Finding, ModuleSet

__all__ = ["Finding", "ModuleSet", "PASSES", "run_checks"]


def __getattr__(name):
    # lazy: the runner imports every pass module, and the passes import
    # this package — a top-level import here would be exactly the
    # import cycle the module-graph report exists to forbid
    if name in ("PASSES", "run_checks"):
        from tuplewise_tpu.analysis import runner

        return getattr(runner, {"PASSES": "PASSES",
                                "run_checks": "run_checks"}[name])
    raise AttributeError(name)
