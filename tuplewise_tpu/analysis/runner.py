"""``tuplewise check`` — run the invariant passes (five syntactic
[ISSUE 12] + the flow-sensitive dataflow tier [ISSUE 13] + the
host-cost / lifecycle certification tier [ISSUE 15]) plus the
module-graph report over the repo, apply the committed waiver file,
and render one JSON report.

The report carries the **overflow certificate** (per-int32-accumulator
worst-case bounds at the compile-ladder maxima), the **hotpath
certificate** [ISSUE 15] (per-request-path-root abstract cost
summaries, diffed by the gate against the committed
``analysis/hotpath_budget.toml`` — growth fails, shrinkage ratchets),
the parse-cache counters (epoch-keyed: a waiver/budget/checker edit
forces a cold run), and a per-pass **timing block** (independent
passes run concurrently on multi-core hosts; ``--jobs 1`` forces the
serial path).

``--diff <ref>`` [ISSUE 15 satellite] restricts reported findings to
files changed vs a git ref PLUS their reverse-dependency closure from
the module graph — the fast pre-commit loop
(``scripts/pre-commit.sh``). Stale waivers never fail a diff run
(out-of-scope findings legitimately match nothing).

Exit status: 0 = no unwaived findings (waived ones are listed, not
fatal); 1 = at least one unwaived finding, a malformed waiver file, or
(``--strict``) a stale waiver matching nothing. The CI leg
(``scripts/analysis_gate.py``) runs this in fail mode, diffs both
certificates against their committed baselines, and uploads the JSON
(and ``--sarif``) artifacts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis import compile_ladder
from tuplewise_tpu.analysis import config_drift
from tuplewise_tpu.analysis import exactness
from tuplewise_tpu.analysis import hotpath
from tuplewise_tpu.analysis import lifecycle
from tuplewise_tpu.analysis import lock_order
from tuplewise_tpu.analysis import modgraph
from tuplewise_tpu.analysis import races
from tuplewise_tpu.analysis import telemetry_xref
from tuplewise_tpu.analysis import traced_purity
from tuplewise_tpu.analysis.cache import ParseCache, compute_epoch
from tuplewise_tpu.analysis.core import Finding, ModuleSet
from tuplewise_tpu.analysis.waivers import (
    WaiverError, apply_waivers, load_waivers,
)

#: (name, pass callable) — five syntactic passes [ISSUE 12], the two
#: dataflow-tier passes [ISSUE 13], the host-cost / lifecycle tier
#: [ISSUE 15], and the module-graph report
PASSES: Tuple[Tuple[str, Callable[[ModuleSet], List[Finding]]], ...] = (
    ("lock-order", lock_order.run),
    ("traced-purity", traced_purity.run),
    ("telemetry-xref", telemetry_xref.run),
    ("compile-ladder", compile_ladder.run),
    ("config-drift", config_drift.run),
    ("races", races.run),
    ("exactness", exactness.run),
    ("hotpath", hotpath.run),
    ("lifecycle", lifecycle.run),
    ("module-graph", modgraph.run),
)

DEFAULT_WAIVERS = "tuplewise_tpu/analysis/waivers.toml"

#: per-pass wall-clock budget inside the process pool before the
#: runner falls back to computing that pass serially
_POOL_PASS_TIMEOUT_S = 300.0

#: the forked workers read this; fork shares it copy-on-write so the
#: parsed corpus is never pickled per task
_POOL_MS: Optional[ModuleSet] = None


def repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _run_one(name: str, ms: ModuleSet):
    """(findings, hotpath certificate or None, seconds) for one pass.
    The hotpath pass derives its findings FROM the certificate, so
    one derivation serves both the findings and the report key."""
    t0 = time.perf_counter()
    if name == "hotpath":
        cert = hotpath.certificates(ms)
        fs = hotpath.missing_findings(cert)
    else:
        cert = None
        fs = dict(PASSES)[name](ms)
    return fs, cert, time.perf_counter() - t0


def _pool_worker(name: str):
    return (name,) + _run_one(name, _POOL_MS)


def _default_jobs() -> int:
    cpus = os.cpu_count() or 1
    if cpus <= 2 or not hasattr(os, "fork"):
        return 1    # fork overhead beats the win on small boxes
    return min(len(PASSES), cpus)


def _run_passes(ms: ModuleSet, jobs: Optional[int]
                ) -> Tuple[Dict[str, List[Finding]],
                           Dict[str, float], Optional[dict], int]:
    """Run every pass, concurrently when the host has the cores for
    it [ISSUE 15 satellite]. Returns (per-pass findings, per-pass
    seconds, hotpath certificate, effective jobs). Pass results are
    deterministic and independent, so parallel == serial output by
    construction; any pool failure falls back to the serial path for
    whatever is missing."""
    jobs = _default_jobs() if jobs is None else max(1, int(jobs))
    results: Dict[str, List[Finding]] = {}
    timings: Dict[str, float] = {}
    cert: Optional[dict] = None
    if jobs > 1:
        global _POOL_MS
        try:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            ctx = multiprocessing.get_context("fork")
            _POOL_MS = ms
            with ProcessPoolExecutor(max_workers=jobs,
                                     mp_context=ctx) as ex:
                futs = {ex.submit(_pool_worker, name): name
                        for name, _fn in PASSES}
                for fut, name in futs.items():
                    try:
                        rname, fs, c, secs = fut.result(
                            timeout=_POOL_PASS_TIMEOUT_S)
                        results[rname] = fs
                        timings[rname] = secs
                        if c is not None:
                            cert = c
                    except Exception:
                        pass    # recomputed serially below
        except Exception:
            jobs = 1
        finally:
            _POOL_MS = None
    for name, _fn in PASSES:
        if name in results:
            continue
        fs, c, secs = _run_one(name, ms)
        results[name] = fs
        timings[name] = secs
        if c is not None:
            cert = c
    return results, timings, cert, jobs


def _git_changed(root: str, ref: str) -> Optional[Set[str]]:
    """Files changed vs ``ref`` (tracked diff + untracked), repo-
    relative; None when git is unavailable / ref unresolvable."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref],
            cwd=root, capture_output=True, text=True, timeout=30)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
        out = {ln.strip() for ln in diff.stdout.splitlines()
               if ln.strip()}
        if untracked.returncode == 0:
            out |= {ln.strip() for ln in untracked.stdout.splitlines()
                    if ln.strip()}
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def run_checks(root: Optional[str] = None,
               waivers_path: Optional[str] = None,
               strict: bool = False,
               ms: Optional[ModuleSet] = None,
               use_cache: bool = True,
               jobs: Optional[int] = None,
               diff_ref: Optional[str] = None) -> dict:
    """The whole check as one JSON-able report dict; ``ms`` overrides
    the repo walk (fixture tests)."""
    t_total = time.perf_counter()
    root = root or repo_root()
    cache = None
    if ms is None:
        cache = ParseCache(root, epoch=compute_epoch(root)) \
            if use_cache else None
        ms = ModuleSet.from_repo(root, cache=cache)

    per_pass_findings, pass_timings, hot_cert, jobs_used = \
        _run_passes(ms, jobs)
    findings: List[Finding] = []
    per_pass = {}
    for name, _fn in PASSES:
        fs = per_pass_findings[name]
        per_pass[name] = len(fs)
        findings.extend(fs)
    findings.sort(key=lambda f: (f.rule, f.file, f.symbol))

    # --diff [ISSUE 15 satellite]: scope findings to the changed
    # files + their reverse-dependency closure. Findings without a
    # real file (module-graph cycles) stay in scope.
    diff_info = None
    if diff_ref is not None:
        changed = _git_changed(root, diff_ref)
        if changed is None:
            diff_info = {"ref": diff_ref, "error":
                         "git diff failed — running unscoped"}
        else:
            scope = modgraph.reverse_closure(
                ms, {p for p in changed if p in ms.modules})
            scope |= changed
            findings = [f for f in findings
                        if f.file in scope
                        or not f.file.endswith(".py")]
            diff_info = {"ref": diff_ref,
                         "changed": sorted(changed & set(ms.modules)),
                         "scope": sorted(scope & set(ms.modules))}

    waiver_error = None
    waivers = []
    wpath = waivers_path
    if wpath is None:
        cand = os.path.join(root, DEFAULT_WAIVERS)
        wpath = cand if os.path.exists(cand) else None
    if wpath:
        try:
            with open(wpath, "r", encoding="utf-8") as f:
                waivers = load_waivers(f.read())
        except WaiverError as e:
            waiver_error = str(e)

    unwaived, waived, unused = apply_waivers(findings, waivers)
    if diff_info is not None:
        unused = []     # out-of-scope findings legitimately unmatched

    # overflow certificate [ISSUE 13]: the per-accumulator bound table
    # at the declared compile-ladder maxima; ok=False bounds already
    # surfaced as overflow-int32 findings through the exactness pass
    cert = exactness.certificates(ms)

    # graph reports, timed so total_s covers the WHOLE check
    t0 = time.perf_counter()
    import_cycles = [cyc for cyc in modgraph.find_cycles(
        modgraph.import_graph(ms))]
    dead = modgraph.dead_symbols(ms)
    pass_timings["module-graph"] = pass_timings.get(
        "module-graph", 0.0) + (time.perf_counter() - t0)

    ok = not unwaived and waiver_error is None \
        and not ms.parse_errors and not (strict and unused)
    report = {
        "stage": "tuplewise_check",
        "ok": ok,
        "summary": {
            "files_analyzed": len(ms.modules),
            "findings_total": len(findings),
            "unwaived": len(unwaived),
            "waived": len(waived),
            "waivers_unused": len(unused),
            "per_pass": per_pass,
            "cache": (cache.stats() if cache is not None
                      else {"enabled": False, "hits": 0,
                            "misses": 0}),
            "timings": {
                "jobs": jobs_used,
                "passes_s": {k: round(v, 4)
                             for k, v in sorted(pass_timings.items())},
                "total_s": round(time.perf_counter() - t_total, 4),
            },
        },
        "overflow_certificate": cert,
        "hotpath_certificate": hot_cert,
        "findings": [f.to_dict() for f in unwaived],
        "waived": [dict(f.to_dict(), reason=w.reason,
                        waiver_line=w.line) for f, w in waived],
        "unused_waivers": [
            {"rule": w.rule, "file": w.file, "symbol": w.symbol,
             "line": w.line} for w in unused],
        "parse_errors": dict(ms.parse_errors),
        "import_cycles": import_cycles,
        "dead_symbols": dead,
    }
    if diff_info is not None:
        report["diff"] = diff_info
    if waiver_error is not None:
        report["waiver_error"] = waiver_error
    return report


def main(args) -> int:
    """CLI entry (argparse namespace from harness/cli.py)."""
    report = run_checks(root=args.root, waivers_path=args.waivers,
                        strict=args.strict,
                        use_cache=not getattr(args, "no_cache",
                                              False),
                        jobs=getattr(args, "jobs", None),
                        diff_ref=getattr(args, "diff", None))
    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        s = report["summary"]
        c = s["cache"]
        t = s["timings"]
        cache_note = (f", cache {c['hits']} hit/{c['misses']} miss"
                      if c["enabled"] else ", cache off")
        diff_note = ""
        if "diff" in report:
            d = report["diff"]
            diff_note = (f", diff vs {d['ref']} "
                         f"({len(d.get('scope', []))} files in scope)")
        print(f"tuplewise check: {s['files_analyzed']} files, "
              f"{s['findings_total']} findings "
              f"({s['waived']} waived, {s['unwaived']} unwaived)"
              f"{cache_note}{diff_note}, {t['total_s']:.2f}s "
              f"(jobs={t['jobs']})")
        for f in report["findings"]:
            print(f"  {f['rule']}: {f['file']}:{f['line']} "
                  f"[{f['symbol']}]\n    {f['message']}")
        if report.get("waiver_error"):
            print(f"  waiver file error: {report['waiver_error']}",
                  file=sys.stderr)
        for w in report["unused_waivers"]:
            print(f"  stale waiver (matched nothing): {w['rule']} "
                  f"{w['file']} [{w['symbol']}] "
                  f"(waivers.toml:{w['line']})")
        if report["dead_symbols"]:
            print(f"  note: {len(report['dead_symbols'])} unreferenced "
                  "public symbols (warn-only; see --json)")
        print("OK" if report["ok"] else "FAIL")
    return 0 if report["ok"] else 1
