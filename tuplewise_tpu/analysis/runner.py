"""``tuplewise check`` — run the invariant passes (five syntactic
[ISSUE 12] + the flow-sensitive dataflow tier [ISSUE 13]: guard
inference + integer-exactness/overflow certification) plus the
module-graph report over the repo, apply the committed waiver file,
and render one JSON report.

The report also carries the **overflow certificate**
(``overflow_certificate``: per-int32-accumulator worst-case bounds at
the compile-ladder maxima) and the parse-cache counters (repeat runs
reparse only changed files; ``--no-cache`` disables).

Exit status: 0 = no unwaived findings (waived ones are listed, not
fatal); 1 = at least one unwaived finding, a malformed waiver file, or
(``--strict``) a stale waiver matching nothing. The CI leg
(``scripts/analysis_gate.py``) runs this in fail mode, diffs the
certificate against the committed ``analysis/exactness_bounds.toml``,
and uploads the JSON (and ``--sarif``) artifacts.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Callable, List, Optional, Tuple

from tuplewise_tpu.analysis import compile_ladder
from tuplewise_tpu.analysis import config_drift
from tuplewise_tpu.analysis import exactness
from tuplewise_tpu.analysis import lock_order
from tuplewise_tpu.analysis import modgraph
from tuplewise_tpu.analysis import races
from tuplewise_tpu.analysis import telemetry_xref
from tuplewise_tpu.analysis import traced_purity
from tuplewise_tpu.analysis.cache import ParseCache
from tuplewise_tpu.analysis.core import Finding, ModuleSet
from tuplewise_tpu.analysis.waivers import (
    WaiverError, apply_waivers, load_waivers,
)

#: (name, pass callable) — five syntactic passes [ISSUE 12], the two
#: dataflow-tier passes [ISSUE 13], and the module-graph report
PASSES: Tuple[Tuple[str, Callable[[ModuleSet], List[Finding]]], ...] = (
    ("lock-order", lock_order.run),
    ("traced-purity", traced_purity.run),
    ("telemetry-xref", telemetry_xref.run),
    ("compile-ladder", compile_ladder.run),
    ("config-drift", config_drift.run),
    ("races", races.run),
    ("exactness", exactness.run),
    ("module-graph", modgraph.run),
)

DEFAULT_WAIVERS = "tuplewise_tpu/analysis/waivers.toml"


def repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def run_checks(root: Optional[str] = None,
               waivers_path: Optional[str] = None,
               strict: bool = False,
               ms: Optional[ModuleSet] = None,
               use_cache: bool = True) -> dict:
    """The whole check as one JSON-able report dict; ``ms`` overrides
    the repo walk (fixture tests)."""
    root = root or repo_root()
    cache = None
    if ms is None:
        cache = ParseCache(root) if use_cache else None
        ms = ModuleSet.from_repo(root, cache=cache)

    findings: List[Finding] = []
    per_pass = {}
    for name, fn in PASSES:
        fs = fn(ms)
        per_pass[name] = len(fs)
        findings.extend(fs)
    findings.sort(key=lambda f: (f.rule, f.file, f.symbol))

    waiver_error = None
    waivers = []
    wpath = waivers_path
    if wpath is None:
        cand = os.path.join(root, DEFAULT_WAIVERS)
        wpath = cand if os.path.exists(cand) else None
    if wpath:
        try:
            with open(wpath, "r", encoding="utf-8") as f:
                waivers = load_waivers(f.read())
        except WaiverError as e:
            waiver_error = str(e)

    unwaived, waived, unused = apply_waivers(findings, waivers)

    # overflow certificate [ISSUE 13]: the per-accumulator bound table
    # at the declared compile-ladder maxima; ok=False bounds already
    # surfaced as overflow-int32 findings through the exactness pass
    cert = exactness.certificates(ms)

    ok = not unwaived and waiver_error is None \
        and not ms.parse_errors and not (strict and unused)
    report = {
        "stage": "tuplewise_check",
        "ok": ok,
        "summary": {
            "files_analyzed": len(ms.modules),
            "findings_total": len(findings),
            "unwaived": len(unwaived),
            "waived": len(waived),
            "waivers_unused": len(unused),
            "per_pass": per_pass,
            "cache": (cache.stats() if cache is not None
                      else {"enabled": False, "hits": 0,
                            "misses": 0}),
        },
        "overflow_certificate": cert,
        "findings": [f.to_dict() for f in unwaived],
        "waived": [dict(f.to_dict(), reason=w.reason,
                        waiver_line=w.line) for f, w in waived],
        "unused_waivers": [
            {"rule": w.rule, "file": w.file, "symbol": w.symbol,
             "line": w.line} for w in unused],
        "parse_errors": dict(ms.parse_errors),
        "import_cycles": [
            cyc for cyc in modgraph.find_cycles(
                modgraph.import_graph(ms))],
        "dead_symbols": modgraph.dead_symbols(ms),
    }
    if waiver_error is not None:
        report["waiver_error"] = waiver_error
    return report


def main(args) -> int:
    """CLI entry (argparse namespace from harness/cli.py)."""
    report = run_checks(root=args.root, waivers_path=args.waivers,
                        strict=args.strict,
                        use_cache=not getattr(args, "no_cache",
                                              False))
    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        s = report["summary"]
        c = s["cache"]
        cache_note = (f", cache {c['hits']} hit/{c['misses']} miss"
                      if c["enabled"] else ", cache off")
        print(f"tuplewise check: {s['files_analyzed']} files, "
              f"{s['findings_total']} findings "
              f"({s['waived']} waived, {s['unwaived']} unwaived)"
              f"{cache_note}")
        for f in report["findings"]:
            print(f"  {f['rule']}: {f['file']}:{f['line']} "
                  f"[{f['symbol']}]\n    {f['message']}")
        if report.get("waiver_error"):
            print(f"  waiver file error: {report['waiver_error']}",
                  file=sys.stderr)
        for w in report["unused_waivers"]:
            print(f"  stale waiver (matched nothing): {w['rule']} "
                  f"{w['file']} [{w['symbol']}] "
                  f"(waivers.toml:{w['line']})")
        if report["dead_symbols"]:
            print(f"  note: {len(report['dead_symbols'])} unreferenced "
                  "public symbols (warn-only; see --json)")
        print("OK" if report["ok"] else "FAIL")
    return 0 if report["ok"] else 1
