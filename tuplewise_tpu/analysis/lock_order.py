"""Pass 1 — lock-order / thread-discipline [ISSUE 12 tentpole].

Extracts the static lock-acquisition graph across the whole package:

* **lock identities** — ``self.X = threading.Lock()/RLock()/Condition``
  become ``Class.X``; a ``Condition(self.Y)`` aliases to ``Class.Y``
  (same underlying mutex); module-level locks become ``module.X``;
  ``with self.q.mutex`` is the queue's internal mutex ``Class.q.mutex``.
* **order edges** — ``with A: ... with B:`` (directly nested, or
  through calls resolved via the class/attribute type map) add edge
  A -> B. A cycle in that graph is an acquisition-order inversion —
  two threads taking the same pair of locks in opposite orders can
  deadlock (rule ``lock-order-cycle``).
* **blocking ops under a lock** (rule ``lock-held-blocking``) — inside
  a ``with <lock>`` block, directly or through resolved repo calls:

    - unbounded ``Queue.put/get`` (no timeout, not ``_nowait``) on
      attributes typed ``queue.Queue``
    - ``time.sleep``
    - ``Thread.join`` / ``Queue.join`` without timeout
    - ``Future.result()`` without timeout
    - ``os.fsync``
    - device dispatch: calls into the jitted/Pallas count layer
      (``parallel.sharded_counts`` / ``ops.pallas_counts`` /
      ``_jit_*_fn`` factories) — the class of pause behind the PR 3
      block-policy shutdown hazard and the PR 11 deadline hole.

Intentional holds (e.g. the index cv held across the count dispatch —
that lock IS the statistic's consistency boundary) are waived in
``analysis/waivers.toml`` with written justification, never silenced
in code.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleInfo, ModuleSet, call_name, dotted,
)

_LOCK_CTORS = ("threading.Lock", "threading.RLock",
               "threading.Condition", "Lock", "RLock", "Condition")
_QUEUE_CTORS = ("queue.Queue", "Queue", "queue.LifoQueue",
                "queue.PriorityQueue")
_THREAD_CTORS = ("threading.Thread", "Thread")

# call targets that ARE device dispatch (jitted / Pallas layer);
# calling the value of a ``*_fn`` jit factory is detected structurally
_DISPATCH_NAMES = {"sharded_counts", "place_base", "signed_pair_counts",
                   "tenant_pack_counts", "sharded_major_merge",
                   "place_tenant_pack", "pallas_call"}


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


class _ClassModel:
    """Lock/queue/thread attribute typing for one class."""

    def __init__(self, ms: ModuleSet, mi: ModuleInfo, cname: str):
        self.ms = ms
        self.mi = mi
        self.cname = cname
        self.locks: Dict[str, str] = {}     # attr -> lock id
        self.queues: Set[str] = set()
        self.threads: Set[str] = set()
        self.attr_class: Dict[str, str] = {}  # attr -> repo class name
        for attr, ctor in mi.attr_ctors.get(cname, {}).items():
            if ctor in _LOCK_CTORS:
                self.locks[attr] = f"{cname}.{attr}"
            elif ctor in _QUEUE_CTORS:
                self.queues.add(attr)
            elif ctor in _THREAD_CTORS:
                self.threads.add(attr)
            else:
                if ctor.startswith("self."):
                    # self._wal = self._open_wal(): type through the
                    # factory method's return expression, one level
                    meth = mi.classes.get(cname, {}).get(
                        ctor[len("self."):])
                    if meth is not None:
                        for st in ast.walk(meth):
                            if isinstance(st, ast.Return) \
                                    and isinstance(st.value, ast.Call):
                                ctor = call_name(st.value) or ctor
                                break
                rc = ms.resolve_class(mi, ctor)
                if rc is not None:
                    self.attr_class[attr] = rc
        # Condition(self.X) aliases to the lock it wraps
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            cn = call_name(node.value)
            if cn not in ("threading.Condition", "Condition"):
                continue
            tgt = dotted(node.targets[0]) if node.targets else None
            if not (tgt and tgt.startswith("self.")):
                continue
            attr = tgt[len("self."):]
            if node.value.args:
                arg = dotted(node.value.args[0])
                if arg and arg.startswith("self."):
                    wrapped = arg[len("self."):]
                    if wrapped in self.locks:
                        self.locks[attr] = self.locks[wrapped]
                        continue
                # Condition(threading.RLock()) and friends
            self.locks.setdefault(attr, f"{cname}.{attr}")

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self."):
            attr = d[len("self."):]
            if attr in self.locks:
                return self.locks[attr]
            if attr.endswith(".mutex"):
                return f"{self.cname}.{attr}"
        return None


class _Analysis:
    def __init__(self, ms: ModuleSet):
        self.ms = ms
        self.models: Dict[Tuple[str, str], _ClassModel] = {}
        # function key -> set of lock ids it (transitively) acquires
        self.acquires: Dict[Tuple[str, str, str], Set[str]] = {}
        # function key -> [(category, detail, line)] blocking ops
        self.blocking: Dict[Tuple[str, str, str],
                            List[Tuple[str, str, int]]] = {}
        self.calls: Dict[Tuple[str, str, str],
                         Set[Tuple[str, str, str]]] = {}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        self.known_funcs: Set[Tuple[str, str, str]] = set()

    def model(self, path: str, cname: str) -> _ClassModel:
        key = (path, cname)
        if key not in self.models:
            self.models[key] = _ClassModel(
                self.ms, self.ms.modules[path], cname)
        return self.models[key]

    # -------------------------------------------------------------- #
    def resolve_call(self, path: str, cls: Optional[str],
                     call: ast.Call, prefix: str = ""
                     ) -> Optional[Tuple[str, str, str]]:
        """Map a call to a (path, class, qualname) key inside the
        corpus, through self-methods, typed self-attributes, local and
        nested functions, and imported repo functions. ``prefix`` is
        the enclosing function's qualname, so a bare call to a nested
        def (the healer's ``attempt`` closures) resolves too."""
        mi = self.ms.modules[path]
        cn = call_name(call)
        if cn is None:
            return None
        if "." not in cn and prefix:
            nested = (path, cls or "", f"{prefix}.{cn}")
            if nested in self.acquires or nested in self.known_funcs:
                return nested
        if cn.startswith("self.") and cls is not None:
            rest = cn[len("self."):]
            if "." not in rest:
                if rest in mi.classes.get(cls, {}):
                    return (path, cls, f"{cls}.{rest}")
                return None
            attr, meth = rest.split(".", 1)
            if "." in meth:
                return None
            model = self.model(path, cls)
            tcls = model.attr_class.get(attr)
            if tcls is not None:
                tpath, methods = self.ms.class_defs[tcls]
                if meth in methods:
                    return (tpath, tcls, f"{tcls}.{meth}")
            return None
        if "." not in cn:
            if cn in mi.functions:
                return (path, "", cn)
            if cls is not None and cn in mi.classes.get(cls, {}):
                return (path, cls, f"{cls}.{cn}")
            resolved = self.ms.resolve_import(mi, cn)
            if resolved is not None:
                tpath, sym = resolved
                tmi = self.ms.modules.get(tpath)
                if tmi is not None and sym in tmi.functions:
                    return (tpath, "", sym)
        return None

    # -------------------------------------------------------------- #
    def direct_blocking(self, path: str, cls: Optional[str],
                        call: ast.Call
                        ) -> Optional[Tuple[str, str]]:
        """(category, detail) when this call is itself a blocking op."""
        cn = call_name(call)
        if cn is None:
            # _jit_count_fn(bb, qb)(base, q): calling the value a jit
            # factory returned IS the dispatch (factories follow the
            # *_fn naming convention, enforced by fixtures)
            if isinstance(call.func, ast.Call):
                inner = call_name(call.func)
                if inner and inner.split(".")[-1].endswith("_fn"):
                    return ("device_dispatch", inner)
            return None
        leaf = cn.split(".")[-1]
        if cn in ("time.sleep", "sleep") and cn.startswith("time."):
            return ("sleep", cn)
        if cn == "os.fsync":
            return ("fsync", cn)
        if leaf == "result" and not call.args \
                and not _has_kw(call, "timeout"):
            return ("future_result", cn)
        if leaf == "join" and not call.args \
                and not _has_kw(call, "timeout"):
            # Thread.join()/Queue.join() without bound; plain
            # "sep".join(...) always takes an argument, so zero-arg
            # join is a synchronization join
            return ("join", cn)
        if leaf in ("put", "get") and cn.startswith("self.") \
                and not _has_kw(call, "timeout"):
            parts = cn.split(".")
            if len(parts) == 3 and cls is not None:
                model = self.model(path, cls)
                if parts[1] in model.queues:
                    if any(isinstance(a, ast.Constant)
                           and a.value is False
                           for a in call.args[1:2]):
                        return None
                    return ("queue_" + leaf, cn)
        if leaf in _DISPATCH_NAMES:
            if cn.startswith("self."):
                return None
            return ("device_dispatch", cn)
        return None

    # -------------------------------------------------------------- #
    def scan_function(self, path: str, fi) -> None:
        key = (path, fi.cls or "", fi.qualname)
        acq: Set[str] = set()
        blocking: List[Tuple[str, str, int]] = []
        calls: Set[Tuple[str, str, str]] = set()
        mi = self.ms.modules[path]
        model = self.model(path, fi.cls) if fi.cls else None

        def lock_of(item: ast.withitem) -> Optional[str]:
            if model is not None:
                lid = model.lock_id(item.context_expr)
                if lid is not None:
                    return lid
            d = dotted(item.context_expr)
            if d is not None and d in self.module_locks.get(path, {}):
                return self.module_locks[path][d]
            return None

        def walk(node: ast.AST) -> None:
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    continue     # nested defs analyzed separately;
                    # callback REFERENCES to them are linked below.
                    # Lambda bodies are walked inline: a lambda handed
                    # to healer.run / _fused_counts executes under
                    # whatever the caller holds.
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        lid = lock_of(item)
                        if lid is not None:
                            acq.add(lid)
                if isinstance(sub, ast.Call):
                    b = self.direct_blocking(path, fi.cls, sub)
                    if b is not None:
                        blocking.append((b[0], b[1], sub.lineno))
                    r = self.resolve_call(path, fi.cls, sub,
                                          prefix=fi.qualname)
                    if r is not None and r != key:
                        calls.add(r)
                    # a nested def passed as a callback (the healer's
                    # ``attempt`` protocol) runs under the caller's
                    # locks — link it as if called here
                    for a in list(sub.args) + [k.value for k in
                                               sub.keywords]:
                        if isinstance(a, ast.Name):
                            cand = (path, fi.cls or "",
                                    f"{fi.qualname}.{a.id}")
                            if cand in self.known_funcs \
                                    and cand != key:
                                calls.add(cand)
                walk(sub)

        # start at the function node itself so a With that IS the
        # first statement registers (it appears as a CHILD of the
        # FunctionDef — the walk detects With nodes as children)
        walk(fi.node)
        self.acquires[key] = acq
        self.blocking[key] = blocking
        self.calls[key] = calls

    # -------------------------------------------------------------- #
    def closure(self, mapping: Dict, merge) -> Dict:
        """Fixpoint over the call graph: propagate callees' sets into
        callers."""
        out = {k: merge(v, None) for k, v in mapping.items()}
        changed = True
        while changed:
            changed = False
            for key, callees in self.calls.items():
                cur = out.get(key)
                if cur is None:
                    continue
                for callee in callees:
                    sub = out.get(callee)
                    if not sub:
                        continue
                    before = len(cur)
                    cur = merge(cur, sub)
                    if len(cur) != before:
                        out[key] = cur
                        changed = True
        return out


def build_analysis(ms: ModuleSet) -> Tuple["_Analysis", List]:
    """Shared setup for this pass AND the guard-inference pass
    [ISSUE 13]: module-level lock identities, per-function scan
    (acquisitions, blocking ops, resolved calls). Returns the
    populated analysis plus the ``(path, FunctionInfo)`` list."""
    an = _Analysis(ms)
    # module-level locks
    for path, mi in ms.modules.items():
        mod_locks: Dict[str, str] = {}
        for node in mi.tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                cn = call_name(node.value)
                if cn in _LOCK_CTORS:
                    for t in node.targets:
                        d = dotted(t)
                        if d:
                            mod_locks[d] = \
                                f"{ms.module_name(path)}.{d}"
        an.module_locks[path] = mod_locks

    funcs = []
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            funcs.append((path, fi))
            an.known_funcs.add((path, fi.cls or "", fi.qualname))
    for path, fi in funcs:
        an.scan_function(path, fi)
    return an, funcs


def run(ms: ModuleSet) -> List[Finding]:
    an, funcs = build_analysis(ms)

    # transitive acquisitions and blocking ops
    acq_star = an.closure(
        an.acquires,
        lambda cur, sub: set(cur) | (set(sub) if sub else set()))
    blk_star = an.closure(
        an.blocking,
        lambda cur, sub: list(dict.fromkeys(
            list(cur) + (list(sub) if sub else []))))

    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], Tuple[str, str, int]] = {}

    for path, fi in funcs:
        key = (path, fi.cls or "", fi.qualname)
        mi = ms.modules[path]
        model = an.model(path, fi.cls) if fi.cls else None

        def lock_of(item: ast.withitem) -> Optional[str]:
            if model is not None:
                lid = model.lock_id(item.context_expr)
                if lid is not None:
                    return lid
            d = dotted(item.context_expr)
            return an.module_locks.get(path, {}).get(d)

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    continue
                now = held
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        lid = lock_of(item)
                        if lid is not None:
                            for h in now:
                                if h != lid:
                                    edges.setdefault(
                                        (h, lid),
                                        (path, fi.qualname,
                                         sub.lineno))
                            now = now + (lid,)
                if isinstance(sub, ast.Call) and held:
                    hits = []
                    b = an.direct_blocking(path, fi.cls, sub)
                    if b is not None:
                        hits.append((b[0], b[1], sub.lineno, ""))
                    r = an.resolve_call(path, fi.cls, sub,
                                        prefix=fi.qualname)
                    if r is not None:
                        for cat, detail, line in blk_star.get(r, ()):
                            hits.append((cat, detail, sub.lineno,
                                         f" via {r[2]}"))
                        for lid in acq_star.get(r, ()):
                            for h in held:
                                if h != lid:
                                    edges.setdefault(
                                        (h, lid),
                                        (path, fi.qualname,
                                         sub.lineno))
                    for cat, detail, line, via in hits:
                        sym = (f"{fi.qualname}::{held[-1]}"
                               f"::{cat}")
                        findings.append(Finding(
                            "lock-held-blocking", path, line, sym,
                            f"{fi.qualname} holds {held[-1]} across "
                            f"{cat} ({detail}{via})"))
                walk(sub, now)

        walk(fi.node, ())

    # acquisition-order cycles over the edge graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    for cyc in _cycles(graph):
        a, b = cyc[0], cyc[1 % len(cyc)]
        path, func, line = edges.get((a, b), ("", "?", 0))
        findings.append(Finding(
            "lock-order-cycle", path or "<graph>", line,
            "->".join(sorted(set(cyc))),
            "lock acquisition-order cycle: "
            + " -> ".join(cyc + [cyc[0]])))

    # dedupe lock-held-blocking by fingerprint (one finding per
    # function x lock x category — chains repeat per call site)
    seen = set()
    out = []
    for f in findings:
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        out.append(f)
    return out


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycle detection via SCCs (every SCC with a cycle is
    reported once, as some cycle through it)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on: Set[str] = set()
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or node in graph.get(node, ()):
                    sccs.append(list(reversed(scc)))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs
