"""Pass — integer-exactness + int32 overflow certification
[ISSUE 13 tentpole].

Every bit-identity claim in this repo (sharded vs single-host, kernel
vs XLA, fleet vs independents) rests on ONE invariant the other passes
cannot see: win counts stay on an integer path end-to-end, so psum'd
per-shard sums are exact. Two analyses enforce it:

**1. Float-taint of the wins2 accumulators** (int lattice over the
dataflow substrate). An abstract interpretation chases every value
through assignments, calls, returns and attribute reads with the
lattice

    pyint  — Python int (arbitrary precision: the wins2 contract)
    int    — int64-family host integer (np.searchsorted, .astype(i64))
    int32  — device-width integer (jnp results, .astype(int32))
    float  — float-tainted (float literals, true division, np
             default-dtype constructors, .astype(float), 0.5 * x)

and judges every store/augmented-store into a ``*wins2*`` attribute:

* ``count-float-taint``        — a float-tainted value flows into a
  wins2 accumulator: the statistic silently stops being exact.
* ``count-narrow-accumulator`` — a raw int32 device value flows in
  without widening (``int()`` / ``.astype(np.int64)``): host
  accumulation inherits the device width and can wrap.

**2. Static overflow certification of int32 device accumulators.**
Every ``@lru_cache`` jit/Pallas factory whose compiled body
accumulates int32 counts is classified structurally (psum present?
run-tuple/run-loop multiplicity? additive rank arithmetic? planned
positions with the int32 sentinel?) and gets a symbolic worst-case
bound in terms of the compile-ladder maxima (S, cap, q_bucket,
t_bucket, max_runs). The evaluated per-accumulator bound table is the
machine-readable **overflow certificate** (report key
``overflow_certificate``; committed baseline
``analysis/exactness_bounds.toml`` — the CI gate diffs them, so a
ladder-maximum bump that breaks int32 safety fails with the violating
bound named). A factory the classifier cannot bound is a finding
(``overflow-unproved``), as is a bound exceeding 2^31 − 1 at the
declared maxima (``overflow-int32``).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted,
)
from tuplewise_tpu.analysis import dataflow

INT32_LIMIT = 2 ** 31 - 1

#: The certified compile-ladder envelope. These are the maxima the
#: ladders are allowed to reach (DESIGN §17); the committed baseline
#: (analysis/exactness_bounds.toml) must declare the SAME values, and
#: the CI gate re-derives every bound from them — bump one here or
#: there without the other and the gate fails.
DEFAULT_MAXIMA: Dict[str, int] = {
    "S": 256,            # mesh width
    "cap": 2 ** 21,      # per-shard row bucket (base/delta/tomb caps)
    "q_bucket": 2 ** 16,  # query-block bucket
    "t_bucket": 2 ** 16,  # tenant-slot bucket
    "max_runs": 3,       # signed runs per side: base + delta + tomb
}

# factory parameter name -> maxima key ("cap-like" params bound the
# searched run length; q/t buckets bound their own axes)
_PARAM_MAXIMA = (
    ("t_bucket", "t_bucket"),
    ("q_bucket", "q_bucket"),
    ("qb", "q_bucket"),
    ("cap", "cap"),         # cap, caps, cap_pos, cap_base, delta_cap...
    ("bucket", "cap"),      # base_bucket, bucket
    ("chunk", "cap"),
)


# --------------------------------------------------------------------- #
# int lattice                                                            #
# --------------------------------------------------------------------- #

PYINT = "pyint"
INT = "int"        # int64-family host value/array
INT32 = "int32"    # device-width integer
FLOAT = "float"    # float-tainted

_INTS = (PYINT, INT, INT32)

_INT64_CTORS = {"np.searchsorted", "numpy.searchsorted"}
_INT32_CTORS = {"jnp.searchsorted", "jax.numpy.searchsorted"}
_FLOAT_CTORS = {"np.zeros", "np.ones", "np.full", "np.empty",
                "jnp.zeros", "jnp.ones", "jnp.full",
                "np.linspace", "jnp.linspace"}
_SHAPE_PRESERVING = {"ravel", "reshape", "copy", "flatten",
                     "squeeze", "transpose", "clip"}


def _dtype_value(node: Optional[ast.AST]) -> Optional[str]:
    """Lattice value named by a dtype expression, if recognizable."""
    if node is None:
        return None
    d = dotted(node)
    if d is None:
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            d = node.value
        else:
            return None
    leaf = d.split(".")[-1]
    if leaf in ("int64", "intp", "int_"):
        return INT
    if leaf in ("int32", "int16", "int8"):
        return INT32
    if leaf in ("float16", "float32", "float64", "bfloat16", "float"):
        return FLOAT
    if leaf == "int":
        return PYINT
    return None


class IntDomain(dataflow.Domain):
    """The integer-exactness lattice. ``top`` = unknown (NOT tainted:
    the pass under-approximates rather than spraying false floats)."""

    top = None

    def join(self, a, b):
        if a == b:
            return a
        if a is None or b is None:
            return None
        if FLOAT in (a, b):
            return FLOAT
        if INT32 in (a, b):
            return INT32
        return INT

    def const(self, value):
        if isinstance(value, bool):
            return PYINT
        if isinstance(value, int):
            return PYINT
        if isinstance(value, float):
            return FLOAT
        return None

    def binop(self, op, left, right):
        if isinstance(op, ast.Div):
            return FLOAT
        if left is None and right is None:
            return None
        if FLOAT in (left, right):
            return FLOAT
        if left is None or right is None:
            return None
        if INT32 in (left, right):
            return INT32
        if left == PYINT and right == PYINT:
            return PYINT
        return INT

    def call(self, cn, node, argvals, kwvals, recv=None):
        if cn is None:
            return None
        leaf = cn.split(".")[-1]
        if cn == "len":
            return PYINT
        if cn == "int":
            return PYINT
        if cn == "float":
            return FLOAT
        if cn in _INT64_CTORS:
            return INT
        if cn in _INT32_CTORS:
            return INT32
        if leaf == "astype":
            v = _dtype_value(node.args[0]) if node.args else \
                _dtype_value(next((k.value for k in node.keywords
                                   if k.arg == "dtype"), None))
            return v
        if cn in _FLOAT_CTORS or leaf in ("arange", "asarray",
                                          "array", "zeros", "full",
                                          "ones", "empty"):
            v = _dtype_value(next(
                (k.value for k in node.keywords if k.arg == "dtype"),
                None))
            if v is not None:
                return v
            if cn in _FLOAT_CTORS:
                return FLOAT     # numpy default dtype is float64
            return None
        if leaf in ("sum", "cumsum", "prod", "max", "min", "dot"):
            return recv
        if leaf in _SHAPE_PRESERVING:
            return recv
        if leaf in ("searchsorted",):
            # method form: arr.searchsorted(...) — host numpy
            return INT
        if leaf in ("mean", "std", "var", "item"):
            return FLOAT if leaf != "item" else recv
        if leaf in ("concatenate", "stack", "hstack", "vstack",
                    "where", "sort"):
            vals = [v for v in argvals if v is not None]
            if len(argvals) == 1 and isinstance(argvals[0],
                                                dataflow.Seq):
                vals = [v for v in argvals[0].elts if v is not None]
            out = None
            for v in vals:
                out = v if out is None else self.join(out, v)
            return out
        return None

    def attribute(self, base, attr):
        if attr == "size":
            return PYINT
        return None

    def subscript(self, base, index):
        # an element/slice of an int array is int-family; of a float
        # array float — the array value IS the element value here
        return base

    def unaryop(self, op, operand):
        return operand


# --------------------------------------------------------------------- #
# float-taint of the wins2 accumulators                                  #
# --------------------------------------------------------------------- #

def _is_wins2_target(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    if "wins2" in leaf:
        return d
    return None


def taint_findings(ms: ModuleSet,
                   engine: Optional[dataflow.Engine] = None
                   ) -> List[Finding]:
    if engine is None:
        engine = dataflow.Engine(ms, IntDomain())
    findings: List[Finding] = []
    seen: Set[str] = set()

    for key, node in engine.graph.functions.items():
        path, cls, qual = key
        hits: List[Tuple[str, int, Any]] = []

        def hook(walker, st, _hits=hits):
            target = value = None
            if isinstance(st, ast.AugAssign):
                target = _is_wins2_target(st.target)
                if target is not None:
                    value = walker.eval(st.value)
            elif isinstance(st, ast.Assign) and len(st.targets) == 1:
                target = _is_wins2_target(st.targets[0])
                if target is not None:
                    value = walker.eval(st.value)
            if target is not None:
                _hits.append((target, st.lineno, value))

        has_wins2 = any(
            isinstance(n, (ast.Assign, ast.AugAssign))
            and _is_wins2_target(
                n.target if isinstance(n, ast.AugAssign)
                else n.targets[0] if len(n.targets) == 1 else n)
            for n in ast.walk(node)
            if isinstance(n, (ast.Assign, ast.AugAssign)))
        if not has_wins2:
            continue
        engine.trace_function(key, hook)
        for target, line, value in hits:
            if value == FLOAT:
                f = Finding(
                    "count-float-taint", path, line,
                    f"{qual}::{target}",
                    f"{qual} stores a float-tainted value into the "
                    f"integer win-count accumulator {target} — the "
                    "statistic silently stops being exact (psum'd "
                    "shard sums, kernel-vs-XLA parity and every "
                    "bit-identity claim depend on the pure-integer "
                    "path, DESIGN §15)")
            elif value == INT32:
                f = Finding(
                    "count-narrow-accumulator", path, line,
                    f"{qual}::{target}",
                    f"{qual} accumulates a raw int32 device value "
                    f"into {target} without widening — host "
                    "accumulation inherits the device width and can "
                    "wrap; widen with int() or .astype(np.int64) "
                    "first")
            else:
                continue
            if f.fingerprint not in seen:
                seen.add(f.fingerprint)
                findings.append(f)
    return findings


# --------------------------------------------------------------------- #
# int32 overflow certification                                           #
# --------------------------------------------------------------------- #

def _param_bound(name: str) -> Optional[str]:
    low = name.lower()
    for pat, key in _PARAM_MAXIMA:
        if pat in low:
            return key
    return None


def _factory_features(node: ast.AST) -> Dict[str, Any]:
    """Structural features of one factory body that drive the bound
    rules."""
    feats = {"int32": False, "searchsorted": False, "psum": False,
             "run_loop": False, "compare_count": False,
             "axis_index": False, "adds": 0, "cumsum": False,
             "planned_pos": False}
    src_names: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            if sub.attr in ("int32", "int16"):
                feats["int32"] = True
        if isinstance(sub, ast.Name):
            src_names.add(sub.id)
        if isinstance(sub, ast.Call):
            cn = call_name(sub) or ""
            leaf = cn.split(".")[-1]
            if leaf == "searchsorted":
                feats["searchsorted"] = True
            elif leaf == "psum":
                feats["psum"] = True
            elif leaf == "axis_index":
                feats["axis_index"] = True
            elif leaf == "cumsum":
                feats["cumsum"] = True
            elif leaf == "astype" and sub.args:
                if _dtype_value(sub.args[0]) == INT32:
                    feats["int32"] = True
        if isinstance(sub, ast.For):
            for inner in ast.walk(sub):
                if isinstance(inner, ast.Call):
                    lf = (call_name(inner) or "").split(".")[-1]
                    if lf in ("searchsorted", "astype", "add"):
                        feats["run_loop"] = True
        if isinstance(sub, ast.Compare) and sub.ops \
                and isinstance(sub.ops[0], (ast.Lt, ast.LtE)):
            feats["compare_count"] = True
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add):
            feats["adds"] += 1
    feats["planned_pos"] = "pos" in src_names and feats["cumsum"]
    return feats


def _expand_helpers(mi, node: ast.AST,
                    params: List[str]) -> Tuple[List[ast.AST],
                                                List[str]]:
    """The factory body plus every same-module helper it reaches (the
    Pallas builders put the kernel in ``_x_call``/``_x_kernel``
    helpers, not the lru body) — features and parameter names are
    judged over the union."""
    nodes = [node]
    names = list(params)
    seen = {getattr(node, "name", "")}
    frontier = [node]
    for _depth in range(3):
        nxt = []
        for n in frontier:
            for sub in ast.walk(n):
                # callees AND bare references: the Pallas builders
                # hand the kernel fn to functools.partial/pallas_call
                # as an argument, not a call
                if isinstance(sub, ast.Name):
                    cands = (sub.id,)
                elif isinstance(sub, ast.Call):
                    cn = call_name(sub)
                    cands = (cn, (cn or "").split(".")[0])
                else:
                    continue
                for cand in cands:
                    if cand and cand in mi.functions \
                            and cand not in seen:
                        helper = mi.functions[cand]
                        seen.add(cand)
                        nodes.append(helper)
                        names.extend(a.arg
                                     for a in helper.args.args)
                        nxt.append(helper)
        frontier = nxt
    return nodes, names


def _merge_features(nodes: List[ast.AST]) -> Dict[str, Any]:
    feats: Optional[Dict[str, Any]] = None
    for n in nodes:
        f = _factory_features(n)
        if feats is None:
            feats = f
        else:
            for k, v in f.items():
                if k == "adds":
                    feats[k] += v
                else:
                    feats[k] = feats[k] or v
    return feats or {}


def _classify(name: str, node: ast.AST,
              params: List[str],
              feats: Optional[Dict[str, Any]] = None
              ) -> Optional[Dict[str, Any]]:
    """(category, symbolic bound terms) for one lru_cache factory, or
    None when it has no int32 accumulator to certify."""
    if feats is None:
        feats = _factory_features(node)
    # bare `<` comparisons are everywhere; only comparison COUNTING
    # (compare + int32 accumulation) or searchsorted is count-shaped
    counts = feats["searchsorted"] \
        or (feats["compare_count"] and feats["int32"])
    if not (feats["int32"] or counts):
        return None
    cap_keys = sorted({k for k in (
        _param_bound(p) for p in params) if k is not None}
        - {"q_bucket", "t_bucket"})
    cap_key = cap_keys[0] if cap_keys else "cap"
    has_runs_tuple = any(p in ("caps", "signs", "runs") or
                         p.endswith("caps") for p in params)
    if feats["planned_pos"]:
        # rank arithmetic against host-planned positions: the int32
        # padding sentinel (iinfo.max) is the worst-case magnitude BY
        # DESIGN — planned ranks themselves stay <= S*cap
        return {"category": "planned-rank",
                "expr": "iinfo(int32).max sentinel (planned "
                        "positions; ranks <= S*cap)",
                "terms": [("const", INT32_LIMIT)]}
    if counts:
        terms: List[Tuple[str, Any]] = []
        if feats["psum"]:
            terms.append(("max", "S"))
        if has_runs_tuple or feats["run_loop"]:
            terms.append(("max", "max_runs"))
        extra_adds = 0
        if not (has_runs_tuple or feats["run_loop"]):
            # additive index construction outside a run loop
            # (jc + searchsorted(...)): each add contributes one more
            # cap-bounded term
            extra_adds = 1 if feats["cumsum"] else 0
        terms.append(("max", cap_key))
        cat = "psum-count" if feats["psum"] else "count"
        return {"category": cat, "terms": terms,
                "extra_terms": 1 + extra_adds}
    # int32 without comparison counting: index/scatter arithmetic
    # bounded by its widest bucket axis
    axes = sorted({k for k in (_param_bound(p) for p in params)
                   if k is not None})
    if not axes:
        return None
    return {"category": "index",
            "terms": [("max", a) for a in axes[:1]],
            "extra_terms": 2 if feats["adds"] else 1}


def certificates(ms: ModuleSet,
                 maxima: Optional[Dict[str, int]] = None
                 ) -> Dict[str, Any]:
    """The overflow certificate: per-factory worst-case int32 bounds
    at the compile-ladder maxima. ``{"maxima": ..., "bounds": [...],
    "ok": bool}`` — each bound entry carries the factory, category,
    symbolic expression, evaluated bound, and its verdict."""
    from tuplewise_tpu.analysis.compile_ladder import _is_lru

    maxima = dict(DEFAULT_MAXIMA if maxima is None else maxima)
    entries: List[Dict[str, Any]] = []
    unproved: List[Tuple[str, str, int]] = []
    for path, mi in sorted(ms.modules.items()):
        for fi in mi.iter_functions():
            node = fi.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not _is_lru(node):
                continue
            nodes, params = _expand_helpers(
                mi, node, [a.arg for a in node.args.args])
            feats = _merge_features(nodes)
            cls = _classify(node.name, node, params, feats=feats)
            if cls is None:
                if feats.get("int32"):
                    unproved.append((path, node.name, node.lineno))
                continue
            if cls["category"] == "planned-rank":
                bound = INT32_LIMIT
                expr = cls["expr"]
            else:
                bound = 1
                parts = []
                for kind, term in cls["terms"]:
                    v = maxima.get(term, None)
                    if v is None:
                        unproved.append((path, node.name, node.lineno))
                        bound = None
                        break
                    bound *= v
                    parts.append(term)
                if bound is None:
                    continue
                extra = cls.get("extra_terms", 1)
                bound *= extra
                expr = " * ".join(parts) + \
                    (f" * {extra}" if extra > 1 else "")
            entries.append({
                "factory": node.name,
                "file": path,
                "line": node.lineno,
                "category": cls["category"],
                "expr": expr,
                "bound": bound,
                "ok": bound <= INT32_LIMIT,
            })
    entries.sort(key=lambda e: (e["file"], e["factory"]))
    return {
        "maxima": maxima,
        "limit": INT32_LIMIT,
        "bounds": entries,
        "unproved": [{"file": p, "factory": f, "line": ln}
                     for p, f, ln in sorted(unproved)],
        "ok": all(e["ok"] for e in entries) and not unproved,
    }


def overflow_findings(cert: Dict[str, Any]) -> List[Finding]:
    findings: List[Finding] = []
    for e in cert["bounds"]:
        if not e["ok"]:
            findings.append(Finding(
                "overflow-int32", e["file"], e["line"], e["factory"],
                f"int32 accumulator in {e['factory']} has worst-case "
                f"magnitude {e['bound']} ( = {e['expr']} at the "
                "declared compile-ladder maxima) > 2^31-1 — shrink "
                "the ladder envelope in analysis/exactness_bounds."
                "toml or widen the accumulator to int64"))
    for u in cert["unproved"]:
        findings.append(Finding(
            "overflow-unproved", u["file"], u["line"], u["factory"],
            f"jit factory {u['factory']} builds int32 values the "
            "overflow classifier cannot bound — add a rule (or "
            "restructure the accumulator) so the certificate covers "
            "it; an unbounded int32 accumulator is exactly how a "
            "ladder bump corrupts counts silently"))
    return findings


# --------------------------------------------------------------------- #
# baseline file (committed envelope)                                     #
# --------------------------------------------------------------------- #

class BaselineError(ValueError):
    """exactness_bounds.toml is malformed."""


def parse_baseline(text: str) -> Dict[str, Any]:
    """Parse the committed envelope: one ``[maxima]`` table plus
    ``[[bound]]`` entries — the same deliberate TOML subset as
    waivers.toml (no tomllib in this container)."""
    maxima: Dict[str, int] = {}
    bounds: List[Dict[str, Any]] = []
    current: Optional[Dict[str, Any]] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[maxima]":
            current = maxima
            continue
        if line == "[[bound]]":
            current = {}
            bounds.append(current)
            continue
        if line.startswith("["):
            raise BaselineError(
                f"exactness_bounds.toml:{lineno}: only [maxima] and "
                f"[[bound]] tables are supported, got {line!r}")
        if "=" not in line or current is None:
            raise BaselineError(
                f"exactness_bounds.toml:{lineno}: expected "
                f"'key = value' inside a table, got {line!r}")
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if val.startswith('"') and val.endswith('"') and len(val) >= 2:
            current[key] = val[1:-1]
        elif val.lstrip("-").isdigit():
            current[key] = int(val)
        else:
            raise BaselineError(
                f"exactness_bounds.toml:{lineno}: value for {key!r} "
                f"must be a string or integer, got {val!r}")
    return {"maxima": maxima, "bounds": bounds}


def compare_to_baseline(cert: Dict[str, Any],
                        baseline_text: str) -> List[str]:
    """Diff the freshly-derived certificate against the committed
    envelope; returns human-readable violations (empty = in sync).
    The gate fails CI on any entry — a ladder bump, a new unproved
    factory, or a bound drift all land here with the bound NAMED."""
    try:
        base = parse_baseline(baseline_text)
    except BaselineError as e:
        return [str(e)]
    errors: List[str] = []
    if base["maxima"] != cert["maxima"]:
        errors.append(
            "ladder maxima drifted: committed "
            f"{base['maxima']} vs derived {cert['maxima']} — "
            "exactness_bounds.toml [maxima] and "
            "analysis/exactness.DEFAULT_MAXIMA must move together")
    by_key = {(b.get("file"), b.get("factory")): b
              for b in base["bounds"]}
    for e in cert["bounds"]:
        k = (e["file"], e["factory"])
        b = by_key.pop(k, None)
        if b is None:
            errors.append(
                f"new int32 accumulator not in the committed "
                f"envelope: {e['factory']} ({e['file']}) bound "
                f"{e['bound']} — re-baseline after review")
            continue
        if int(b.get("bound", -1)) != int(e["bound"]):
            errors.append(
                f"bound drifted for {e['factory']} ({e['file']}): "
                f"committed {b.get('bound')} vs derived {e['bound']} "
                f"( = {e['expr']})")
        if not e["ok"]:
            errors.append(
                f"int32 safety violated: {e['factory']} "
                f"({e['file']}) worst-case {e['bound']} = "
                f"{e['expr']} > 2^31-1")
    for (path, fac) in sorted(k for k in by_key):
        errors.append(
            f"stale baseline entry: {fac} ({path}) no longer derived "
            "— prune it from exactness_bounds.toml")
    for u in cert["unproved"]:
        errors.append(
            f"unproved int32 factory: {u['factory']} ({u['file']})")
    return errors


# --------------------------------------------------------------------- #
# the pass                                                               #
# --------------------------------------------------------------------- #

def run(ms: ModuleSet,
        maxima: Optional[Dict[str, int]] = None) -> List[Finding]:
    engine = dataflow.Engine(ms, IntDomain())
    findings = taint_findings(ms, engine)
    findings.extend(overflow_findings(certificates(ms, maxima)))
    return findings
