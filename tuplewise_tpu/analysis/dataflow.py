"""Shared flow-sensitive dataflow substrate [ISSUE 13 tentpole].

PR 12's passes chased values through exactly ONE local assignment and
resolved calls ad hoc; the first full run's triage traced every
precision gap to that. This module is the replacement substrate the
flow-sensitive tier (``races``, ``exactness``, and the reworked
``compile_ladder``) is built on:

* :func:`build_call_graph` — one interprocedural call graph over the
  corpus: self-methods, attribute-typed calls (``self.index.insert``
  through the class/attribute type map), local + nested functions,
  and imported repo functions. The resolution logic generalizes the
  lock pass's resolver; confidently-resolved edges only, so clients
  under-approximate instead of spraying false positives.

* :class:`Engine` — a forward abstract interpreter parameterized by a
  :class:`Domain`. Per function it walks statements in order
  (branches join, loops iterate to a bounded fixpoint), maintaining a
  name -> abstract-value environment; across functions it computes
  memoized summaries (param values in, joined return value out) with
  cycle cut-off, chases class-attribute writes (``self.x = expr``
  joined over every write site), and tracks NamedTuple/dataclass
  constructor fields so ``plan.pos`` evaluates to what the
  constructor was given.

Domains stay SMALL: abstract values must be hashable and the lattice
finite-height — the engine bounds loop iterations and call depth, so
termination never depends on the domain being clever.
"""

from __future__ import annotations

import ast
import itertools
from typing import Any, Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    FunctionInfo, ModuleInfo, ModuleSet, call_name, dotted,
)

#: (path, class name or "", qualname) — the one function key every
#: layer of the tier shares
FuncKey = Tuple[str, str, str]

_MAX_CALL_DEPTH = 8      # interprocedural evaluation depth
_MAX_LOOP_PASSES = 2     # loop bodies re-evaluated until join stabilizes
_MAX_CALLSITE_JOIN = 12  # call sites joined into a parameter value


class Domain:
    """Abstract-value lattice + transfer functions.

    Subclasses override what they care about; everything defaults to
    ``top`` (= "unknown"), so a domain only models the expressions its
    pass judges. Values MUST be hashable (they key summary memos).
    """

    top: Any = None

    def join(self, a, b):
        if a == b:
            return a
        return self.top

    def const(self, value) -> Any:
        return self.top

    def call(self, cn: Optional[str], node: ast.Call,
             argvals: List[Any], kwvals: Dict[str, Any],
             recv: Any = None) -> Any:
        """Value of a call the engine could NOT resolve in-corpus (or
        a resolved one after summary evaluation returned top). ``cn``
        is the dotted callee name as written, possibly None; ``recv``
        is the receiver's abstract value for method calls
        (``less.sum()`` sees the value of ``less``)."""
        return self.top

    def attribute(self, base: Any, attr: str) -> Any:
        return self.top

    def subscript(self, base: Any, index: Any) -> Any:
        return self.top

    def binop(self, op: ast.AST, left: Any, right: Any) -> Any:
        return self.top

    def unaryop(self, op: ast.AST, operand: Any) -> Any:
        return operand if isinstance(op, ast.USub) else self.top

    def sequence(self, node: ast.AST, elts: List[Any]) -> Any:
        """Value of a Tuple/List/Set display."""
        return self.top


class Struct:
    """A constructor result with known fields (NamedTuple/dataclass):
    ``plan.pos`` evaluates to the value the constructor was given.
    Hashable on sorted items."""

    __slots__ = ("cls", "fields")

    def __init__(self, cls: str, fields: Dict[str, Any]):
        self.cls = cls
        self.fields = fields

    def __eq__(self, other):
        return (isinstance(other, Struct) and other.cls == self.cls
                and other.fields == self.fields)

    def __hash__(self):
        return hash((self.cls, tuple(sorted(
            (k, v) for k, v in self.fields.items()))))

    def __repr__(self):
        return f"Struct({self.cls}, {self.fields})"


class Seq:
    """A tuple/list display with known element values (supports
    unpacking assignment and iteration joins)."""

    __slots__ = ("elts",)

    def __init__(self, elts: Tuple[Any, ...]):
        self.elts = tuple(elts)

    def __eq__(self, other):
        return isinstance(other, Seq) and other.elts == self.elts

    def __hash__(self):
        return hash(self.elts)

    def __repr__(self):
        return f"Seq{self.elts}"


# --------------------------------------------------------------------- #
# class attribute typing (shared with the lock pass's model)             #
# --------------------------------------------------------------------- #

def attr_class_map(ms: ModuleSet, mi: ModuleInfo,
                   cname: str) -> Dict[str, str]:
    """{self-attr -> repo class name} for one class, chasing a
    one-level factory-method return the way the lock pass does."""
    out: Dict[str, str] = {}
    for attr, ctor in mi.attr_ctors.get(cname, {}).items():
        if ctor.startswith("self."):
            meth = mi.classes.get(cname, {}).get(ctor[len("self."):])
            if meth is not None:
                for st in ast.walk(meth):
                    if isinstance(st, ast.Return) \
                            and isinstance(st.value, ast.Call):
                        ctor = call_name(st.value) or ctor
                        break
        rc = ms.resolve_class(mi, ctor)
        if rc is not None:
            out[attr] = rc
    return out


def annotation_class(ms: ModuleSet, mi: ModuleInfo,
                     ann: Optional[ast.AST]) -> Optional[str]:
    """Resolve a parameter/variable annotation to a repo class name
    (string annotations and Optional[X] unwrapped)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        d = dotted(ann.value)
        if d in ("Optional", "typing.Optional"):
            return annotation_class(ms, mi, ann.slice)
        return None
    d = dotted(ann)
    if d is None:
        return None
    return ms.resolve_class(mi, d)


# --------------------------------------------------------------------- #
# call graph                                                             #
# --------------------------------------------------------------------- #

class CallGraph:
    """Resolved corpus call graph + the resolver every client shares."""

    def __init__(self, ms: ModuleSet):
        self.ms = ms
        self.functions: Dict[FuncKey, ast.AST] = {}
        self.infos: Dict[FuncKey, FunctionInfo] = {}
        self.edges: Dict[FuncKey, Set[FuncKey]] = {}
        self._attr_classes: Dict[Tuple[str, str], Dict[str, str]] = {}
        for path, mi in ms.modules.items():
            for fi in mi.iter_functions():
                key = (path, fi.cls or "", fi.qualname)
                self.functions[key] = fi.node
                self.infos[key] = fi
        for key in self.functions:
            self.edges[key] = set()
        for key, node in self.functions.items():
            path, cls, qual = key
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    r = self.resolve_call(path, cls or None, sub,
                                          prefix=qual)
                    if r is not None and r != key:
                        self.edges[key].add(r)

    # ------------------------------------------------------------------ #
    def attr_classes(self, path: str, cname: str) -> Dict[str, str]:
        key = (path, cname)
        if key not in self._attr_classes:
            self._attr_classes[key] = attr_class_map(
                self.ms, self.ms.modules[path], cname)
        return self._attr_classes[key]

    def resolve_call(self, path: str, cls: Optional[str],
                     call: ast.Call, prefix: str = ""
                     ) -> Optional[FuncKey]:
        """Map a call to a corpus function key: nested defs (via the
        enclosing qualname ``prefix``), self-methods, typed
        self-attributes, local defs, imported repo functions, and
        repo-class constructors (-> ``__init__``)."""
        ms = self.ms
        mi = ms.modules[path]
        cn = call_name(call)
        if cn is None:
            return None
        if "." not in cn and prefix:
            nested = (path, cls or "", f"{prefix}.{cn}")
            if nested in self.functions:
                return nested
        if cn.startswith("self.") and cls is not None:
            rest = cn[len("self."):]
            if "." not in rest:
                if rest in mi.classes.get(cls, {}):
                    return (path, cls, f"{cls}.{rest}")
                return None
            attr, meth = rest.split(".", 1)
            if "." in meth:
                return None
            tcls = self.attr_classes(path, cls).get(attr)
            if tcls is not None:
                tpath, methods = ms.class_defs[tcls]
                if meth in methods:
                    return (tpath, tcls, f"{tcls}.{meth}")
            return None
        if "." not in cn:
            if cn in mi.functions:
                return (path, "", cn)
            if cls is not None and cn in mi.classes.get(cls, {}):
                return (path, cls, f"{cls}.{cn}")
            resolved = ms.resolve_import(mi, cn)
            if resolved is not None:
                tpath, sym = resolved
                tmi = ms.modules.get(tpath)
                if tmi is not None and sym in tmi.functions:
                    return (tpath, "", sym)
        return None

    def resolve_constructor(self, path: str,
                            call: ast.Call) -> Optional[str]:
        """Repo class name when the call constructs one, else None."""
        cn = call_name(call)
        if cn is None:
            return None
        return self.ms.resolve_class(self.ms.modules[path], cn)

    def callers(self) -> Dict[FuncKey, Set[Tuple[FuncKey, ast.Call]]]:
        """{callee -> {(caller, call node)}} — parameter-value joins
        need the actual call expressions, not just the edge."""
        out: Dict[FuncKey, Set[Tuple[FuncKey, ast.Call]]] = {}
        for key, node in self.functions.items():
            path, cls, qual = key
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    r = self.resolve_call(path, cls or None, sub,
                                          prefix=qual)
                    if r is not None and r != key:
                        out.setdefault(r, set()).add((key, sub))
        return out


# --------------------------------------------------------------------- #
# the forward abstract interpreter                                       #
# --------------------------------------------------------------------- #

class Engine:
    """Interprocedural forward dataflow over a :class:`Domain`.

    * :meth:`eval_function` — flow-sensitive walk of one function with
      given parameter values; returns the joined return value and
      (optionally) a per-node value map for clients that inspect
      intermediate expressions.
    * :meth:`summary` — memoized interprocedural summary: evaluate the
      callee with the given argument values; recursion and depth are
      cut to ``domain.top``.
    * :meth:`param_values` — join a function's parameter values over
      every resolved call site (the chase that proves e.g. "every
      caller pads this query block to its bucket").
    * class-attribute values: ``self.x`` reads evaluate to the join of
      every ``self.x = ...`` write in the class (two rounds, so writes
      that read other attributes settle).
    """

    def __init__(self, ms: ModuleSet, domain: Domain,
                 graph: Optional[CallGraph] = None):
        self.ms = ms
        self.domain = domain
        self.graph = graph if graph is not None else CallGraph(ms)
        self._summaries: Dict[Tuple[FuncKey, Tuple], Any] = {}
        self._active: Set[FuncKey] = set()
        self._attr_values: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._attrs_ready: Set[Tuple[str, str]] = set()
        self._callers = None
        self._param_memo: Dict[FuncKey, Dict[str, Any]] = {}
        self._param_active: Set[FuncKey] = set()
        self._closure_memo: Dict[FuncKey, Dict[str, Any]] = {}
        self._closure_active: Set[FuncKey] = set()

    # ------------------------------------------------------------------ #
    # class attribute values                                             #
    # ------------------------------------------------------------------ #
    def attr_value(self, path: str, cname: str, attr: str) -> Any:
        key = (path, cname)
        if key not in self._attrs_ready:
            self._attrs_ready.add(key)     # cut self-recursion first
            self._attr_values[key] = self._compute_attrs(path, cname)
        return self._attr_values.get(key, {}).get(attr,
                                                  self.domain.top)

    def _compute_attrs(self, path: str, cname: str) -> Dict[str, Any]:
        mi = self.ms.modules.get(path)
        if mi is None or cname not in mi.classes:
            return {}
        out: Dict[str, Any] = {}
        for _round in range(2):
            for mname, mnode in mi.classes[cname].items():
                key = (path, cname, f"{cname}.{mname}")
                if key not in self.graph.functions:
                    continue
                env = self._entry_env(key, None)
                walker = _FunctionWalk(self, key, env)
                walker.run()
                for attr, val in walker.attr_writes.items():
                    if attr in out:
                        out[attr] = self.domain.join(out[attr], val)
                    else:
                        out[attr] = val
        return out

    # ------------------------------------------------------------------ #
    # parameter joins over call sites                                    #
    # ------------------------------------------------------------------ #
    def param_values(self, key: FuncKey) -> Dict[str, Any]:
        """{param name -> joined abstract value over every resolved
        call site}. Params no site binds (or functions with no known
        callers) default to ``top``."""
        if key in self._param_memo:
            return self._param_memo[key]
        if key in self._param_active or len(self._param_active) > 24:
            return {}
        self._param_active.add(key)
        try:
            if self._callers is None:
                self._callers = self.graph.callers()
            node = self.graph.functions.get(key)
            sites = list(self._callers.get(key, ()))[:_MAX_CALLSITE_JOIN]
            if node is None or not sites \
                    or not isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                self._param_memo[key] = {}
                return {}
            params = [a.arg for a in node.args.args]
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            joined: Dict[str, Any] = {}
            for caller, call in sites:
                env = self._entry_env(caller, None)
                walker = _FunctionWalk(self, caller, env,
                                       stop_at=call)
                walker.run()
                argvals = [walker.eval(a) for a in call.args]
                kwvals = {k.arg: walker.eval(k.value)
                          for k in call.keywords if k.arg}
                bound = dict(zip(params, argvals))
                bound.update({k: v for k, v in kwvals.items()
                              if k in params})
                for p in params:
                    v = bound.get(p, self.domain.top)
                    if p in joined:
                        joined[p] = self.domain.join(joined[p], v)
                    else:
                        joined[p] = v
            self._param_memo[key] = joined
            return joined
        finally:
            self._param_active.discard(key)

    # ------------------------------------------------------------------ #
    # function evaluation + summaries                                    #
    # ------------------------------------------------------------------ #
    def _entry_env(self, key: FuncKey,
                   argvals: Optional[List[Any]],
                   kwvals: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
        node = self.graph.functions[key]
        env: Dict[str, Any] = {}
        args = getattr(node, "args", None)
        if args is None:
            return env
        params = [a.arg for a in args.args]
        vals = list(argvals) if argvals is not None else []
        if params and params[0] in ("self", "cls"):
            env[params[0]] = self.domain.top
            params = params[1:]
        for i, p in enumerate(params):
            env[p] = vals[i] if i < len(vals) else self.domain.top
        if kwvals:
            for k, v in kwvals.items():
                if k in params:
                    env[k] = v
        return env

    def eval_function(self, key: FuncKey,
                      argvals: Optional[List[Any]] = None,
                      kwvals: Optional[Dict[str, Any]] = None) -> Any:
        """Joined return value of ``key`` under the given argument
        values (missing ones default to the call-site join, then
        top)."""
        env = self._entry_env(key, argvals, kwvals)
        if argvals is None and kwvals is None:
            for p, v in self.param_values(key).items():
                if env.get(p, self.domain.top) is self.domain.top:
                    env[p] = v
        walker = _FunctionWalk(self, key, env)
        walker.run()
        return walker.returns

    def closure_env(self, key: FuncKey) -> Dict[str, Any]:
        """Free-variable environment of a NESTED def: the enclosing
        function's final env (the healer's ``attempt`` closures read
        the padded query blocks their enclosing method built)."""
        path, cls, qual = key
        if "." not in qual:
            return {}
        parent = (path, cls, qual.rsplit(".", 1)[0])
        if parent not in self.graph.functions:
            return {}
        if parent in self._closure_memo:
            return self._closure_memo[parent]
        if parent in self._closure_active:
            return {}
        self._closure_active.add(parent)
        try:
            env = self._entry_env(parent, None)
            for p, v in self.param_values(parent).items():
                if env.get(p, self.domain.top) is self.domain.top:
                    env[p] = v
            walker = _FunctionWalk(self, parent, env)
            walker.run()
            self._closure_memo[parent] = dict(walker.env)
            return self._closure_memo[parent]
        finally:
            self._closure_active.discard(parent)

    def trace_function(self, key: FuncKey, hook) -> None:
        """Flow-sensitive walk of ``key`` calling ``hook(walker,
        stmt)`` before each statement — clients inspect assignments
        with the environment AT that program point (parameters default
        to their call-site join)."""
        env = self._entry_env(key, None)
        for p, v in self.param_values(key).items():
            if env.get(p, self.domain.top) is self.domain.top:
                env[p] = v
        walker = _FunctionWalk(self, key, env, stmt_hook=hook)
        walker.run()

    def summary(self, key: FuncKey, argvals: List[Any],
                kwvals: Dict[str, Any]) -> Any:
        if key in self._active or len(self._active) >= _MAX_CALL_DEPTH:
            return self.domain.top
        memo = (key, tuple(argvals),
                tuple(sorted(kwvals.items())) if kwvals else ())
        try:
            if memo in self._summaries:
                return self._summaries[memo]
        except TypeError:       # unhashable domain value: no memo
            memo = None
        self._active.add(key)
        try:
            val = self.eval_function(key, argvals, kwvals)
        finally:
            self._active.discard(key)
        if memo is not None:
            self._summaries[memo] = val
        return val


class _FunctionWalk:
    """Flow-sensitive walk of ONE function body.

    ``stop_at`` — an AST node; evaluation stops once the statement
    containing it has been processed (used to read the environment a
    call site sees). ``attr_writes`` — joined values of every
    ``self.x = ...`` in the body. ``returns`` — joined return value.
    """

    def __init__(self, engine: Engine, key: FuncKey,
                 env: Dict[str, Any], stop_at: Optional[ast.AST] = None,
                 stmt_hook=None):
        self.engine = engine
        self.domain = engine.domain
        self.key = key
        self.env = env
        self.stop_at = stop_at
        self.stmt_hook = stmt_hook
        self._stopped = False
        self.returns = self.domain.top
        self._saw_return = False
        self.attr_writes: Dict[str, Any] = {}

    # ------------------------------------------------------------------ #
    def run(self) -> None:
        node = self.engine.graph.functions[self.key]
        body = getattr(node, "body", [])
        if isinstance(body, ast.AST):    # Lambda
            self.returns = self.eval(body)
            return
        self.exec_block(body)
        if not self._saw_return:
            self.returns = self.domain.top

    def exec_block(self, stmts) -> None:
        for st in stmts:
            if self._stopped:
                return
            self.exec_stmt(st)
            if self._stopped:
                return      # a nested block hit stop_at: the branch
                # env is preserved as-is (no join past this point)
            if self.stop_at is not None and self._contains(st):
                self._stopped = True
                return

    def _contains(self, st: ast.AST) -> bool:
        return any(n is self.stop_at for n in ast.walk(st))

    # ------------------------------------------------------------------ #
    def exec_stmt(self, st: ast.AST) -> None:
        d = self.domain
        if self.stmt_hook is not None:
            self.stmt_hook(self, st)
        if isinstance(st, ast.Assign):
            val = self.eval(st.value)
            for t in st.targets:
                self.assign(t, val)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.assign(st.target, self.eval(st.value))
        elif isinstance(st, ast.AugAssign):
            cur = self.eval(st.target)
            val = d.binop(st.op, cur, self.eval(st.value))
            self.assign(st.target, val)
        elif isinstance(st, ast.Return):
            val = self.eval(st.value) if st.value is not None else d.top
            self.returns = val if not self._saw_return \
                else d.join(self.returns, val)
            self._saw_return = True
        elif isinstance(st, (ast.If,)):
            self.eval(st.test)
            before = dict(self.env)
            self.exec_block(st.body)
            if self._stopped:
                return      # stop_at inside then-branch: keep its env
            then_env = self.env
            self.env = before
            self.exec_block(st.orelse)
            if self._stopped:
                return
            self.env = self._join_env(then_env, self.env)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            it = self.eval(st.iter)
            elem = d.top
            if isinstance(it, Seq):
                vals = list(it.elts)
                if vals:
                    elem = vals[0]
                    for v in vals[1:]:
                        elem = d.join(elem, v)
            self.assign(st.target, elem)
            for _ in range(_MAX_LOOP_PASSES):
                before = dict(self.env)
                self.exec_block(st.body)
                if self._stopped:
                    return
                joined = self._join_env(before, self.env)
                if joined == before:
                    self.env = joined
                    break
                self.env = joined
            self.exec_block(st.orelse)
        elif isinstance(st, ast.While):
            self.eval(st.test)
            for _ in range(_MAX_LOOP_PASSES):
                before = dict(self.env)
                self.exec_block(st.body)
                if self._stopped:
                    return
                joined = self._join_env(before, self.env)
                if joined == before:
                    self.env = joined
                    break
                self.env = joined
            self.exec_block(st.orelse)
        elif isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
            for item in st.items:
                v = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, v)
            self.exec_block(st.body)
        elif isinstance(st, ast.Try):
            self.exec_block(st.body)
            if self._stopped:
                return
            before = dict(self.env)
            for h in st.handlers:
                self.env = dict(before)
                self.exec_block(h.body)
                if self._stopped:
                    return
                before = self._join_env(before, self.env)
            self.env = before
            self.exec_block(st.orelse)
            self.exec_block(st.finalbody)
        elif isinstance(st, ast.Expr):
            self.eval(st.value)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            pass    # nested defs have their own keys
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
        # Import/Global/Pass/Raise/Assert/...: no value flow modeled

    def _join_env(self, a: Dict[str, Any],
                  b: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k in set(a) | set(b):
            va = a.get(k, self.domain.top)
            vb = b.get(k, self.domain.top)
            out[k] = self.domain.join(va, vb)
        return out

    # ------------------------------------------------------------------ #
    def assign(self, target: ast.AST, val: Any) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, ast.Starred):
            self.assign(target.value, self.domain.top)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(val, Seq) \
                    and len(val.elts) == len(target.elts):
                for t, v in zip(target.elts, val.elts):
                    self.assign(t, v)
            else:
                for t in target.elts:
                    self.assign(t, self.domain.top)
        elif isinstance(target, ast.Attribute):
            d = dotted(target)
            if d is not None and d.startswith("self.") \
                    and "." not in d[len("self."):]:
                attr = d[len("self."):]
                if attr in self.attr_writes:
                    self.attr_writes[attr] = self.domain.join(
                        self.attr_writes[attr], val)
                else:
                    self.attr_writes[attr] = val
        # Subscript stores: no container content tracking

    # ------------------------------------------------------------------ #
    def eval(self, node: Optional[ast.AST]) -> Any:
        d = self.domain
        if node is None:
            return d.top
        if isinstance(node, ast.Constant):
            return d.const(node.value)
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            cenv = self.engine.closure_env(self.key)
            if node.id in cenv:
                return cenv[node.id]
            return self._module_const(node.id)
        if isinstance(node, ast.Attribute):
            dn = dotted(node)
            if dn is not None and dn.startswith("self.") \
                    and "." not in dn[len("self."):]:
                path, cls, _ = self.key
                if cls:
                    v = self.engine.attr_value(path, cls,
                                               dn[len("self."):])
                    if v is not d.top:
                        return v
            base = self.eval(node.value)
            if isinstance(base, Struct):
                if node.attr in base.fields:
                    return base.fields[node.attr]
                return d.top
            return d.attribute(base, node.attr)
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value)
            idx = self.eval(node.slice)
            if isinstance(base, Seq) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, int) \
                    and 0 <= node.slice.value < len(base.elts):
                return base.elts[node.slice.value]
            return d.subscript(base, idx)
        if isinstance(node, ast.BinOp):
            return d.binop(node.op, self.eval(node.left),
                           self.eval(node.right))
        if isinstance(node, ast.UnaryOp):
            return d.unaryop(node.op, self.eval(node.operand))
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = d.join(out, v)
            return out
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return d.join(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            elts = [self.eval(e) for e in node.elts]
            if any(isinstance(e, ast.Starred) for e in node.elts):
                return d.sequence(node, elts)
            seq = Seq(tuple(elts))
            custom = d.sequence(node, elts)
            return custom if custom is not d.top else seq
        if isinstance(node, ast.Compare):
            for c in itertools.chain([node.left], node.comparators):
                self.eval(c)
            return d.top
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            return d.top
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.Lambda):
            return d.top
        if isinstance(node, ast.JoinedStr):
            return d.top
        return d.top

    def _module_const(self, name: str) -> Any:
        """Module-level scalar constants (``_MERGE_CHUNK = 32768``)."""
        path = self.key[0]
        mi = self.engine.ms.modules.get(path)
        if mi is None:
            return self.domain.top
        for st in mi.tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name) \
                    and st.targets[0].id == name \
                    and isinstance(st.value, ast.Constant):
                return self.domain.const(st.value.value)
        return self.domain.top

    def eval_call(self, node: ast.Call) -> Any:
        d = self.domain
        engine = self.engine
        path, cls, qual = self.key
        argvals = [self.eval(a) for a in node.args]
        kwvals = {k.arg: self.eval(k.value)
                  for k in node.keywords if k.arg}
        cn = call_name(node)
        recv = None
        if isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
        # domain transfer first: it sees the raw call + arg values and
        # may fully decide (len, next_bucket, np.zeros, x.sum(), ...)
        val = d.call(cn, node, argvals, kwvals, recv=recv)
        if val is not d.top:
            return val
        # repo constructor -> Struct of its fields
        ctor = engine.graph.resolve_constructor(path, node) \
            if cn is not None else None
        if ctor is not None:
            fields = dict(kwvals)
            tpath, _ = engine.ms.class_defs[ctor]
            tmi = engine.ms.modules[tpath]
            names = _field_names(tmi, ctor)
            for i, v in enumerate(argvals):
                if i < len(names):
                    fields.setdefault(names[i], v)
            if fields:
                return Struct(ctor, fields)
            return d.top
        # interprocedural summary
        r = engine.graph.resolve_call(path, cls or None, node,
                                      prefix=qual)
        if r is not None:
            return engine.summary(r, argvals, kwvals)
        return d.top


def _field_names(mi: ModuleInfo, cname: str) -> List[str]:
    """Positional field names of a constructor: NamedTuple/dataclass
    annotations, else the ``__init__`` parameters (a plain class that
    stores its ctor args — ``self.x = x`` — chases the same way)."""
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.ClassDef) and node.name == cname:
            fields = [st.target.id for st in node.body
                      if isinstance(st, ast.AnnAssign)
                      and isinstance(st.target, ast.Name)]
            if fields:
                return fields
            init = mi.classes.get(cname, {}).get("__init__")
            if init is not None:
                return [a.arg for a in init.args.args
                        if a.arg not in ("self", "cls")]
    return []
