"""Pass — RacerD-style guard inference [ISSUE 13 tentpole].

The lock pass (PR 12) checks lock *order* and blocking ops; it never
asks the question the one-dispatch-core refactor will stress: **which
lock guards which field?** This pass infers it:

* **thread roles** — every ``threading.Thread(target=...)`` /
  ``threading.Timer(..., fn)`` construction names a role (batcher,
  compactor, reaper, flusher, snapshotter, prober, ... — from the
  thread's ``name=`` literal or the target function). Public methods
  of the analyzed classes are the ``caller`` role: whatever thread
  the API user brings.

* **guard contexts** — from each role's entry point, the corpus call
  graph is walked carrying the set of locks held: ``with`` blocks add
  locks (class-attribute and module locks, ``Condition(lock)``
  aliasing, ``q.mutex`` — the lock pass's identity model), and every
  confidently-resolved call propagates the held set into the callee.
  An attribute access observed under context (role, held-locks) is
  one **access-site evidence** record.

* **attribute accesses** — loads and stores of ``self.attr`` (and of
  attributes reached through typed references: ``self._pos.buf``,
  annotated parameters like ``side: _ClassSide``). Container-mutator
  method calls (``.append`` / ``.pop`` / ``.remove`` / ...) count as
  writes. Constructor (``__init__``/``__post_init__``) accesses are
  ignored — the object is not shared yet — and lock / queue / thread
  attributes themselves are exempt (queues lock internally).

Rules, for every attribute reachable from >= 2 roles with at least
one non-constructor write:

* ``race-unguarded-shared`` — some access holds NO lock: that site
  bypasses whatever guard the others use.
* ``race-inconsistent-guard`` — every access is guarded but no single
  lock is common to all of them: two sites believe different locks
  protect the field, which is how the pre-PR-11 deadline-reaper hole
  and the pre-PR-3 block-policy shutdown hazard shipped (both are
  seeded regression fixtures in tests/test_analysis_dataflow.py).

Findings carry the access-site evidence chain (role, site, locks
held). Intentional protocols the checker cannot see locally — the
compactor's worker-claim ownership of snapshotted container prefixes,
idempotent shutdown flags — are waived in ``analysis/waivers.toml``
with written justifications, never silenced in code.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted,
)
from tuplewise_tpu.analysis.dataflow import annotation_class
from tuplewise_tpu.analysis import lock_order

#: packages whose classes are analyzed by default — the serving stack
#: the one-dispatch-core churn will rewrite
DEFAULT_SCOPE = ("tuplewise_tpu/serving/", "tuplewise_tpu/parallel/",
                 "tuplewise_tpu/obs/")

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}

#: substrings that canonicalize a thread name / target into a role
_ROLE_HINTS = ("batcher", "compactor", "reaper", "flusher",
               "snapshotter", "writer", "probe", "controller",
               "healer", "watchdog", "supervisor")

#: method calls that mutate the receiver container (write accesses)
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "pop",
             "popleft", "remove", "clear", "insert", "add", "discard",
             "update", "setdefault", "sort"}

#: contexts kept per (function, role) before collapsing to their
#: intersection — bounds the walk on diamond-heavy call graphs
_MAX_CONTEXTS = 6

_INIT_METHODS = {"__init__", "__post_init__"}

FuncKey = Tuple[str, str, str]


class Access:
    __slots__ = ("cls", "attr", "path", "line", "write", "role",
                 "held")

    def __init__(self, cls: str, attr: str, path: str, line: int,
                 write: bool, role: str, held: FrozenSet[str]):
        self.cls = cls
        self.attr = attr
        self.path = path
        self.line = line
        self.write = write
        self.role = role
        self.held = held


def _role_of(name_literal: Optional[str], target: str) -> str:
    """Canonical role from the thread's ``name=`` literal (preferred)
    or its target function name."""
    for source in (name_literal or "", target):
        low = source.lower()
        for hint in _ROLE_HINTS:
            if hint in low:
                return hint
    base = (name_literal or target).rsplit(".", 1)[-1]
    return base.lstrip("_") or "thread"


def thread_roles(ms: ModuleSet, an: "lock_order._Analysis"
                 ) -> Dict[FuncKey, str]:
    """{entry function key -> role} from every Thread/Timer
    construction in the corpus."""
    roles: Dict[FuncKey, str] = {}
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                target_expr = None
                if cn in _THREAD_CTORS:
                    for k in node.keywords:
                        if k.arg == "target":
                            target_expr = k.value
                elif cn in _TIMER_CTORS and len(node.args) >= 2:
                    target_expr = node.args[1]
                if target_expr is None:
                    continue
                tname = dotted(target_expr)
                if tname is None:
                    continue
                name_lit = None
                for k in node.keywords:
                    if k.arg == "name" \
                            and isinstance(k.value, ast.Constant) \
                            and isinstance(k.value.value, str):
                        name_lit = k.value.value
                key: Optional[FuncKey] = None
                if tname.startswith("self.") and fi.cls:
                    meth = tname[len("self."):]
                    if "." not in meth \
                            and meth in mi.classes.get(fi.cls, {}):
                        key = (path, fi.cls, f"{fi.cls}.{meth}")
                elif "." not in tname:
                    cand = (path, fi.cls or "",
                            f"{fi.qualname}.{tname}")
                    if cand in an.known_funcs:
                        key = cand
                    elif tname in mi.functions:
                        key = (path, "", tname)
                if key is not None:
                    roles[key] = _role_of(name_lit, tname)
    return roles


class _Walker:
    """One (function, role, inherited-held) context walk: records
    attribute accesses under the locks held and propagates contexts
    into resolved callees via the shared worklist."""

    def __init__(self, race: "_RaceAnalysis", key: FuncKey,
                 role: str, held: FrozenSet[str]):
        self.race = race
        self.an = race.an
        self.ms = race.ms
        self.key = key
        self.role = role
        self.entry_held = held
        path, cls, qual = key
        self.path = path
        self.cls = cls or None
        self.qual = qual
        self.mi = self.ms.modules[path]
        self.model = (self.an.model(path, self.cls)
                      if self.cls else None)
        # local name -> repo class (annotated params, typed aliases)
        self.local_types: Dict[str, str] = {}
        fnode = race.func_nodes[key]
        args = getattr(fnode, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                c = annotation_class(self.ms, self.mi, a.annotation)
                if c is not None:
                    self.local_types[a.arg] = c

    # ------------------------------------------------------------------ #
    def lock_of(self, item: ast.withitem) -> Optional[str]:
        if self.model is not None:
            lid = self.model.lock_id(item.context_expr)
            if lid is not None:
                return lid
        d = dotted(item.context_expr)
        if d is not None:
            return self.an.module_locks.get(self.path, {}).get(d)
        return None

    def _owner_of(self, expr: ast.AST) -> Optional[str]:
        """Repo class owning an attribute accessed as
        ``<expr>.attr`` — self, a typed self-attribute, or a typed
        local."""
        d = dotted(expr)
        if d is None:
            return None
        if d == "self":
            return self.cls
        if d.startswith("self.") and self.cls is not None:
            rest = d[len("self."):]
            if "." not in rest:
                model = self.an.model(self.path, self.cls)
                return model.attr_class.get(rest)
            return None
        if "." not in d:
            return self.local_types.get(d)
        return None

    def _is_exempt(self, owner: str, attr: str) -> bool:
        """Locks themselves, queues (internally synchronized), thread
        handles, and dunders are not race subjects."""
        if attr.startswith("__"):
            return True
        cdef = self.race.class_paths.get(owner)
        if cdef is None:
            return True
        model = self.an.model(cdef, owner)
        return (attr in model.locks or attr in model.queues
                or attr in model.threads)

    # ------------------------------------------------------------------ #
    def run(self, node: ast.AST) -> None:
        self.walk(node, self.entry_held)

    def walk(self, node: ast.AST, held: FrozenSet[str]) -> None:
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                continue    # nested defs get contexts via callback
                # linking in lock_order's call resolution
            now = held
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lid = self.lock_of(item)
                    if lid is not None:
                        now = now | {lid}
            elif isinstance(sub, ast.Assign) \
                    and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                # typed alias: side = self._pos
                src = dotted(sub.value)
                if src is not None and src.startswith("self.") \
                        and self.cls is not None:
                    rest = src[len("self."):]
                    if "." not in rest:
                        model = self.an.model(self.path, self.cls)
                        t = model.attr_class.get(rest)
                        if t is not None:
                            self.local_types[sub.targets[0].id] = t
            elif isinstance(sub, (ast.For, ast.AsyncFor)) \
                    and isinstance(sub.target, ast.Name) \
                    and isinstance(sub.iter, (ast.Tuple, ast.List)):
                # for side in (self._pos, self._neg): type the target
                # when every element agrees
                owners = {self._attr_type(e) for e in sub.iter.elts}
                owners.discard(None)
                if len(owners) == 1:
                    self.local_types[sub.target.id] = owners.pop()
            if isinstance(sub, ast.Attribute):
                self._record(sub, now)
            if isinstance(sub, ast.Call):
                self._record_mutator(sub, now)
                self._propagate(sub, now)
            self.walk(sub, now)

    def _attr_type(self, expr: ast.AST) -> Optional[str]:
        d = dotted(expr)
        if d is None or not d.startswith("self.") \
                or self.cls is None:
            return None
        rest = d[len("self."):]
        if "." in rest:
            return None
        model = self.an.model(self.path, self.cls)
        return model.attr_class.get(rest)

    # ------------------------------------------------------------------ #
    def _record(self, node: ast.Attribute,
                held: FrozenSet[str]) -> None:
        owner = self._owner_of(node.value)
        if owner is None or self._is_exempt(owner, node.attr):
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        self.race.add_access(Access(
            owner, node.attr, self.path, node.lineno, write,
            self.role, held))

    def _record_mutator(self, call: ast.Call,
                        held: FrozenSet[str]) -> None:
        """``self._pending.append(x)`` — a container-mutator method
        call is a WRITE to the attribute's object."""
        if not isinstance(call.func, ast.Attribute):
            return
        if call.func.attr not in _MUTATORS:
            return
        recv = call.func.value
        if not isinstance(recv, ast.Attribute):
            return
        owner = self._owner_of(recv.value)
        if owner is None or self._is_exempt(owner, recv.attr):
            return
        self.race.add_access(Access(
            owner, recv.attr, self.path, call.lineno, True,
            self.role, held))

    def _propagate(self, call: ast.Call,
                   held: FrozenSet[str]) -> None:
        r = self.an.resolve_call(self.path, self.cls, call,
                                 prefix=self.qual)
        if r is None or r == self.key:
            return
        if r[2].rsplit(".", 1)[-1] in _INIT_METHODS:
            return      # constructing a FRESH object: not shared yet
        self.race.enqueue(r, self.role, held)
        # nested defs passed as callbacks run under the caller's locks
        for a in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(a, ast.Name):
                cand = (self.path, self.cls or "",
                        f"{self.qual}.{a.id}")
                if cand in self.an.known_funcs and cand != self.key:
                    self.race.enqueue(cand, self.role, held)


class _RaceAnalysis:
    def __init__(self, ms: ModuleSet, an, funcs, scope):
        self.ms = ms
        self.an = an
        self.scope = scope
        self.func_nodes: Dict[FuncKey, ast.AST] = {
            (path, fi.cls or "", fi.qualname): fi.node
            for path, fi in funcs}
        self.func_infos = {(path, fi.cls or "", fi.qualname): fi
                           for path, fi in funcs}
        # class name -> defining path (scoped classes only)
        self.class_paths: Dict[str, str] = {}
        for cname, (path, _) in ms.class_defs.items():
            if any(path.startswith(p) for p in scope):
                self.class_paths[cname] = path
        self.accesses: Dict[Tuple[str, str], List[Access]] = {}
        self.seen_ctx: Dict[FuncKey,
                            Set[Tuple[str, FrozenSet[str]]]] = {}
        self.worklist: List[Tuple[FuncKey, str, FrozenSet[str]]] = []

    def add_access(self, acc: Access) -> None:
        self.accesses.setdefault((acc.cls, acc.attr), []).append(acc)

    def enqueue(self, key: FuncKey, role: str,
                held: FrozenSet[str]) -> None:
        if key not in self.func_nodes:
            return
        ctxs = self.seen_ctx.setdefault(key, set())
        if (role, held) in ctxs:
            return
        same_role = [h for r, h in ctxs if r == role]
        if len(same_role) >= _MAX_CONTEXTS:
            # collapse: keep the intersection — the locks GUARANTEED
            # held however this function was reached in this role
            inter = frozenset.intersection(held, *same_role)
            if any(h == inter for h in same_role):
                return
            held = inter
            if (role, held) in ctxs:
                return
        ctxs.add((role, held))
        self.worklist.append((key, role, held))

    def drain(self) -> None:
        while self.worklist:
            key, role, held = self.worklist.pop()
            walker = _Walker(self, key, role, held)
            walker.run(self.func_nodes[key])


def run(ms: ModuleSet, scope: Tuple[str, ...] = DEFAULT_SCOPE
        ) -> List[Finding]:
    an, funcs = lock_order.build_analysis(ms)
    race = _RaceAnalysis(ms, an, funcs, scope)
    roles = thread_roles(ms, an)

    # entries: thread/timer targets + public API ("caller" role).
    # Caller entries exist only for CONCURRENCY-OWNING classes (a lock
    # attribute or a thread spawn): passive helpers (_ClassSide,
    # StreamingIncompleteU, the health monitors) are externally
    # synchronized by contract — their accesses are judged along the
    # owner paths that reach them, not from a phantom bare-API entry.
    for key, role in roles.items():
        race.enqueue(key, role, frozenset())
    for (path, cls, qual), fi in race.func_infos.items():
        if not any(path.startswith(p) for p in scope):
            continue
        leaf = qual.rsplit(".", 1)[-1]
        if leaf.startswith("_") or leaf in _INIT_METHODS:
            continue
        if (path, cls, qual) in roles:
            continue
        if "." in qual and cls and not qual.startswith(f"{cls}."):
            continue    # nested def, not API surface
        if cls and not _owns_concurrency(race, path, cls):
            continue
        race.enqueue((path, cls, qual), "caller", frozenset())
    race.drain()

    findings: List[Finding] = []
    for (cls, attr), accs in sorted(race.accesses.items()):
        roles_seen = {a.role for a in accs}
        if len(roles_seen) < 2:
            continue
        writes = [a for a in accs if a.write]
        if not writes:
            continue
        unguarded = [a for a in accs if not a.held]
        common = frozenset.intersection(*[a.held for a in accs]) \
            if not unguarded else frozenset()
        if unguarded:
            rule = "race-unguarded-shared"
            head = (f"{cls}.{attr} is shared across roles "
                    f"{sorted(roles_seen)} with at least one write, "
                    "but some sites access it with NO lock held")
        elif not common:
            rule = "race-inconsistent-guard"
            head = (f"{cls}.{attr} is shared across roles "
                    f"{sorted(roles_seen)} with at least one write, "
                    "and no single lock guards every access — sites "
                    "disagree about which lock protects it")
        else:
            continue    # consistently guarded: the invariant holds
        evidence = _evidence(accs, unguarded)
        first = (unguarded or writes or accs)[0]
        findings.append(Finding(
            rule, race.class_paths.get(cls, first.path), first.line,
            f"{cls}.{attr}",
            head + "; evidence: " + "; ".join(evidence)))
    return findings


def _owns_concurrency(race: _RaceAnalysis, path: str,
                      cls: str) -> bool:
    """True when the class owns a lock or spawns a thread — the
    classes whose public API is a real cross-thread entry surface."""
    model = race.an.model(path, cls)
    if model.locks or model.threads:
        return True
    mi = race.ms.modules[path]
    for mnode in mi.classes.get(cls, {}).values():
        for node in ast.walk(mnode):
            if isinstance(node, ast.Call) and call_name(node) in (
                    _THREAD_CTORS | _TIMER_CTORS):
                return True
    return False


def _evidence(accs: List[Access],
              unguarded: List[Access]) -> List[str]:
    """A compact access-site chain: one site per (role, guardedness),
    unguarded and write sites first."""
    picked: List[Access] = []
    seen: Set[Tuple[str, bool, FrozenSet[str]]] = set()
    ordered = sorted(accs, key=lambda a: (bool(a.held), not a.write,
                                          a.line))
    for a in ordered:
        sig = (a.role, bool(a.held), a.held)
        if sig in seen:
            continue
        seen.add(sig)
        picked.append(a)
        if len(picked) >= 4:
            break
    out = []
    for a in picked:
        locks = ",".join(sorted(a.held)) if a.held else "NO LOCK"
        kind = "write" if a.write else "read"
        out.append(f"[{a.role}] {kind} {a.path}:{a.line} "
                   f"holding {locks}")
    return out
