"""Module-graph report [ISSUE 12 satellite]: import cycles (fail) and
dead public symbols (warn-only).

* ``import-cycle`` — a cycle among TOP-LEVEL imports inside
  ``tuplewise_tpu``. Function-local (lazy) imports are exempt: the
  repo lazy-imports deliberately to keep jax off the cold path, and a
  lazy edge cannot deadlock module init. A new top-level cycle fails
  CI like any other finding.
* dead symbols — module-level public (non-underscore) functions and
  classes in ``tuplewise_tpu`` that no other corpus file references by
  name. Reported in the JSON (``dead_symbols``) for humans; NOT a
  failing finding — public API kept for external callers is
  legitimate, and name-reference analysis has false negatives.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from tuplewise_tpu.analysis.core import Finding, ModuleSet

_PKG = "tuplewise_tpu"


def import_graph(ms: ModuleSet) -> Dict[str, Set[str]]:
    """Top-level (eager) import edges between corpus modules."""
    graph: Dict[str, Set[str]] = {}
    for path, mi in ms.modules.items():
        if not path.startswith(_PKG + "/"):
            continue
        mod = ms.module_name(path)
        edges: Set[str] = set()
        for node in mi.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith(_PKG):
                        edges.add(a.name)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith(_PKG):
                edges.add(node.module)
                # `from pkg import submodule` edges resolve to the
                # submodule itself when one exists — the diff
                # closure's blast radius needs the finer edge
                for a in node.names:
                    cand = f"{node.module}.{a.name}"
                    if ms.path_of_module(cand) is not None:
                        edges.add(cand)
        graph[mod] = {e for e in edges
                      if ms.path_of_module(e) is not None}
    return graph


def reverse_closure(ms: ModuleSet, paths: Set[str]) -> Set[str]:
    """The changed files plus every corpus file that (transitively)
    imports one of them — the blast radius a pre-commit ``check
    --diff`` must re-judge [ISSUE 15 satellite]. Non-package files
    (scripts, bench.py) participate as themselves: nothing imports
    them, but their own findings stay in scope."""
    graph = import_graph(ms)
    rev: Dict[str, Set[str]] = {}
    for mod, edges in graph.items():
        for e in edges:
            rev.setdefault(e, set()).add(mod)
    out = {p for p in paths}
    frontier = [ms.module_name(p) for p in paths if p in ms.modules]
    seen = set(frontier)
    while frontier:
        mod = frontier.pop()
        for importer in rev.get(mod, ()):
            if importer in seen:
                continue
            seen.add(importer)
            frontier.append(importer)
            p = ms.path_of_module(importer)
            if p is not None:
                out.add(p)
    return out


def find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    from tuplewise_tpu.analysis.lock_order import _cycles

    return _cycles({k: set(v) for k, v in graph.items()})


def public_symbols(ms: ModuleSet) -> List[Tuple[str, str, int]]:
    out = []
    for path, mi in ms.modules.items():
        if not path.startswith(_PKG + "/") \
                or path.endswith("__init__.py"):
            continue
        for node in mi.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) \
                    and not node.name.startswith("_"):
                out.append((path, node.name, node.lineno))
    return out


def dead_symbols(ms: ModuleSet) -> List[dict]:
    """Public module-level symbols never referenced by name outside
    their defining module (corpus-wide word search, tests included —
    the test tree is read for references even though the passes do not
    analyze it)."""
    import os

    refs: Dict[str, Set[str]] = {}
    sources = {p: mi.source for p, mi in ms.modules.items()}
    if ms.root:
        tdir = os.path.join(ms.root, "tests")
        if os.path.isdir(tdir):
            for fn in sorted(os.listdir(tdir)):
                if fn.endswith(".py"):
                    with open(os.path.join(tdir, fn), "r",
                              encoding="utf-8") as f:
                        sources[f"tests/{fn}"] = f.read()
    names = public_symbols(ms)
    uniq = sorted({n for _, n, _ in names})
    if not uniq:
        return []
    # ONE combined word-boundary scan per source instead of one regex
    # per symbol per source — the per-symbol loop was the slowest
    # single step of the whole check (measured ~15s of a ~25s gate)
    # [ISSUE 15 satellite: the timing block made it visible]
    pat = re.compile(r"\b(" + "|".join(re.escape(n) for n in uniq)
                     + r")\b")
    appears: Dict[str, Set[str]] = {}
    for p, src in sources.items():
        for hit in set(pat.findall(src)):
            appears.setdefault(hit, set()).add(p)
    for path, name, line in names:
        refs[f"{path}:{name}"] = appears.get(name, set()) - {path}
    return [{"file": path, "symbol": name, "line": line}
            for path, name, line in names
            if not refs[f"{path}:{name}"]]


def run(ms: ModuleSet) -> List[Finding]:
    findings = []
    for cyc in find_cycles(import_graph(ms)):
        findings.append(Finding(
            "import-cycle", "<module-graph>", 0,
            "->".join(sorted(set(cyc))),
            "top-level import cycle: " + " -> ".join(cyc + [cyc[0]])
            + " (lazy-import one edge to break module-init order "
            "dependence)"))
    return findings
