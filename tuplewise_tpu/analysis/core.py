"""Shared substrate for the invariant passes [ISSUE 12]: parsed
modules, import resolution, class/attribute typing, and the
:class:`Finding` record every pass emits.

Everything operates on a :class:`ModuleSet` — a mapping of repo-
relative paths to parsed ASTs — so the full-repo run
(``ModuleSet.from_repo``) and the fixture tests
(``ModuleSet.from_sources``) drive the identical code.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
from typing import Dict, Iterator, List, Optional, Tuple

#: modules scanned by default, relative to the repo root
DEFAULT_GLOBS = ("tuplewise_tpu/**/*.py", "scripts/*.py", "bench.py")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation.

    ``fingerprint`` is line-independent (rule + file + symbol) so a
    waiver survives unrelated line churn; ``symbol`` therefore has to
    name the violating construct stably (function qualname, metric
    name, config field) rather than a position.
    """

    rule: str
    file: str
    line: int
    symbol: str
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.file}:{self.symbol}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d


def dotted(node: ast.AST) -> Optional[str]:
    """Best-effort dotted name of an expression: ``self._q.put`` ->
    "self._q.put"; None for anything not a Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_glob(node: ast.AST) -> Optional[str]:
    """A JoinedStr (f-string) as a glob: f"requests_{k}_total" ->
    "requests_*_total" — the producer-pattern form the telemetry pass
    matches consumers against."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    return "".join(parts)


def name_or_glob(node: ast.AST) -> Optional[str]:
    return literal_str(node) if literal_str(node) is not None \
        else fstring_glob(node)


@dataclasses.dataclass
class FunctionInfo:
    qualname: str            # "module-relative" e.g. Class.method or fn
    node: ast.AST            # FunctionDef / AsyncFunctionDef / Lambda
    cls: Optional[str]       # owning class name, if a method


class ModuleInfo:
    """One parsed module: AST + source lines + import table + classes."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # imported name -> fully qualified target ("pkg.mod" for module
        # imports, "pkg.mod:sym" for from-imports), including imports
        # nested inside functions (the repo lazy-imports heavily)
        self.imports: Dict[str, str] = {}
        self.toplevel_imports: Dict[str, str] = {}
        # class name -> {method name -> FunctionDef}
        self.classes: Dict[str, Dict[str, ast.AST]] = {}
        # class name -> {self-attr -> constructor name as written}
        self.attr_ctors: Dict[str, Dict[str, str]] = {}
        self.functions: Dict[str, ast.AST] = {}
        self._index()

    # ------------------------------------------------------------------ #
    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module}:{a.name}"
        for node in self.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        self.toplevel_imports[
                            a.asname or a.name.split(".")[0]] = a.name
                elif node.module:
                    for a in node.names:
                        self.toplevel_imports[a.asname or a.name] = \
                            f"{node.module}:{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods: Dict[str, ast.AST] = {}
                ctors: Dict[str, str] = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        methods[sub.name] = sub
                        for st in ast.walk(sub):
                            if (isinstance(st, ast.Assign)
                                    and len(st.targets) == 1):
                                t = dotted(st.targets[0])
                                val = st.value
                                # x = C(...) if cond else None
                                if isinstance(val, ast.IfExp):
                                    val = (val.body
                                           if isinstance(val.body,
                                                         ast.Call)
                                           else val.orelse)
                                if (t and t.startswith("self.")
                                        and isinstance(val, ast.Call)):
                                    cn = call_name(val)
                                    if cn:
                                        ctors.setdefault(
                                            t[len("self."):], cn)
                self.classes[node.name] = methods
                self.attr_ctors[node.name] = ctors

    # ------------------------------------------------------------------ #
    def iter_functions(self) -> Iterator[FunctionInfo]:
        """Every def in the module (module-level, methods, nested),
        with a stable qualname."""
        stack: List[Tuple[ast.AST, str, Optional[str]]] = [
            (self.tree, "", None)]
        while stack:
            node, prefix, cls = stack.pop()
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    q = f"{prefix}{sub.name}"
                    yield FunctionInfo(q, sub, cls)
                    stack.append((sub, q + ".", cls))
                elif isinstance(sub, ast.ClassDef):
                    stack.append((sub, f"{prefix}{sub.name}.",
                                  sub.name))


class ModuleSet:
    """The analyzed corpus: repo-relative path -> :class:`ModuleInfo`,
    plus whatever non-Python text files the doc-facing passes need."""

    def __init__(self, modules: Dict[str, ModuleInfo],
                 texts: Optional[Dict[str, str]] = None,
                 root: Optional[str] = None):
        self.modules = modules
        self.texts = texts or {}
        self.root = root
        self.parse_errors: Dict[str, str] = {}
        # global class registry (name -> (path, methods)); ambiguous
        # names keep the first definition — good enough for call
        # resolution, and the repo keeps class names unique
        self.class_defs: Dict[str, Tuple[str, Dict[str, ast.AST]]] = {}
        for path, mi in modules.items():
            for cname, methods in mi.classes.items():
                self.class_defs.setdefault(cname, (path, methods))

    # ------------------------------------------------------------------ #
    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     texts: Optional[Dict[str, str]] = None
                     ) -> "ModuleSet":
        mods = {}
        errors = {}
        for path, src in sources.items():
            try:
                mods[path] = ModuleInfo(path, src)
            except SyntaxError as e:   # keep analyzing the rest
                errors[path] = repr(e)
        ms = cls(mods, texts=texts)
        ms.parse_errors = errors
        return ms

    @classmethod
    def from_repo(cls, root: str,
                  globs: Tuple[str, ...] = DEFAULT_GLOBS,
                  text_files: Tuple[str, ...] = (
                      "README.md", "docs/DESIGN.md"),
                  cache=None) -> "ModuleSet":
        sources: Dict[str, str] = {}
        for pat in globs:
            base = pat.split("*")[0].rstrip("/")
            start = os.path.join(root, base) if base else root
            if pat.endswith(".py") and "*" not in pat:
                p = os.path.join(root, pat)
                if os.path.exists(p):
                    sources[pat] = _read(p)
                continue
            for dirpath, dirnames, filenames in os.walk(start):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in filenames:
                    if not fn.endswith(".py"):
                        continue
                    full = os.path.join(dirpath, fn)
                    rel = os.path.relpath(full, root).replace(
                        os.sep, "/")
                    if fnmatch.fnmatch(rel, pat):
                        sources[rel] = _read(full)
        texts = {}
        for tf in text_files:
            p = os.path.join(root, tf)
            if os.path.exists(p):
                texts[tf] = _read(p)
        if cache is None:
            ms = cls.from_sources(sources, texts=texts)
        else:
            # incremental parse [ISSUE 13 satellite]: content-sha hits
            # skip the parse+index entirely; misses are stored back
            mods: Dict[str, ModuleInfo] = {}
            errors: Dict[str, str] = {}
            for path, src in sources.items():
                mi = cache.get(path, src)
                if mi is None:
                    try:
                        mi = ModuleInfo(path, src)
                    except SyntaxError as e:
                        errors[path] = repr(e)
                        continue
                    cache.put(path, src, mi)
                mods[path] = mi
            ms = cls(mods, texts=texts)
            ms.parse_errors = errors
        ms.root = root
        return ms

    # ------------------------------------------------------------------ #
    def module_name(self, path: str) -> str:
        """"tuplewise_tpu/serving/index.py" -> "tuplewise_tpu.serving.index"."""
        p = path[:-3] if path.endswith(".py") else path
        p = p[:-len("/__init__")] if p.endswith("/__init__") else p
        return p.replace("/", ".")

    def path_of_module(self, mod: str) -> Optional[str]:
        cand = mod.replace(".", "/") + ".py"
        if cand in self.modules:
            return cand
        cand = mod.replace(".", "/") + "/__init__.py"
        if cand in self.modules:
            return cand
        return None

    def resolve_import(self, mi: ModuleInfo, name: str
                       ) -> Optional[Tuple[str, str]]:
        """Resolve a bare name used in ``mi`` through its import table
        to ``(module_path, symbol)`` inside this ModuleSet; None for
        stdlib / third-party / unresolved names."""
        tgt = mi.imports.get(name)
        if tgt is None:
            return None
        if ":" in tgt:
            mod, sym = tgt.split(":", 1)
        else:
            mod, sym = tgt, ""
        path = self.path_of_module(mod)
        if path is None:
            return None
        return path, sym

    def resolve_class(self, mi: ModuleInfo, ctor: str
                      ) -> Optional[str]:
        """Map a constructor name as written ("ExactAucIndex",
        "queue.Queue", "threading.Thread") to a repo class name when it
        is one, else None."""
        head = ctor.split(".")[0]
        if ctor in mi.classes:
            return ctor
        resolved = self.resolve_import(mi, head)
        if resolved is not None:
            _, sym = resolved
            name = sym or ctor.split(".")[-1]
            if name in self.class_defs:
                return name
        if ctor in self.class_defs:
            return ctor
        return None


def _read(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def glob_match(name: str, patterns) -> bool:
    return any(fnmatch.fnmatchcase(name, p) for p in patterns)
