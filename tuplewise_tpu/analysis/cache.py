"""Incremental analysis cache [ISSUE 13 satellite].

``tuplewise check`` re-parses ~100 modules per run; CI runs it on
every push. Parsed modules are immutable functions of their source
bytes, so they cache perfectly: each file's :class:`ModuleInfo`
(AST + index tables) is pickled under its content sha — a repeat run
reparses ONLY changed files and the report carries the hit/miss
counters. ``--no-cache`` is the escape hatch; the cache directory
(``.tuplewise_check_cache/``, gitignored) is safe to delete at any
time.

Keys include an ``ANALYSIS_CACHE_VERSION`` stamp and the Python
version: bumping the version whenever ``core.ModuleInfo``'s shape
changes invalidates every stale entry at once — a wrong hit can never
outlive the code that wrote it.

Keys ALSO include a **global cache epoch** [ISSUE 15 bugfix]: the
content digest of the checker package itself plus the committed
waiver/budget/bounds files (``compute_epoch``). Content-sha-of-the-
analyzed-file alone is not a sound key for anything derived from the
ANALYZER: a waivers.toml edit, a checker bugfix, or a budget change
must force a cold re-run, never replay results computed under the old
rules. The epoch folds all of that state into every key, so editing
any of it invalidates the whole cache at once.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys
from typing import Optional

#: bump when core.ModuleInfo's pickled shape changes
ANALYSIS_CACHE_VERSION = 1

DEFAULT_CACHE_DIR = ".tuplewise_check_cache"


def _stamp() -> str:
    return (f"v{ANALYSIS_CACHE_VERSION}-py{sys.version_info[0]}."
            f"{sys.version_info[1]}")


def compute_epoch(root: str) -> str:
    """Global cache epoch [ISSUE 15 bugfix]: digest of the checker
    package sources AND the committed waivers/budget/bounds TOMLs
    under ``tuplewise_tpu/analysis/``. Any edit to the analyzer or
    its committed inputs changes the epoch, so every cached entry
    goes cold at once — stale results can never replay across a
    checker-version bump or a waiver/budget change."""
    h = hashlib.sha256()
    h.update(_stamp().encode())
    adir = os.path.join(root, "tuplewise_tpu", "analysis")
    if os.path.isdir(adir):
        for fn in sorted(os.listdir(adir)):
            if not fn.endswith((".py", ".toml")):
                continue
            h.update(fn.encode())
            try:
                with open(os.path.join(adir, fn), "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(b"<unreadable>")
    return h.hexdigest()[:16]


class ParseCache:
    """Content-sha keyed store of pickled ModuleInfo objects. One file
    per module path (sha inside), so stale entries replace themselves
    and the directory never grows past the corpus size. ``epoch``
    (see :func:`compute_epoch`) folds the analyzer's own state into
    every key."""

    def __init__(self, root: str,
                 subdir: str = DEFAULT_CACHE_DIR,
                 epoch: str = ""):
        self.dir = os.path.join(root, subdir)
        self.epoch = epoch if epoch else compute_epoch(root)
        self.hits = 0
        self.misses = 0
        self._ready = False

    def _ensure_dir(self) -> bool:
        if not self._ready:
            try:
                os.makedirs(self.dir, exist_ok=True)
                self._ready = True
            except OSError:
                return False
        return True

    def key(self, path: str, source: str) -> str:
        h = hashlib.sha256()
        h.update(_stamp().encode())
        h.update(self.epoch.encode())
        h.update(path.encode())
        h.update(source.encode())
        return h.hexdigest()

    def _entry_path(self, path: str) -> str:
        safe = path.replace("/", "__").replace("\\", "__")
        return os.path.join(self.dir, safe + ".pkl")

    def get(self, path: str, source: str):
        """The cached ModuleInfo for (path, source), or None."""
        try:
            with open(self._entry_path(path), "rb") as f:
                sha, mi = pickle.load(f)
        except (OSError, pickle.PickleError, EOFError,
                AttributeError, ImportError):
            self.misses += 1
            return None
        if sha != self.key(path, source):
            self.misses += 1
            return None
        self.hits += 1
        return mi

    def put(self, path: str, source: str, mi) -> None:
        if not self._ensure_dir():
            return
        tmp = self._entry_path(path) + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump((self.key(path, source), mi), f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._entry_path(path))
        except (OSError, pickle.PickleError):
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def stats(self) -> dict:
        return {"enabled": True, "hits": self.hits,
                "misses": self.misses, "epoch": self.epoch}
