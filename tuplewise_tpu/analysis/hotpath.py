"""Pass — host-cost certification of the request path [ISSUE 15
tentpole].

PR 14's runtime ledger measured host_fraction 0.979: the request path
is Python, not device. The one-dispatch-core refactor on the roadmap
exists to kill that — and this pass is its STATIC twin, so the
refactor's progress ratchets in CI (the compile_ladder →
exactness_bounds pattern, applied to host cost) and a regression
fails by name instead of surfacing as a perf-gate breach three PRs
later.

For every **request-path root** (`MicroBatchEngine.submit/insert/
score` + batcher apply, the `MultiTenantEngine` twins, the index and
fleet insert paths, the sharded/fused/tenant-axis count dispatchers)
the pass walks everything reachable through the corpus call graph and
derives an abstract **cost summary**: how many of each cost-bearing
construct execute, classified by loop multiplicity:

* ``alloc``     — dict/list/tuple/set displays + comprehensions
                  (every one is a Python object construction)
* ``ctor``      — class constructions (repo classes and stdlib
                  container ctors: per-event object graphs are
                  exactly what the arena/SoA refactor removes)
* ``np_alloc``  — numpy/jax array-allocating calls (asarray,
                  concatenate, zeros, sort, insert, …)
* ``attr_hop``  — attribute / subscript indirection loads (the
                  per-tenant dict-hop tax the ledger measured)
* ``lock``      — lock acquisitions (``with self._lock``)
* ``dispatch``  — device dispatches (the lock pass's detection:
                  ``sharded_counts``/``tenant_pack_counts``/… and
                  ``*_fn(...)(...)`` jit-factory calls)

**Loop classification.** Each site's multiplicity is the join of its
enclosing loops, inferred by a dataflow chase over the loop iterable
(local assignment chase, then token classification over the serving
stack's wave/batch/tenant collection vocabulary):

* ``O(1)``        — not in a loop, constant-tuple iteration,
                    ``range(<const>)``
* ``O(tenants)``  — loops over tenants-in-wave collections
                    (``groups``/``segs``/``_pending``/``wave``/…)
* ``O(events)``   — loops over request/event collections (``run``/
                    ``batch``/``scores``/``reqs``/…); unknown
                    iterables conservatively land here

Interprocedural propagation carries the caller's site multiplicity
into callees (a helper called per event pays per event), visited once
per (function, multiplicity) per root.

The evaluated table is the **hotpath certificate** (report key
``hotpath_certificate``), diffed by the CI gate against the committed
``tuplewise_tpu/analysis/hotpath_budget.toml``: any root whose loop
class worsens or whose counter GROWS fails CI naming the root, the
contributing sites, and the violated budget line; any counter that
SHRINKS ratchets the budget file downward (the gate rewrites it, the
PR commits the improvement). A root the corpus no longer defines is a
finding (``hotpath-root-missing``) so a rename can never silently
drop certification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted,
)
from tuplewise_tpu.analysis import lock_order

FuncKey = Tuple[str, str, str]

#: the certified request-path roots: (path, class, method). submit /
#: insert / score are the caller-facing edge; the batcher apply
#: functions are the per-wave hot loop; the index / fleet insert
#: paths and the count dispatchers are what they reach.
ROOTS: Tuple[Tuple[str, str, str], ...] = (
    ("tuplewise_tpu/serving/engine.py", "MicroBatchEngine", "submit"),
    ("tuplewise_tpu/serving/engine.py", "MicroBatchEngine", "insert"),
    ("tuplewise_tpu/serving/engine.py", "MicroBatchEngine", "score"),
    ("tuplewise_tpu/serving/engine.py", "MicroBatchEngine",
     "_apply_inserts_wave"),
    ("tuplewise_tpu/serving/tenancy.py", "MultiTenantEngine", "submit"),
    ("tuplewise_tpu/serving/tenancy.py", "MultiTenantEngine", "insert"),
    ("tuplewise_tpu/serving/tenancy.py", "MultiTenantEngine", "score"),
    ("tuplewise_tpu/serving/tenancy.py", "MultiTenantEngine",
     "_apply_insert_wave_ledgered"),
    ("tuplewise_tpu/serving/index.py", "ExactAucIndex", "insert_batch"),
    ("tuplewise_tpu/serving/tenancy.py", "TenantFleetIndex",
     "apply_inserts"),
    ("tuplewise_tpu/parallel/sharded_counts.py", "", "sharded_counts"),
    ("tuplewise_tpu/parallel/sharded_counts.py", "",
     "signed_pair_counts"),
    ("tuplewise_tpu/parallel/sharded_counts.py", "",
     "tenant_pack_counts"),
)

#: multiplicity lattice (index = severity order)
O1 = "O(1)"
OTEN = "O(tenants)"
OEV = "O(events)"
_MULT_ORDER = (O1, OTEN, OEV)
_MULT_SUFFIX = {O1: "per_wave", OTEN: "per_tenant", OEV: "per_event"}

#: counter families
COUNTERS = ("alloc", "ctor", "np_alloc", "attr_hop", "lock",
            "dispatch")

#: iterable-name tokens that classify a loop bound. Matched against
#: the (chased) dotted source of the iterable, token-wise.
_EVENT_TOKENS = {"run", "runs", "batch", "reqs", "requests", "scores",
                 "labels", "queue_waits", "events", "stale", "expired",
                 "dq", "live", "vals", "values", "items", "keep",
                 "records", "plan", "batches"}
_TENANT_TOKENS = {"groups", "segs", "tenants", "sts", "wave", "waves",
                  "pending", "_pending", "rotation", "tids",
                  "by_tenant", "packs", "slots", "dirty"}

#: array-allocating numpy/jax call leaves
_NP_ALLOC_LEAVES = {"asarray", "array", "atleast_1d", "concatenate",
                    "zeros", "ones", "empty", "full", "arange",
                    "linspace", "sort", "insert", "searchsorted",
                    "stack", "hstack", "vstack", "copy", "astype",
                    "repeat", "tile", "where", "cumsum", "unique",
                    "split", "pad"}
_NP_HEADS = {"np", "numpy", "jnp"}

#: stdlib container constructors (counted as ctor when called)
_STDLIB_CTORS = {"dict", "list", "set", "tuple", "deque",
                 "OrderedDict", "defaultdict", "Counter", "Future"}

_MAX_DEPTH = 10         # call-graph walk depth per root
_MAX_SITES = 8          # example sites kept per (root, counter key)


def _join_mult(a: str, b: str) -> str:
    return _MULT_ORDER[max(_MULT_ORDER.index(a), _MULT_ORDER.index(b))]


def _tokens(expr: str) -> Set[str]:
    out: Set[str] = set()
    for part in expr.replace("(", ".").replace(")", ".").split("."):
        part = part.strip().strip("_")
        if part:
            out.add(part)
            out.add("_" + part)
    return out


def classify_source(expr: str) -> str:
    """Multiplicity class of a loop iterable named ``expr`` (after
    the local chase): tenant tokens beat event tokens beat the
    conservative O(events) default for unknowns."""
    toks = _tokens(expr)
    if toks & _TENANT_TOKENS:
        return OTEN
    if toks & _EVENT_TOKENS:
        return OEV
    return OEV      # unknown collection: price it conservatively


class _CostWalker:
    """One (function, multiplicity) context walk for one root:
    records cost sites and enqueues resolved callees at the call
    site's multiplicity."""

    def __init__(self, cost: "_CostAnalysis", key: FuncKey,
                 mult: str):
        self.cost = cost
        self.an = cost.an
        self.ms = cost.ms
        self.key = key
        self.entry_mult = mult
        path, cls, qual = key
        self.path = path
        self.cls = cls or None
        self.qual = qual
        self.model = (self.an.model(path, self.cls)
                      if self.cls else None)
        # local name -> source expression string (one-step chase for
        # loop-iterable classification)
        self.sources: Dict[str, str] = {}

    # ------------------------------------------------------------------ #
    def loop_class(self, it: ast.AST) -> str:
        """Multiplicity of one loop's iterable."""
        # constant displays iterate a fixed small number of times
        if isinstance(it, (ast.Tuple, ast.List, ast.Set)):
            return O1
        if isinstance(it, ast.Call):
            cn = call_name(it) or ""
            leaf = cn.split(".")[-1]
            if leaf == "range":
                if all(isinstance(a, ast.Constant) for a in it.args):
                    return O1
                args = " ".join(dotted(a) or "" for a in it.args)
                return self.loop_class_of_name(args)
            if leaf in ("items", "keys", "values", "enumerate", "zip",
                        "sorted", "reversed", "list"):
                inner = (it.func.value
                         if isinstance(it.func, ast.Attribute)
                         else (it.args[0] if it.args else None))
                if inner is not None:
                    return self.loop_class(inner)
            if leaf == "_waves" or "wave" in leaf:
                return OTEN
            return self.loop_class_of_name(cn)
        d = dotted(it)
        if d is not None:
            return self.loop_class_of_name(d)
        if isinstance(it, (ast.ListComp, ast.GeneratorExp)):
            return self.loop_class(it.generators[0].iter)
        return OEV

    def loop_class_of_name(self, name: str) -> str:
        # chase one local assignment: groups = wave["insert"] etc.
        head = name.split(".")[0].split(" ")[0]
        src = self.sources.get(head)
        if src is not None and src != name:
            return classify_source(f"{src} {name}")
        return classify_source(name)

    # ------------------------------------------------------------------ #
    def run(self, node: ast.AST) -> None:
        for sub in ast.iter_child_nodes(node):
            self.visit(sub, self.entry_mult)

    def visit(self, node: ast.AST, mult: str) -> None:
        """Record ``node``'s own cost at ``mult`` and recurse, raising
        the multiplicity for loop bodies (a For's header still bills
        once per enclosing iteration)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # nested defs priced when called / linked
        self._record(node, mult)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self.visit(node.iter, mult)
            inner = _join_mult(mult, self.loop_class(node.iter))
            for st in [node.target] + node.body + node.orelse:
                self.visit(st, inner)
            return
        if isinstance(node, ast.While):
            # a while on the request path prices conservatively: a
            # drain/retry loop scales with what it drains
            inner = _join_mult(mult, OEV)
            self.visit(node.test, inner)
            for st in node.body + node.orelse:
                self.visit(st, inner)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            inner = _join_mult(mult,
                               self.loop_class(node.generators[0].iter))
            for gen in node.generators:
                self.visit(gen.iter, mult)
                for cond in gen.ifs:
                    self.visit(cond, inner)
            for part in ("elt", "key", "value"):
                sub = getattr(node, part, None)
                if sub is not None:
                    self.visit(sub, inner)
            return
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            src = dotted(node.value)
            if src is None and isinstance(node.value, ast.Call):
                src = call_name(node.value)
            if src is None and isinstance(node.value, ast.Subscript):
                src = dotted(node.value.value)
            if src is not None:
                self.sources[node.targets[0].id] = src
        for sub in ast.iter_child_nodes(node):
            self.visit(sub, mult)

    # ------------------------------------------------------------------ #
    def _record(self, sub: ast.AST, mult: str) -> None:
        """Record cost sites on ``sub`` itself at ``mult``."""
        add = self.cost.add_site
        if isinstance(sub, (ast.Dict, ast.List, ast.Set, ast.Tuple)) \
                and isinstance(getattr(sub, "ctx", ast.Load()),
                               ast.Load):
            add("alloc", self.key, sub.lineno, mult)
        elif isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
            add("alloc", self.key, sub.lineno, mult)
        elif isinstance(sub, ast.Attribute) \
                and isinstance(sub.ctx, ast.Load):
            add("attr_hop", self.key, sub.lineno, mult)
        elif isinstance(sub, ast.Subscript) \
                and isinstance(sub.ctx, ast.Load):
            add("attr_hop", self.key, sub.lineno, mult)
        elif isinstance(sub, ast.With):
            for item in sub.items:
                lid = self._lock_of(item)
                if lid is not None:
                    add("lock", self.key, sub.lineno, mult,
                        detail=lid)
        if isinstance(sub, ast.Call):
            self._record_call(sub, mult)

    def _lock_of(self, item: ast.withitem) -> Optional[str]:
        if self.model is not None:
            lid = self.model.lock_id(item.context_expr)
            if lid is not None:
                return lid
        d = dotted(item.context_expr)
        if d is not None:
            return self.an.module_locks.get(self.path, {}).get(d)
        return None

    def _record_call(self, call: ast.Call, mult: str) -> None:
        add = self.cost.add_site
        cn = call_name(call)
        b = self.an.direct_blocking(self.path, self.cls, call)
        if b is not None and b[0] == "device_dispatch":
            add("dispatch", self.key, call.lineno, mult, detail=b[1])
        if cn is not None:
            leaf = cn.split(".")[-1]
            head = cn.split(".")[0]
            if head in _NP_HEADS and leaf in _NP_ALLOC_LEAVES:
                add("np_alloc", self.key, call.lineno, mult, detail=cn)
            elif leaf in _NP_ALLOC_LEAVES and "." in cn \
                    and head not in ("self",):
                # method form: arr.astype(...), arr.copy()
                add("np_alloc", self.key, call.lineno, mult,
                    detail=cn)
            if cn in _STDLIB_CTORS:
                add("ctor", self.key, call.lineno, mult, detail=cn)
            else:
                rc = self.ms.resolve_class(
                    self.ms.modules[self.path], cn)
                if rc is not None:
                    add("ctor", self.key, call.lineno, mult,
                        detail=rc)
        # propagate multiplicity into resolved callees (+ nested defs
        # handed as callbacks, the healer's ``attempt`` protocol)
        r = self.an.resolve_call(self.path, self.cls, call,
                                 prefix=self.qual)
        if r is not None and r != self.key:
            self.cost.enqueue(r, mult)
        for a in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(a, ast.Name):
                cand = (self.path, self.cls or "",
                        f"{self.qual}.{a.id}")
                if cand in self.an.known_funcs and cand != self.key:
                    self.cost.enqueue(cand, mult)


class _CostAnalysis:
    """Per-root accumulation: (counter, multiplicity) -> count +
    example sites."""

    def __init__(self, ms: ModuleSet, an: "lock_order._Analysis"):
        self.ms = ms
        self.an = an
        self.counts: Dict[Tuple[str, str], int] = {}
        self.sites: Dict[Tuple[str, str], List[str]] = {}
        self.worst: str = O1
        self.seen: Set[Tuple[FuncKey, str]] = set()
        self.worklist: List[Tuple[FuncKey, str]] = []
        self.funcs_reached: Set[FuncKey] = set()

    def add_site(self, counter: str, key: FuncKey, line: int,
                 mult: str, detail: str = "") -> None:
        k = (counter, mult)
        self.counts[k] = self.counts.get(k, 0) + 1
        sites = self.sites.setdefault(k, [])
        if len(sites) < _MAX_SITES:
            tag = f"{key[0]}:{line} ({key[2]}"
            tag += f" {detail})" if detail else ")"
            sites.append(tag)
        if counter in ("alloc", "ctor", "np_alloc", "lock",
                       "dispatch"):
            self.worst = _join_mult(self.worst, mult)

    def enqueue(self, key: FuncKey, mult: str) -> None:
        if key not in self.an.known_funcs:
            return
        # one visit per (function, multiplicity): a helper called
        # both per-wave and per-event pays in BOTH classes — that is
        # the semantics, and it keeps the counters stable under
        # traversal-order churn
        if (key, mult) in self.seen or len(self.seen) > 4000:
            return
        self.seen.add((key, mult))
        self.worklist.append((key, mult))

    def drain(self, func_nodes: Dict[FuncKey, ast.AST]) -> None:
        depth = 0
        while self.worklist and depth < 200000:
            depth += 1
            key, mult = self.worklist.pop()
            node = func_nodes.get(key)
            if node is None:
                continue
            self.funcs_reached.add(key)
            _CostWalker(self, key, mult).run(node)


def _root_key(ms: ModuleSet, path: str, cls: str,
              meth: str) -> Optional[FuncKey]:
    mi = ms.modules.get(path)
    if mi is None:
        return None
    if cls:
        if meth in mi.classes.get(cls, {}):
            return (path, cls, f"{cls}.{meth}")
        return None
    if meth in mi.functions:
        return (path, "", meth)
    return None


def certificates(ms: ModuleSet,
                 roots: Tuple[Tuple[str, str, str], ...] = ROOTS
                 ) -> Dict[str, object]:
    """The hotpath certificate: one cost summary per request-path
    root. ``{"roots": [...], "missing": [...]}`` — each root entry
    carries the flattened ``<counter>_<class>`` table, the worst loop
    class, and example sites per counter."""
    an, funcs = lock_order.build_analysis(ms)
    func_nodes: Dict[FuncKey, ast.AST] = {
        (path, fi.cls or "", fi.qualname): fi.node
        for path, fi in funcs}
    entries: List[dict] = []
    missing: List[dict] = []
    for path, cls, meth in roots:
        key = _root_key(ms, path, cls, meth)
        name = f"{cls}.{meth}" if cls else meth
        if key is None:
            missing.append({"root": name, "file": path})
            continue
        cost = _CostAnalysis(ms, an)
        cost.enqueue(key, O1)
        cost.drain(func_nodes)
        counters: Dict[str, int] = {}
        sites: Dict[str, List[str]] = {}
        for c in COUNTERS:
            for m in _MULT_ORDER:
                v = cost.counts.get((c, m), 0)
                if v:
                    k = f"{c}_{_MULT_SUFFIX[m]}"
                    counters[k] = v
                    sites[k] = cost.sites.get((c, m), [])
        entries.append({
            "root": name,
            "file": path,
            "line": func_nodes[key].lineno,
            "loop_class": cost.worst,
            "functions_reached": len(cost.funcs_reached),
            "counters": counters,
            "sites": sites,
        })
    entries.sort(key=lambda e: (e["file"], e["root"]))
    return {"roots": entries, "missing": missing}


# --------------------------------------------------------------------- #
# committed budget (the downward ratchet)                                 #
# --------------------------------------------------------------------- #

class BudgetError(ValueError):
    """hotpath_budget.toml is malformed."""


def parse_budget(text: str) -> List[Dict[str, object]]:
    """``[[root]]`` tables of scalar keys (the waivers.toml TOML
    subset); every value keeps its line number so a violated budget
    line can be NAMED in the gate failure."""
    entries: List[Dict[str, object]] = []
    current: Optional[Dict[str, object]] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[root]]":
            current = {"__lines__": {}}
            entries.append(current)
            continue
        if line.startswith("["):
            raise BudgetError(
                f"hotpath_budget.toml:{lineno}: only [[root]] tables "
                f"are supported, got {line!r}")
        if "=" not in line or current is None:
            raise BudgetError(
                f"hotpath_budget.toml:{lineno}: expected 'key = "
                f"value' inside a [[root]] table, got {line!r}")
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if val.startswith('"') and val.endswith('"') and len(val) >= 2:
            current[key] = val[1:-1]
        elif val.lstrip("-").isdigit():
            current[key] = int(val)
        else:
            raise BudgetError(
                f"hotpath_budget.toml:{lineno}: value for {key!r} "
                f"must be a string or integer, got {val!r}")
        current["__lines__"][key] = lineno       # type: ignore[index]
    for e in entries:
        for req in ("root", "file", "loop_class"):
            if req not in e:
                raise BudgetError(
                    f"hotpath_budget.toml: [[root]] entry missing "
                    f"required key {req!r}")
    return entries


def format_budget(cert: Dict[str, object]) -> str:
    """Render a certificate as the committed budget file — the exact
    text the gate rewrites when every drift is downward."""
    out = [
        "# Committed host-cost budget for the request path "
        "[ISSUE 15] — DESIGN §17.",
        "#",
        "# One [[root]] table per certified request-path root: the "
        "abstract cost",
        "# counters (<counter>_<multiplicity>) scripts/analysis_gate"
        ".py derives",
        "# from the corpus every run. A counter that GROWS (or a "
        "loop class that",
        "# worsens) fails CI naming the root, the contributing "
        "sites, and the",
        "# violated line below; a counter that SHRINKS is ratcheted "
        "down — the",
        "# gate rewrites this file and the improvement is committed "
        "with the PR.",
        "# Regenerate: python scripts/analysis_gate.py "
        "--update-hotpath-budget",
        "",
    ]
    for e in cert["roots"]:
        out.append("[[root]]")
        out.append(f'root = "{e["root"]}"')
        out.append(f'file = "{e["file"]}"')
        out.append(f'loop_class = "{e["loop_class"]}"')
        for k in sorted(e["counters"]):
            out.append(f"{k} = {e['counters'][k]}")
        out.append("")
    return "\n".join(out)


def compare_to_budget(cert: Dict[str, object], budget_text: str
                      ) -> Tuple[List[str], List[str]]:
    """(violations, shrinks). Violations fail the gate: a grown
    counter, a worsened loop class, a root missing from either side,
    or a malformed budget — each naming the root, the budget line,
    and (for growth) the contributing sites. Shrinks are the downward
    ratchet: the gate rewrites the budget file from the fresh
    certificate."""
    try:
        budget = parse_budget(budget_text)
    except BudgetError as e:
        return [str(e)], []
    errors: List[str] = []
    shrinks: List[str] = []
    by_root = {b["root"]: b for b in budget}
    for e in cert["roots"]:
        b = by_root.pop(e["root"], None)
        if b is None:
            errors.append(
                f"root {e['root']} ({e['file']}) has no committed "
                "budget — add its [[root]] table to "
                "hotpath_budget.toml (or run analysis_gate.py "
                "--update-hotpath-budget) after review")
            continue
        lines = b.get("__lines__", {})
        bc = _join_mult(str(b.get("loop_class", O1)), O1)
        if _MULT_ORDER.index(e["loop_class"]) > _MULT_ORDER.index(bc):
            errors.append(
                f"loop class worsened for root {e['root']}: budget "
                f"says {bc} (hotpath_budget.toml:"
                f"{lines.get('loop_class', '?')}), derived "
                f"{e['loop_class']} — a new request-path loop now "
                "scales with the wave")
        keys = set(e["counters"]) | {
            k for k in b if k not in ("root", "file", "loop_class",
                                      "__lines__")}
        for k in sorted(keys):
            derived = int(e["counters"].get(k, 0))
            committed = int(b.get(k, 0))        # type: ignore[arg-type]
            if derived > committed:
                where = lines.get(k)
                sites = e["sites"].get(k, [])
                errors.append(
                    f"host-cost budget exceeded: root {e['root']} "
                    f"counter {k} = {derived} > budgeted {committed} "
                    f"(hotpath_budget.toml:"
                    f"{where if where is not None else 'missing key'}"
                    f"); contributing sites: "
                    + ("; ".join(sites) if sites else "<none kept>"))
            elif derived < committed:
                shrinks.append(
                    f"{e['root']}: {k} {committed} -> {derived}")
    for name in sorted(by_root):
        errors.append(
            f"stale budget entry: root {name} is no longer derived "
            "— prune its [[root]] table (or rename it in "
            "analysis/hotpath.ROOTS)")
    for m in cert["missing"]:
        errors.append(
            f"request-path root {m['root']} not found in "
            f"{m['file']} — update analysis/hotpath.ROOTS alongside "
            "the rename so the certificate keeps covering it")
    return errors, shrinks


# --------------------------------------------------------------------- #
# the pass                                                               #
# --------------------------------------------------------------------- #

def run(ms: ModuleSet) -> List[Finding]:
    """Findings from certification itself: a declared root the corpus
    no longer defines. Budget drift is the CI gate's job (the
    exactness_bounds pattern) — it needs the committed file."""
    return missing_findings(certificates(ms))


def missing_findings(cert: Dict[str, object]) -> List[Finding]:
    findings: List[Finding] = []
    for m in cert["missing"]:
        findings.append(Finding(
            "hotpath-root-missing", m["file"], 0, m["root"],
            f"request-path root {m['root']} is declared in "
            "analysis/hotpath.ROOTS but not defined in "
            f"{m['file']} — a renamed/moved hot-path entry point "
            "must move in ROOTS too, or its host-cost certification "
            "silently vanishes"))
    return findings
