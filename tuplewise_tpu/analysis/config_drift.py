"""Pass 5 — config / CLI / doc drift [ISSUE 12].

``ServingConfig`` / ``TenancyConfig`` / ``ControllerConfig`` fields,
the ``harness.cli`` flags that set them, and the README/DESIGN prose
that teaches them must agree:

* ``config-field-unbound`` — a config field with neither a CLI flag
  (``--field-with-dashes``, or a declared alias like
  ``flush_timeout_s`` <-> ``--flush-timeout-ms``) nor a doc mention:
  a knob nobody can discover or set from the outside.
* ``doc-flag-unknown`` — a ``--flag`` mentioned in README/DESIGN that
  no argparse ``add_argument`` defines: the quickstart teaches a flag
  the CLI rejects.

Scope note: only the three serving-stack configs are checked — the
experiment configs (VarianceConfig etc.) generate their flags
mechanically from the dataclass and cannot drift.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleSet, call_name, dotted, literal_str,
)

_CHECKED_CONFIGS = ("ServingConfig", "TenancyConfig",
                    "ControllerConfig")

# field -> flag spelled differently than field.replace("_", "-")
_FLAG_ALIASES = {
    "flush_timeout_s": "flush-timeout-ms",
    "deadline_s": "deadline-ms",
    "weight": "tenant-weight",
    "flight_recorder_size": "flight-recorder-size",
}

_DOC_FLAG_RE = re.compile(r"--[a-z][a-z0-9-]+")
# flags documented but owned by other tools (XLA, pytest, pip, git)
_FOREIGN_FLAG_PREFIXES = ("--xla",)


def dataclass_fields(ms: ModuleSet) -> Dict[str, List[Tuple[str, int]]]:
    """{class name: [(field, line)]} for every dataclass in the
    corpus."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for path, mi in ms.modules.items():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            is_dc = False
            for deco in node.decorator_list:
                d = deco.func if isinstance(deco, ast.Call) else deco
                if dotted(d) in ("dataclasses.dataclass", "dataclass"):
                    is_dc = True
            if not is_dc:
                continue
            fields = []
            for sub in node.body:
                if isinstance(sub, ast.AnnAssign) \
                        and isinstance(sub.target, ast.Name):
                    fields.append((sub.target.id, sub.lineno))
            out.setdefault(node.name, fields)
    return out


def cli_flags(ms: ModuleSet) -> Set[str]:
    """Every literal ``--flag`` passed to an ``add_argument`` call in
    the corpus (harness CLI and the scripts' own parsers). When any
    parser generates flags mechanically from a dataclass
    (``add_argument`` with a computed first argument, the
    ``_add_variance_args`` pattern), every dataclass field's dashed
    form is admitted too — mechanical generation cannot drift."""
    flags: Set[str] = set()
    all_fields = dataclass_fields(ms)
    for path, mi in ms.modules.items():
        for fi in mi.iter_functions():
            dynamic = False
            generated: Set[str] = set()
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                if cn and cn.endswith("add_argument"):
                    literal_seen = False
                    for a in node.args:
                        s = literal_str(a)
                        if s and s.startswith("--"):
                            flags.add(s.lstrip("-"))
                            literal_seen = True
                    if node.args and not literal_seen:
                        dynamic = True
                elif cn in ("dataclasses.fields", "fields") \
                        and node.args \
                        and isinstance(node.args[0], ast.Name):
                    generated.add(node.args[0].id)
            if dynamic:
                # flags generated mechanically from the dataclass the
                # same function iterates — those cannot drift
                for cname in generated:
                    for f, _ in all_fields.get(cname, ()):
                        flags.add(f.replace("_", "-"))
    return flags


def _config_paths(ms: ModuleSet) -> Dict[str, Tuple[str, int]]:
    locs: Dict[str, Tuple[str, int]] = {}
    for path, mi in ms.modules.items():
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ClassDef) \
                    and node.name in _CHECKED_CONFIGS:
                locs.setdefault(node.name, (path, node.lineno))
    return locs


def run(ms: ModuleSet) -> List[Finding]:
    fields = dataclass_fields(ms)
    flags = cli_flags(ms)
    locs = _config_paths(ms)
    doc_text = "\n".join(ms.texts.values())
    findings: List[Finding] = []

    for cname in _CHECKED_CONFIGS:
        if cname not in fields or cname not in locs:
            continue
        path, _ = locs[cname]
        for field, line in fields[cname]:
            flag = _FLAG_ALIASES.get(field, field.replace("_", "-"))
            if flag in flags:
                continue
            # doc mention: the bare field name as a word (backticked
            # or prose) OR its dashed flag form in README/DESIGN — a
            # doc teaching `--flush-timeout-ms` documents the field
            # even before the parser defines it [ISSUE 13 satellite]
            if re.search(rf"\b{re.escape(field)}\b", doc_text) \
                    or f"--{flag}" in doc_text:
                continue
            findings.append(Finding(
                "config-field-unbound", path, line,
                f"{cname}.{field}",
                f"{cname}.{field} has no CLI flag (--{flag}) and no "
                "README/DESIGN mention — an undiscoverable knob"))

    for doc_path, text in ms.texts.items():
        seen: Set[str] = set()
        for m in _DOC_FLAG_RE.finditer(text):
            tok = m.group(0)
            if tok in seen:
                continue
            seen.add(tok)
            if any(tok.startswith(p) for p in _FOREIGN_FLAG_PREFIXES) \
                    and tok.lstrip("-") not in flags:
                continue
            if tok.lstrip("-") not in flags:
                findings.append(Finding(
                    "doc-flag-unknown", doc_path, 0, tok,
                    f"{doc_path} mentions {tok} but no argparse "
                    "definition exists — the doc teaches a flag the "
                    "CLI rejects"))
    return findings
