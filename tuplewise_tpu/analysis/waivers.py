"""Waiver file + ratchet semantics [ISSUE 12].

Findings are suppressible ONLY through the committed
``analysis/waivers.toml``. Each entry must carry a written
justification, names one finding fingerprint family, and absorbs a
BOUNDED number of findings::

    [[waiver]]
    rule = "lock-held-blocking"
    file = "tuplewise_tpu/serving/index.py"
    symbol = "ExactAucIndex.insert_batch::*"
    count = 3
    reason = "the cv IS the statistic's consistency boundary: ..."

Matching: ``rule`` and ``file`` exact, ``symbol`` a glob (``*``
matches everything when omitted — but then ``count`` bounds it).
**Ratchet**: a waiver matches at most ``count`` findings (default 1);
finding number ``count+1`` under the same pattern is NEW damage and
fails the run even though its older siblings are waived. Waivers that
match nothing are reported (``unused_waivers``) so stale entries get
pruned; ``strict`` turns them into failures.

The parser is a deliberate TOML subset (``[[waiver]]`` tables with
string/int scalar keys and ``#`` comments) — the container has neither
``tomllib`` (3.10) nor a third-party toml package, and the waiver
format needs nothing more.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Dict, List, Tuple

from tuplewise_tpu.analysis.core import Finding

_MIN_REASON = 20    # characters; "perf" is not a justification


class WaiverError(ValueError):
    """The waiver file is malformed or an entry lacks justification."""


@dataclasses.dataclass
class Waiver:
    rule: str
    file: str
    reason: str
    symbol: str = "*"
    count: int = 1
    line: int = 0

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule and f.file == self.file
                and fnmatch.fnmatchcase(f.symbol, self.symbol))


def parse_toml_subset(text: str) -> List[dict]:
    """``[[waiver]]`` tables of scalar keys; raises WaiverError on
    anything outside the subset so a typo never silently un-waives."""
    entries: List[dict] = []
    current: dict = {}
    in_table = False
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            if in_table:
                entries.append(current)
            current = {"__line__": lineno}
            in_table = True
            continue
        if line.startswith("["):
            raise WaiverError(
                f"waivers.toml:{lineno}: only [[waiver]] tables are "
                f"supported, got {line!r}")
        if "=" not in line or not in_table:
            raise WaiverError(
                f"waivers.toml:{lineno}: expected 'key = value' "
                f"inside a [[waiver]] table, got {line!r}")
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if val.startswith('"') and val.endswith('"') and len(val) >= 2:
            parsed: object = val[1:-1]
        elif val.lstrip("-").isdigit():
            parsed = int(val)
        else:
            raise WaiverError(
                f"waivers.toml:{lineno}: value for {key!r} must be a "
                f'"double-quoted string" or an integer, got {val!r}')
        current[key] = parsed
    if in_table:
        entries.append(current)
    return entries


def load_waivers(text: str) -> List[Waiver]:
    out = []
    for ent in parse_toml_subset(text):
        line = ent.pop("__line__", 0)
        unknown = set(ent) - {"rule", "file", "symbol", "count",
                              "reason"}
        if unknown:
            raise WaiverError(
                f"waivers.toml:{line}: unknown keys {sorted(unknown)}")
        for req in ("rule", "file", "reason"):
            if not ent.get(req):
                raise WaiverError(
                    f"waivers.toml:{line}: missing required key "
                    f"{req!r}")
        if len(str(ent["reason"]).strip()) < _MIN_REASON:
            raise WaiverError(
                f"waivers.toml:{line}: reason too short — every "
                "waiver carries a real written justification "
                f"(≥ {_MIN_REASON} chars)")
        count = int(ent.get("count", 1))
        if count < 1:
            raise WaiverError(
                f"waivers.toml:{line}: count must be >= 1")
        out.append(Waiver(rule=str(ent["rule"]), file=str(ent["file"]),
                          reason=str(ent["reason"]),
                          symbol=str(ent.get("symbol", "*")),
                          count=count, line=line))
    return out


def apply_waivers(findings: List[Finding], waivers: List[Waiver]
                  ) -> Tuple[List[Finding], List[Tuple[Finding, Waiver]],
                             List[Waiver]]:
    """(unwaived, [(finding, waiver)], unused_waivers). Each waiver
    absorbs at most ``count`` findings — the ratchet: the count+1'th
    match is returned as unwaived."""
    budget: Dict[int, int] = {i: w.count for i, w in enumerate(waivers)}
    used: Dict[int, int] = {i: 0 for i in range(len(waivers))}
    unwaived: List[Finding] = []
    waived: List[Tuple[Finding, Waiver]] = []
    for f in findings:
        hit = None
        for i, w in enumerate(waivers):
            if w.matches(f) and budget[i] > 0:
                hit = i
                break
        if hit is None:
            unwaived.append(f)
        else:
            budget[hit] -= 1
            used[hit] += 1
            waived.append((f, waivers[hit]))
    unused = [w for i, w in enumerate(waivers) if used[i] == 0]
    return unwaived, waived, unused
