"""Pass — exception-flow + resource-lifecycle analysis [ISSUE 15
tentpole].

The serving stack's liveness rests on conventions no pass checked:
every ``Future`` handed to a caller MUST resolve on every path of its
owning scope (the pre-PR-8 fleet-close leak left "block"-policy
producers hanging forever; the pre-PR-11 reaper-vs-apply race
double-resolved and crashed the batcher), every thread must be
daemonized or joined, every WAL/snapshot/metrics handle must close on
exception paths, and every typed serving error must be visible to the
wire protocol, the doctor, and the docs. Five rule families:

* ``future-leak`` — a function resolves futures (``set_result``) but
  an exception between dispatch and resolution leaves them
  unresolved: no enclosing ``try`` (in the function or, transitively,
  in a caller up to 3 frames) has a handler that ``set_exception``\\ s
  the stranded futures. This is the hole class behind the pre-PR-8
  fleet close leak.
* ``future-double-resolve`` — in a class that resolves futures from
  ≥ 2 methods (apply path + reaper + close are different threads), a
  resolution site with neither a ``.done()`` guard nor a
  ``try``-arbitration wrapper: the loser of the race raises
  ``InvalidStateError`` on the resolving thread (the pre-PR-11
  reaper-vs-apply shape).
* ``future-close-leak`` — a class that queues future-carrying
  requests whose ``close()``/``shutdown()`` never reaches a drain
  that fails them: producers blocked on the dead engine hang forever.
* ``thread-undisciplined`` — a ``Thread``/``Timer`` constructed
  neither ``daemon=True`` nor joined/cancelled from a lifecycle
  method (``close``/``stop``/``shutdown``/``__exit__``/``join``):
  process exit (or SIGTERM) wedges on it.
* ``handle-leak`` — ``open()`` outside a ``with``: a local handle
  with no ``try/finally`` close leaks on the exception path; an
  attribute-stored handle is accepted only when the owning class has
  a close-like method that closes it.

* error taxonomy (the telemetry_xref discipline extended to errors):
  every typed ``*Error`` class DEFINED AND RAISED in ``serving/*``
  must be (a) protocol-handled — an ``except`` clause whose handler
  builds a ``{"error": ...}`` wire response (the serve JSONL loop),
  else ``error-unhandled-protocol``; (b) doctor-visible — the class
  name, or a counter incremented in the raising function, appears in
  ``obs/report.py``/``obs/doctor.py``, else
  ``error-not-doctor-visible``; (c) documented — mentioned in
  README/DESIGN, else ``error-undocumented``.

Both historical bugs are seeded regression fixtures in
``tests/test_analysis_lifecycle.py``; the live repo is
clean-modulo-waivers with written justifications (first-run triage,
like PRs 12/13).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tuplewise_tpu.analysis.core import (
    Finding, ModuleInfo, ModuleSet, call_name, dotted, parent_map,
)

FuncKey = Tuple[str, str, str]

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}
_FUTURE_CTORS = {"Future", "concurrent.futures.Future",
                 "futures.Future"}
_CLOSE_METHODS = ("close", "stop", "shutdown", "__exit__", "join",
                  "checkpoint_and_close")
_MAX_CALLER_DEPTH = 3

#: error-taxonomy scope: typed errors defined+raised here are part of
#: the serving contract
_ERROR_SCOPE = "tuplewise_tpu/serving/"
_OBS_CONSUMERS = ("tuplewise_tpu/obs/report.py",
                  "tuplewise_tpu/obs/doctor.py")


def _is_future_expr(node: ast.AST) -> bool:
    """``<x>.future`` or a name bound from request iteration — the
    attribute spelling is the repo-wide convention."""
    if isinstance(node, ast.Attribute) and node.attr == "future":
        return True
    d = dotted(node)
    return d is not None and d.split(".")[-1] == "future"


def _resolution_calls(node: ast.AST) -> List[Tuple[ast.Call, str]]:
    """(call, kind) for every ``*.future.set_result/set_exception``
    under ``node`` (excluding nested defs)."""
    out: List[Tuple[ast.Call, str]] = []
    stack = [node]
    while stack:
        cur = stack.pop()
        for sub in ast.iter_child_nodes(cur):
            if isinstance(sub, (ast.FunctionDef,
                                ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in ("set_result",
                                          "set_exception") \
                    and _is_future_expr(sub.func.value):
                out.append((sub, sub.func.attr))
            stack.append(sub)
    return out


def _protecting_try(pm: Dict[ast.AST, ast.AST],
                    node: ast.AST) -> Optional[ast.Try]:
    """The nearest enclosing Try whose HANDLERS contain a
    ``set_exception`` resolution (the fail-the-run pattern) — the
    exception path that resolves stranded futures. ``try/finally``
    without such a handler does not protect."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        parent = pm.get(cur)
        if isinstance(parent, ast.Try) and cur in parent.body:
            for h in parent.handlers:
                for call, kind in _resolution_calls(h):
                    if kind == "set_exception":
                        return parent
        if isinstance(parent, (ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            return None
        cur = parent
    return None


def _arbitration_try(pm: Dict[ast.AST, ast.AST],
                     node: ast.AST) -> bool:
    """True when ``node`` sits in a TIGHT Try whose handlers swallow
    the lost race: ``try: fut.set_exception(...) except ...: ...``
    (engine._expire_request). A broad umbrella try does NOT count —
    inside one, the InvalidStateError of a lost race would be
    mis-filed as a dispatch failure, which is exactly the pre-PR-11
    confusion; tight means the try body is (nearly) just the
    resolution."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        parent = pm.get(cur)
        if isinstance(parent, ast.Try) and cur in parent.body \
                and parent.handlers and len(parent.body) == 1:
            return True
        if isinstance(parent, (ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            return False
        cur = parent
    return False


def _done_guarded(pm: Dict[ast.AST, ast.AST], node: ast.AST) -> bool:
    """True when an enclosing If/While test (or a comprehension
    filter) consults ``.done()`` — the winner-takes-the-resolution
    idiom. A guard anywhere up the chain counts: the done-filter may
    select the loop's elements rather than wrap the call."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        parent = pm.get(cur)
        if isinstance(parent, (ast.If, ast.While, ast.IfExp)):
            for sub in ast.walk(parent.test):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "done":
                    return True
        if isinstance(parent, (ast.For, ast.AsyncFor)) \
                and isinstance(parent.iter, (ast.ListComp,
                                             ast.GeneratorExp)):
            for gen in parent.iter.generators:
                for cond in gen.ifs:
                    for sub in ast.walk(cond):
                        if isinstance(sub, ast.Call) \
                                and isinstance(sub.func,
                                               ast.Attribute) \
                                and sub.func.attr == "done":
                            return True
        if isinstance(parent, (ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            return False
        cur = parent
    return False


class _Corpus:
    """Shared indices: function nodes, parent maps, resolved callers."""

    def __init__(self, ms: ModuleSet):
        self.ms = ms
        self.funcs: Dict[FuncKey, ast.AST] = {}
        self.pmaps: Dict[str, Dict[ast.AST, ast.AST]] = {}
        for path, mi in ms.modules.items():
            self.pmaps[path] = parent_map(mi.tree)
            for fi in mi.iter_functions():
                self.funcs[(path, fi.cls or "", fi.qualname)] = fi.node
        # callee -> [(caller key, call node)] via the lock pass's
        # resolver semantics (self methods, typed attrs, local defs)
        from tuplewise_tpu.analysis import lock_order

        self.an, _ = lock_order.build_analysis(ms)
        self.callers: Dict[FuncKey,
                           List[Tuple[FuncKey, ast.Call]]] = {}
        for key, node in self.funcs.items():
            path, cls, qual = key
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    r = self.an.resolve_call(path, cls or None, sub,
                                             prefix=qual)
                    if r is not None and r != key:
                        self.callers.setdefault(r, []).append(
                            (key, sub))

    def enclosing_func(self, path: str,
                       node: ast.AST) -> Optional[FuncKey]:
        pm = self.pmaps[path]
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = pm.get(cur)
            if isinstance(cur, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                for key, fnode in self.funcs.items():
                    if key[0] == path and fnode is cur:
                        return key
        return None


# --------------------------------------------------------------------- #
# future resolution rules                                                #
# --------------------------------------------------------------------- #

def _caller_protected(corpus: _Corpus, key: FuncKey,
                      depth: int, seen: Set[FuncKey]) -> bool:
    """Every known caller path wraps the call (transitively) in a Try
    whose handlers set_exception — the engine's _dispatch umbrella."""
    if depth > _MAX_CALLER_DEPTH or key in seen:
        return False
    seen.add(key)
    sites = corpus.callers.get(key, [])
    if not sites:
        return False
    for caller, call in sites:
        pm = corpus.pmaps[caller[0]]
        if _protecting_try(pm, call) is not None:
            continue
        if _caller_protected(corpus, caller, depth + 1, seen):
            continue
        return False
    return True


def future_findings(ms: ModuleSet, corpus: _Corpus) -> List[Finding]:
    findings: List[Finding] = []
    resolver_methods: Dict[Tuple[str, str], Set[str]] = {}
    all_sites: List[Tuple[FuncKey, ast.Call, str]] = []
    for key, node in corpus.funcs.items():
        for call, kind in _resolution_calls(node):
            all_sites.append((key, call, kind))
            if key[1]:
                resolver_methods.setdefault(
                    (key[0], key[1]), set()).add(key[2])

    # future-leak: a set_result with no exception path that would
    # resolve the stranded futures
    leak_seen: Set[str] = set()
    for key, call, kind in all_sites:
        if kind != "set_result":
            continue
        path, cls, qual = key
        pm = corpus.pmaps[path]
        if _protecting_try(pm, call) is not None:
            continue
        if _caller_protected(corpus, key, 1, set()):
            continue
        sym = f"{qual}::set_result"
        if sym in leak_seen:
            continue
        leak_seen.add(sym)
        findings.append(Finding(
            "future-leak", path, call.lineno, sym,
            f"{qual} resolves request futures with set_result but no "
            "enclosing try (here or in any resolved caller, depth "
            f"<= {_MAX_CALLER_DEPTH}) has a handler that "
            "set_exception's them — an exception before this line "
            "strands every future in the batch and its callers hang "
            "until timeout (the pre-PR-8 fleet-close hole class)"))

    # future-double-resolve: unguarded resolution in a multi-resolver
    # class (two threads can race to resolve the same future)
    dbl_seen: Set[str] = set()
    for key, call, kind in all_sites:
        path, cls, qual = key
        if not cls or len(resolver_methods.get((path, cls),
                                               ())) < 2:
            continue
        pm = corpus.pmaps[path]
        if _done_guarded(pm, call) or _arbitration_try(pm, call):
            continue
        sym = f"{qual}::{kind}"
        if sym in dbl_seen:
            continue
        dbl_seen.add(sym)
        findings.append(Finding(
            "future-double-resolve", path, call.lineno, sym,
            f"{qual} calls {kind} without a .done() guard or a "
            f"try-arbitration wrapper, and {cls} resolves futures "
            f"from {len(resolver_methods[(path, cls)])} methods "
            "(different threads: apply / reaper / close) — the loser "
            "of the race raises InvalidStateError on the resolving "
            "thread (the pre-PR-11 reaper-vs-apply shape)"))

    # future-close-leak: queue-of-futures class whose close path
    # never reaches a set_exception drain
    for (path, cls), methods in sorted(resolver_methods.items()):
        mi = ms.modules[path]
        model_queues = _queue_attrs(mi, cls)
        if not model_queues or not _constructs_futures(mi, cls):
            continue
        close_keys = [
            (path, cls, f"{cls}.{m}")
            for m in mi.classes.get(cls, {})
            if m in _CLOSE_METHODS]
        if not close_keys:
            findings.append(Finding(
                "future-close-leak", path, 0, f"{cls}.close",
                f"{cls} queues future-carrying requests but has no "
                "close()/shutdown() at all — producers blocked on a "
                "dead engine hang forever"))
            continue
        if not any(_reaches_set_exception(corpus, k, 0, set())
                   for k in close_keys):
            node = corpus.funcs.get(close_keys[0])
            findings.append(Finding(
                "future-close-leak", path,
                getattr(node, "lineno", 0),
                f"{cls}.{close_keys[0][2].rsplit('.', 1)[-1]}",
                f"{cls}.close never reaches a drain that "
                "set_exception's the queued futures — every "
                "unapplied request (and every 'block'-policy "
                "producer waiting on queue capacity) hangs at "
                "shutdown (the pre-PR-8 fleet-close leak)"))
    return findings


def _queue_attrs(mi: ModuleInfo, cls: str) -> Set[str]:
    out = set()
    for attr, ctor in mi.attr_ctors.get(cls, {}).items():
        if ctor in ("queue.Queue", "Queue", "queue.LifoQueue",
                    "collections.deque", "deque"):
            out.add(attr)
    # dict-of-deques fleets: a dict attr written via setdefault(deque)
    for mnode in mi.classes.get(cls, {}).values():
        for sub in ast.walk(mnode):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "setdefault" \
                    and len(sub.args) >= 2:
                cn = call_name(sub.args[1]) if isinstance(
                    sub.args[1], ast.Call) else None
                if cn in ("collections.deque", "deque"):
                    d = dotted(sub.func.value)
                    if d and d.startswith("self."):
                        out.add(d[len("self."):])
    return out


def _constructs_futures(mi: ModuleInfo, cls: str) -> bool:
    """The class (or a request class it instantiates in-module)
    creates Futures."""
    req_classes = set()
    for mnode in mi.classes.get(cls, {}).values():
        for sub in ast.walk(mnode):
            if isinstance(sub, ast.Call):
                cn = call_name(sub)
                if cn in _FUTURE_CTORS:
                    return True
                if cn in mi.classes:
                    req_classes.add(cn)
    for rc in req_classes:
        for mnode in mi.classes.get(rc, {}).values():
            for sub in ast.walk(mnode):
                if isinstance(sub, ast.Call) \
                        and call_name(sub) in _FUTURE_CTORS:
                    return True
    return False


def _reaches_set_exception(corpus: _Corpus, key: FuncKey,
                           depth: int, seen: Set[FuncKey]) -> bool:
    if depth > 4 or key in seen:
        return False
    seen.add(key)
    node = corpus.funcs.get(key)
    if node is None:
        return False
    for _call, kind in _resolution_calls(node):
        if kind == "set_exception":
            return True
    path, cls, qual = key
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            r = corpus.an.resolve_call(path, cls or None, sub,
                                       prefix=qual)
            if r is not None and r != key \
                    and _reaches_set_exception(corpus, r, depth + 1,
                                               seen):
                return True
    return False


# --------------------------------------------------------------------- #
# thread / timer lifecycle                                               #
# --------------------------------------------------------------------- #

def thread_findings(ms: ModuleSet, corpus: _Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for path, mi in sorted(ms.modules.items()):
        pm = corpus.pmaps[path]
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            is_timer = cn in _TIMER_CTORS
            if cn not in _THREAD_CTORS and not is_timer:
                continue
            if any(k.arg == "daemon"
                   and isinstance(k.value, ast.Constant)
                   and k.value.value is True
                   for k in node.keywords):
                continue
            # stored where? self.attr = Thread(...) -> accept when a
            # lifecycle method joins/cancels it; local t = Thread(...)
            # -> accept a join/cancel in the same function, or an
            # immediate daemon flag assignment
            parent = pm.get(node)
            target_attr = None
            local_name = None
            if isinstance(parent, ast.Assign) and parent.targets:
                d = dotted(parent.targets[0])
                if d and d.startswith("self."):
                    target_attr = d[len("self."):]
                elif d and "." not in d:
                    local_name = d
            key = corpus.enclosing_func(path, node)
            fname = key[2] if key else "<module>"
            cls = key[1] if key else ""
            ok = False
            closers = ("cancel",) if is_timer else ("join",)
            if target_attr and cls:
                ok = _attr_closed(mi, cls, target_attr,
                                  closers + ("daemon",))
            elif local_name and key is not None:
                fnode = corpus.funcs[key]
                ok = _local_closed(fnode, local_name, closers)
            if ok:
                continue
            kind = "Timer" if is_timer else "Thread"
            findings.append(Finding(
                "thread-undisciplined", path, node.lineno,
                f"{fname}::{kind}",
                f"{fname} constructs a {kind} that is neither "
                "daemon=True nor joined/cancelled from a lifecycle "
                "method — process exit wedges on it (or the timer "
                "fires into a torn-down object)"))
    return findings


def _attr_closed(mi: ModuleInfo, cls: str, attr: str,
                 closers: Tuple[str, ...]) -> bool:
    for mnode in mi.classes.get(cls, {}).values():
        for sub in ast.walk(mnode):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in closers:
                d = dotted(sub.func.value)
                if d == f"self.{attr}":
                    return True
            if isinstance(sub, ast.Assign) and sub.targets:
                d = dotted(sub.targets[0])
                if d == f"self.{attr}.daemon" \
                        and isinstance(sub.value, ast.Constant) \
                        and sub.value.value is True:
                    return True
    return False


def _local_closed(fnode: ast.AST, name: str,
                  closers: Tuple[str, ...]) -> bool:
    for sub in ast.walk(fnode):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in closers \
                and dotted(sub.func.value) == name:
            return True
        if isinstance(sub, ast.Assign) and sub.targets:
            d = dotted(sub.targets[0])
            if d == f"{name}.daemon" \
                    and isinstance(sub.value, ast.Constant) \
                    and sub.value.value is True:
                return True
    return False


# --------------------------------------------------------------------- #
# file-handle lifecycle                                                  #
# --------------------------------------------------------------------- #

def handle_findings(ms: ModuleSet, corpus: _Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for path, mi in sorted(ms.modules.items()):
        pm = corpus.pmaps[path]
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in ("open", "io.open",
                                            "os.fdopen")):
                continue
            parent = pm.get(node)
            if isinstance(parent, ast.withitem):
                continue            # with open(...) as f: fine
            if isinstance(parent, ast.Attribute):
                continue            # open(...).read() one-shot chain
            key = corpus.enclosing_func(path, node)
            fname = key[2] if key else "<module>"
            target_attr = local_name = None
            if isinstance(parent, ast.Assign) and parent.targets:
                d = dotted(parent.targets[0])
                if d and d.startswith("self."):
                    target_attr = d[len("self."):]
                elif d and "." not in d:
                    local_name = d
            ok = False
            if target_attr and key and key[1]:
                ok = _attr_closed(mi, key[1], target_attr, ("close",))
            elif local_name and key is not None:
                ok = _finally_closed(corpus.funcs[key], pm, node,
                                     local_name)
            if ok:
                continue
            findings.append(Finding(
                "handle-leak", path, node.lineno,
                f"{fname}::open",
                f"{fname} opens a file outside `with` and no "
                "try/finally (local) or owning close() method "
                "(attribute) closes it — the handle leaks on the "
                "exception path; WAL/snapshot/metrics files must "
                "close deterministically"))
    return findings


def _finally_closed(fnode: ast.AST, pm: Dict[ast.AST, ast.AST],
                    node: ast.AST, name: str) -> bool:
    """The open site sits inside (or immediately before) a Try whose
    finalbody closes the local — or the function returns the handle
    (ownership transferred)."""
    for sub in ast.walk(fnode):
        if isinstance(sub, ast.Try) and sub.finalbody:
            for f in sub.finalbody:
                for c in ast.walk(f):
                    if isinstance(c, ast.Call) \
                            and isinstance(c.func, ast.Attribute) \
                            and c.func.attr == "close" \
                            and dotted(c.func.value) == name:
                        return True
        if isinstance(sub, ast.Return) and sub.value is not None \
                and dotted(sub.value) == name:
            return True
    return False


# --------------------------------------------------------------------- #
# error taxonomy cross-reference                                         #
# --------------------------------------------------------------------- #

def _serving_errors(ms: ModuleSet) -> List[Tuple[str, str, int]]:
    """(class name, defining path, line) for typed errors defined AND
    raised in serving/*."""
    defined: Dict[str, Tuple[str, int]] = {}
    for path, mi in ms.modules.items():
        if not path.startswith(_ERROR_SCOPE):
            continue
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef) \
                    and node.name.endswith("Error"):
                defined.setdefault(node.name, (path, node.lineno))
    raised: Set[str] = set()
    for path, mi in ms.modules.items():
        if not path.startswith(_ERROR_SCOPE):
            continue
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                d = dotted(exc)
                if d is not None and d.split(".")[-1] in defined:
                    raised.add(d.split(".")[-1])
    return sorted((n,) + defined[n] for n in raised)


def _protocol_handlers(ms: ModuleSet) -> Set[str]:
    """Error class names caught by an except clause whose handler
    builds a ``{"error": ...}`` wire response (the serve JSONL
    protocol loop)."""
    out: Set[str] = set()
    for path, mi in ms.modules.items():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.ExceptHandler) \
                    or node.type is None:
                continue
            types = node.type.elts if isinstance(
                node.type, ast.Tuple) else [node.type]
            names = {(dotted(t) or "").split(".")[-1] for t in types}
            has_wire = any(
                isinstance(sub, ast.Dict) and any(
                    isinstance(k, ast.Constant) and k.value == "error"
                    for k in sub.keys)
                for sub in ast.walk(node))
            if has_wire:
                out.update(n for n in names if n)
    return out


def _raise_site_counters(ms: ModuleSet, ename: str) -> Set[str]:
    """Metric-name literals adjacent to the raises of ``ename``: a
    counter incremented in the raising function, resolved through the
    ``self._c_x = m.counter("lit")`` registry idiom or inline
    ``...counter("lit"...)`` calls."""
    out: Set[str] = set()
    for path, mi in ms.modules.items():
        if not path.startswith(_ERROR_SCOPE):
            continue
        # class attr -> counter literal map for this module
        attr_lit: Dict[Tuple[str, str], str] = {}
        for cname, methods in mi.classes.items():
            for mnode in methods.values():
                for sub in ast.walk(mnode):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.value, ast.Call):
                        cn = call_name(sub.value) or ""
                        if cn.split(".")[-1] in ("counter",):
                            d = dotted(sub.targets[0])
                            lit = (sub.value.args
                                   and isinstance(sub.value.args[0],
                                                  ast.Constant)
                                   and sub.value.args[0].value)
                            if d and d.startswith("self.") and lit:
                                attr_lit[(cname,
                                          d[len("self."):])] = lit
        for fi in mi.iter_functions():
            raises_here = any(
                isinstance(n, ast.Raise) and n.exc is not None
                and (dotted(n.exc.func) if isinstance(n.exc, ast.Call)
                     else dotted(n.exc) or "").split(".")[-1] == ename
                for n in ast.walk(fi.node))
            if not raises_here:
                continue
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Call):
                    continue
                cn = call_name(sub) or ""
                leaf = cn.split(".")[-1]
                if leaf == "inc" and cn.startswith("self."):
                    attr = cn[len("self."):-len(".inc")]
                    lit = attr_lit.get((fi.cls or "", attr))
                    if lit:
                        out.add(lit)
                elif leaf == "counter" and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    out.add(sub.args[0].value)
                elif leaf == "record" and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    out.add(sub.args[0].value)   # flight event kind
    return out


def error_findings(ms: ModuleSet) -> List[Finding]:
    findings: List[Finding] = []
    handled = _protocol_handlers(ms)
    obs_src = "\n".join(ms.modules[p].source for p in _OBS_CONSUMERS
                        if p in ms.modules)
    doc_src = "\n".join(ms.texts.values())
    for ename, path, line in _serving_errors(ms):
        if ename not in handled:
            findings.append(Finding(
                "error-unhandled-protocol", path, line, ename,
                f"typed serving error {ename} is raised on the "
                "request path but no except handler maps it to a "
                '{"error": ...} wire response — a serve-loop client '
                "sees a broken pipe instead of a typed, retryable "
                "failure"))
        visible = re.search(rf"\b{re.escape(ename)}\b", obs_src)
        if not visible:
            counters = _raise_site_counters(ms, ename)
            visible = any(
                re.search(rf"\b{re.escape(c)}\b", obs_src)
                for c in counters)
        if not visible:
            findings.append(Finding(
                "error-not-doctor-visible", path, line, ename,
                f"typed serving error {ename} has no doctor/report "
                "consumer: neither the class name nor any counter "
                "incremented at its raise sites appears in "
                "obs/report.py or obs/doctor.py — operators cannot "
                "see this failure mode post-hoc"))
        if not re.search(rf"\b{re.escape(ename)}\b", doc_src):
            findings.append(Finding(
                "error-undocumented", path, line, ename,
                f"typed serving error {ename} is part of the serving "
                "contract but README.md/docs/DESIGN.md never mention "
                "it — callers cannot code against an error taxonomy "
                "the docs hide"))
    return findings


# --------------------------------------------------------------------- #
# the pass                                                               #
# --------------------------------------------------------------------- #

def run(ms: ModuleSet) -> List[Finding]:
    corpus = _Corpus(ms)
    findings: List[Finding] = []
    findings.extend(future_findings(ms, corpus))
    findings.extend(thread_findings(ms, corpus))
    findings.extend(handle_findings(ms, corpus))
    findings.extend(error_findings(ms))
    return findings
