"""tuplewise_tpu — a TPU-native framework for distributed tuplewise
(U-statistic) estimation and learning.

Re-implements, TPU-first, the capabilities of the reference codebase
``RobinVogel/Trade-offs-in-Distributed-Tuplewise-Estimation-and-Learning``
(companion code to "Trade-offs in Large-Scale Distributed Tuplewise
Estimation and Learning", NeurIPS 2019, arXiv:1906.09234).

NOTE on citations: the reference mount at /root/reference was empty at
survey time (see SURVEY.md §0), so docstrings cite the paper's algorithms
via SURVEY.md sections ([SURVEY §x.y]) rather than reference file:line.

Layer map (SURVEY §2):
  L0 data        -> tuplewise_tpu.data
  L1 kernels     -> tuplewise_tpu.ops.kernels
  L2 partitioner -> tuplewise_tpu.parallel.partition
  L3 estimators  -> tuplewise_tpu.estimators  (Estimator(backend=...))
  L5 learner     -> tuplewise_tpu.models
  L4/L6 harness  -> tuplewise_tpu.harness
  comm backend   -> tuplewise_tpu.parallel (mesh, ring collectives)
"""

from tuplewise_tpu.utils.compat import (
    ensure_lax_axis_size as _ensure_lax_axis_size,
    ensure_shard_map as _ensure_shard_map,
)

_ensure_shard_map()
_ensure_lax_axis_size()

from tuplewise_tpu.estimators.estimator import Estimator
from tuplewise_tpu.estimators.streaming import StreamingEstimator
from tuplewise_tpu.ops.kernels import (
    Kernel,
    auc_kernel,
    hinge_kernel,
    logistic_kernel,
    triplet_hinge_kernel,
    triplet_indicator_kernel,
    get_kernel,
)

__version__ = "0.1.0"

__all__ = [
    "Estimator",
    "StreamingEstimator",
    "Kernel",
    "auc_kernel",
    "hinge_kernel",
    "logistic_kernel",
    "triplet_hinge_kernel",
    "triplet_indicator_kernel",
    "get_kernel",
    "__version__",
]
