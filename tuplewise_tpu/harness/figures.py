"""Figure generation: the paper-shaped trade-off plots [SURVEY §2 L4/L6].

Kept separate from measurement (harness emits JSONL; figures consume it
or fresh results) per SURVEY §5.6. Matplotlib only; written to PNG.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np


def _results(path_or_list):
    if isinstance(path_or_list, (list, tuple)):
        return list(path_or_list)
    with open(path_or_list) as f:
        return [json.loads(line) for line in f if line.strip()]


def _plot_variance_loglog(results, out_png, x_key, xlabel, series_label,
                          baseline=None, theory=None) -> str:
    """Shared log-log variance plot: measured series, optional
    closed-form Hoeffding overlay, optional complete-U floor."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rs = _results(results)
    x = [r["config"][x_key] for r in rs]
    var = [r["variance"] for r in rs]
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.loglog(x, var, "o-", label=series_label)
    if theory:
        ax.loglog(*zip(*theory), ":", c="C1",
                  label="Hoeffding closed form")
    if baseline is not None:
        ax.axhline(baseline["variance"], ls="--", c="gray",
                   label="complete $U_n$")
    ax.set_xlabel(xlabel)
    ax.set_ylabel("estimator variance")
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_variance_vs_rounds(results, out_png: str,
                            baseline: Optional[dict] = None,
                            theory: Optional[list] = None) -> str:
    """Variance vs T (repartitions) — the communication trade-off curve
    [SURVEY §1.2 item 3]; optionally overlays the complete-U variance
    and the closed-form Hoeffding prediction (list of (T, var))."""
    return _plot_variance_loglog(
        results, out_png, "n_rounds",
        "repartition rounds T (communication)",
        "repartitioned $U_{N,T}$", baseline, theory,
    )


def plot_variance_vs_workers(results, out_png: str,
                             baseline: Optional[dict] = None,
                             theory: Optional[list] = None) -> str:
    """Variance of the local-average estimator vs worker count N — the
    paper's 'what local averaging costs' figure [SURVEY §1.2 item 2].
    The gap off the complete-U floor scales as ~1/m with m = n/N
    per-worker rows, so it only opens up once blocks get small."""
    return _plot_variance_loglog(
        results, out_png, "n_workers", "workers N",
        "local average $U^{loc}_N$", baseline, theory,
    )


def _wc_var(rs):
    """(wall-clock per estimate, variance) series for a result list —
    the one place the per-estimate normalization lives."""
    return ([r["wallclock_s"] / r["n_reps"] for r in rs],
            [r["variance"] for r in rs])


def plot_variance_vs_wallclock(results, out_png: str) -> str:
    """Variance vs wall-clock — the headline trade-off axis
    (BASELINE.json:2)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rs = _results(results)
    wc, var = _wc_var(rs)
    labels = [str(r["config"].get("n_rounds", "")) for r in rs]
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.loglog(wc, var, "o-")
    for x, y, l in zip(wc, var, labels):
        ax.annotate(f"T={l}", (x, y), fontsize=7,
                    textcoords="offset points", xytext=(4, 4))
    ax.set_xlabel("wall-clock per estimate [s]")
    ax.set_ylabel("estimator variance")
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_variance_vs_pairs(results, out_png: str) -> str:
    """Variance vs sampled-pair budget B (incomplete U) [SURVEY §1.1]."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rs = _results(results)
    B = [r["config"]["n_pairs"] for r in rs]
    var = [r["variance"] for r in rs]
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.loglog(B, var, "o-", label=r"incomplete $\tilde{U}_B$")
    ax.set_xlabel("sampled pairs B")
    ax.set_ylabel("estimator variance")
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_learning_curve(history, out_png: str,
                        auc_before: Optional[float] = None,
                        auc_after: Optional[float] = None) -> str:
    """Pairwise-SGD training curve [SURVEY §2 L5]: per-step surrogate
    loss, with before/after test AUC annotated when provided."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    loss = np.asarray(history["loss"])
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.plot(np.arange(len(loss)), loss, lw=1.2)
    ax.set_xlabel("SGD step")
    ax.set_ylabel("pairwise surrogate loss")
    if auc_before is not None and auc_after is not None:
        ax.set_title(
            f"test AUC {auc_before:.3f} -> {auc_after:.3f}", fontsize=9
        )
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_frontier(groups, out_png: str) -> str:
    """The headline axis in one picture [BASELINE.json:2]: estimator
    variance vs wall-clock per estimate for every scheme family.
    ``groups`` maps a series label to a list of harness result dicts;
    each point is one committed experiment."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(5.5, 4))
    markers = {"complete": "*", "incomplete": "o", "repartitioned": "s",
               "local": "D"}
    for label, rs in groups.items():
        rs = _results(rs)
        if not rs:  # tolerate not-yet-populated series
            continue
        wc, var = _wc_var(rs)
        scheme = rs[0]["config"]["scheme"]
        ax.loglog(wc, var, markers.get(scheme, "o"),
                  ls="-" if len(rs) > 1 else "",
                  ms=9 if scheme == "complete" else 5, label=label)
    ax.set_xlabel("wall-clock per estimate [s]")
    ax.set_ylabel("estimator variance")
    ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def _nr_label(row) -> str:
    nr = row.get("n_r")
    return "never" if nr is None else f"$n_r$={nr}"


def plot_learning_curves(rows, out_png: str, title: str = "") -> str:
    """Learning-side trade-off curves [SURVEY §1.3, §4.4]: mean held-out
    AUC vs SGD steps, one line per repartition period n_r, +-2 SE band
    over the Monte-Carlo seeds. ``rows`` are learning-suite records
    (same dataset/N/B) with eval_steps / auc_mean / auc_se arrays."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = _results(rows)
    fig, ax = plt.subplots(figsize=(5.5, 4))
    lo, hi = np.inf, -np.inf
    # frequent repartition first so legend order mirrors the physics
    for row in sorted(rows, key=lambda r: (r.get("n_r") is None,
                                           r.get("n_r") or 0)):
        s = np.asarray(row["eval_steps"])
        mu = np.asarray(row["auc_mean"])
        # n_seeds=1 rows carry null SEs (no spread estimate): plot the
        # mean with a zero-width band rather than crashing
        se = np.asarray(
            [0.0 if v is None else v for v in row["auc_se"]], float
        )
        (ln,) = ax.plot(s, mu, lw=1.4, label=_nr_label(row))
        ax.fill_between(s, mu - 2 * se, mu + 2 * se,
                        color=ln.get_color(), alpha=0.18, lw=0)
        tail = s >= 0.2 * s[-1]
        lo = min(lo, (mu - 3 * se)[tail].min())
        hi = max(hi, (mu + 3 * se)[tail].max())
    if np.isfinite(lo) and hi > lo:
        # zoom past the shared initial ramp: the per-n_r separation is
        # millis of AUC and invisible on the full [init, converged] range
        pad = 0.15 * (hi - lo)
        ax.set_ylim(lo - pad, hi + pad)
    ax.set_xlabel("SGD step")
    ax.set_ylabel("held-out AUC (zoomed to converged range)")
    if title:
        ax.set_title(title, fontsize=9)
    ax.legend(fontsize=8, title="repartition every", title_fontsize=8)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_auc_vs_comm(rows, out_png: str, title: str = "") -> str:
    """The learning analogue of variance-vs-T [VERDICT r2 next #1]:
    final held-out AUC (+-2 SE) against the number of communication
    (repartition) events the schedule paid, one line per worker count.
    Frequent repartition buys gradient quality with communication —
    the paper's learning trade-off in one picture."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = _results(rows)
    fig, ax = plt.subplots(figsize=(5.5, 4))
    by_n = {}
    for r in rows:
        by_n.setdefault(r["n_workers"], []).append(r)
    for N, rs in sorted(by_n.items()):
        rs = sorted(rs, key=lambda r: r["comm_events"])
        x = [r["comm_events"] for r in rs]
        y = [r["final_auc_mean"] for r in rs]
        e = [2 * (r["final_auc_se"] or 0.0) for r in rs]
        ax.errorbar(x, y, yerr=e, marker="o", ms=4, lw=1.2, capsize=2,
                    label=f"N={N}")
    ax.set_xscale("log")
    ax.set_xlabel("communication events (repartitions)")
    ax.set_ylabel("final held-out AUC")
    if title:
        ax.set_title(title, fontsize=9)
    ax.legend(fontsize=8, title="workers", title_fontsize=8)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_auc_vs_budget(rows, out_png: str, title: str = "") -> str:
    """Final held-out AUC vs per-worker pair budget B at fixed N, one
    line per repartition period — the learning analogue of the
    incomplete-U budget curve [SURVEY §1.2 item 4]. B=None rows
    (all local pairs) plot at x = m1*m2, the full local grid."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = _results(rows)
    fig, ax = plt.subplots(figsize=(5.5, 4))
    by_nr = {}
    for r in rows:
        by_nr.setdefault(r.get("n_r"), []).append(r)
    for nr in sorted(by_nr, key=lambda v: (v is None, v or 0)):
        rs = by_nr[nr]
        # sampled-B rows form the line; the all-local-pairs row plots
        # as a separate STAR at x = m1*m2 — same x when B happens to
        # equal the full grid, but distinguishable (swr sampling of the
        # grid is not the same estimator as the full grid)
        sampled = sorted(
            (r for r in rs if r["pairs_per_worker"] is not None),
            key=lambda r: r["pairs_per_worker"],
        )
        full = [r for r in rs if r["pairs_per_worker"] is None]
        color = None
        if sampled:
            x = [r["pairs_per_worker"] for r in sampled]
            y = [r["final_auc_mean"] for r in sampled]
            e = [2 * (r["final_auc_se"] or 0.0) for r in sampled]
            eb = ax.errorbar(x, y, yerr=e, marker="o", ms=4, lw=1.2,
                             capsize=2, label=_nr_label(rs[0]))
            color = eb.lines[0].get_color()
        for r in full:
            ax.errorbar(
                [r["m_per_worker"][0] * r["m_per_worker"][1]],
                [r["final_auc_mean"]],
                yerr=[2 * (r["final_auc_se"] or 0.0)],
                marker="*", ms=11, capsize=2, color=color,
                label=None if sampled else _nr_label(r),
            )
    ax.set_xscale("log")
    ax.set_xlabel("pairs per worker per step B (star = all local pairs)")
    ax.set_ylabel("final held-out AUC")
    if title:
        ax.set_title(title, fontsize=9)
    ax.legend(fontsize=8, title="repartition every", title_fontsize=8)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_sd_vs_comm(rows, out_png: str,
                    title: str = "") -> Optional[str]:
    """Across-seed SD of the final model vs communication events — the
    learning analogue of the estimator's variance-vs-T decay (RESULTS
    §6.1 finding 2). No closed-form guide is drawn: unlike the
    repartitioned ESTIMATOR (which averages all T rounds equally), a
    constant-lr SGD iterate only averages partitions inside its
    O(1/lr)-step memory, so the decay starts slower than T^(-1/2) and
    steepens once repartitions outpace that window — exactly what the
    measured curves show."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = [r for r in _results(rows) if r.get("final_auc_sd")]
    if not rows:   # all-n_seeds=1 suites have no spread to plot: skip
        return None   # (no file written — callers must null-check)
    fig, ax = plt.subplots(figsize=(5.5, 4))
    by_n = {}
    for r in rows:
        by_n.setdefault(r["n_workers"], []).append(r)
    for N, rs in sorted(by_n.items()):
        rs = sorted(rs, key=lambda r: r["comm_events"])
        x = [r["comm_events"] for r in rs]
        y = [r["final_auc_sd"] for r in rs]
        ax.loglog(x, y, "o-", ms=4, lw=1.2, label=f"N={N}")
    ax.set_xlabel("communication events (repartitions)")
    ax.set_ylabel("SD of final held-out AUC across partitions")
    if title:
        ax.set_title(title, fontsize=9)
    ax.legend(fontsize=8, title="workers", title_fontsize=8)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_design_budget(rows, out_png: str, title: str = "") -> str:
    """Final held-out AUC vs per-worker budget B, one line per pair
    DESIGN (swr/swor/bernoulli) at each repartition period — does the
    finite-population design reach a better budget-noise floor?
    [SURVEY §1.2 item 4; VERDICT r3 next #6]."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = _results(rows)
    fig, ax = plt.subplots(figsize=(5.5, 4))
    markers = {"swr": "o", "swor": "s", "bernoulli": "^"}
    for nr in sorted({r.get("n_r") for r in rows},
                     key=lambda v: (v is None, v or 0)):
        for design in ("swr", "swor", "bernoulli"):
            rs = sorted(
                (r for r in rows
                 if r.get("n_r") == nr
                 and r.get("pair_design", "swr") == design),
                key=lambda r: r["pairs_per_worker"],
            )
            if not rs:
                continue
            x = [r["pairs_per_worker"] for r in rs]
            y = [r["final_auc_mean"] for r in rs]
            e = [2 * (r["final_auc_se"] or 0.0) for r in rs]
            ax.errorbar(
                x, y, yerr=e, marker=markers[design], ms=4, lw=1.2,
                capsize=2,
                label=f"{design}, {_nr_label(rs[0])}",
            )
    ax.set_xlabel("pairs per worker per step B")
    ax.set_ylabel("final held-out AUC")
    if title:
        ax.set_title(title, fontsize=9)
    ax.legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_triplet_curves(rows, out_png: str, title: str = "") -> str:
    """Held-out triplet-accuracy curves of the degree-3 metric learner
    (models.triplet_sgd), one line per repartition period, one panel
    per task [VERDICT r3 next #9]."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = _results(rows)
    tasks = sorted({r["task"] for r in rows})
    fig, axes = plt.subplots(
        1, len(tasks), figsize=(5.0 * len(tasks), 4), squeeze=False
    )
    for ax, task in zip(axes[0], tasks):
        for r in sorted(
            (r for r in rows if r["task"] == task),
            key=lambda r: (r["n_r"] is None, r["n_r"] or 0),
        ):
            curve = r["acc_curve_mean"]
            steps = r["steps"]
            x = [steps * (i + 1) / len(curve)
                 for i in range(len(curve))]
            ax.plot([0] + x, [r["acc_init_mean"]] + list(curve),
                    marker="o", ms=3, lw=1.2, label=_nr_label(r))
        ax.set_xlabel("step")
        ax.set_ylabel("held-out triplet accuracy")
        ax.set_title(task, fontsize=9)
        ax.legend(fontsize=8)
    if title:
        fig.suptitle(title, fontsize=10)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png
