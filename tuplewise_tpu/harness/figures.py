"""Figure generation: the paper-shaped trade-off plots [SURVEY §2 L4/L6].

Kept separate from measurement (harness emits JSONL; figures consume it
or fresh results) per SURVEY §5.6. Matplotlib only; written to PNG.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np


def _results(path_or_list):
    if isinstance(path_or_list, (list, tuple)):
        return list(path_or_list)
    with open(path_or_list) as f:
        return [json.loads(line) for line in f if line.strip()]


def _plot_variance_loglog(results, out_png, x_key, xlabel, series_label,
                          baseline=None, theory=None) -> str:
    """Shared log-log variance plot: measured series, optional
    closed-form Hoeffding overlay, optional complete-U floor."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rs = _results(results)
    x = [r["config"][x_key] for r in rs]
    var = [r["variance"] for r in rs]
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.loglog(x, var, "o-", label=series_label)
    if theory:
        ax.loglog(*zip(*theory), ":", c="C1",
                  label="Hoeffding closed form")
    if baseline is not None:
        ax.axhline(baseline["variance"], ls="--", c="gray",
                   label="complete $U_n$")
    ax.set_xlabel(xlabel)
    ax.set_ylabel("estimator variance")
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_variance_vs_rounds(results, out_png: str,
                            baseline: Optional[dict] = None,
                            theory: Optional[list] = None) -> str:
    """Variance vs T (repartitions) — the communication trade-off curve
    [SURVEY §1.2 item 3]; optionally overlays the complete-U variance
    and the closed-form Hoeffding prediction (list of (T, var))."""
    return _plot_variance_loglog(
        results, out_png, "n_rounds",
        "repartition rounds T (communication)",
        "repartitioned $U_{N,T}$", baseline, theory,
    )


def plot_variance_vs_workers(results, out_png: str,
                             baseline: Optional[dict] = None,
                             theory: Optional[list] = None) -> str:
    """Variance of the local-average estimator vs worker count N — the
    paper's 'what local averaging costs' figure [SURVEY §1.2 item 2].
    The gap off the complete-U floor scales as ~1/m with m = n/N
    per-worker rows, so it only opens up once blocks get small."""
    return _plot_variance_loglog(
        results, out_png, "n_workers", "workers N",
        "local average $U^{loc}_N$", baseline, theory,
    )


def _wc_var(rs):
    """(wall-clock per estimate, variance) series for a result list —
    the one place the per-estimate normalization lives."""
    return ([r["wallclock_s"] / r["n_reps"] for r in rs],
            [r["variance"] for r in rs])


def plot_variance_vs_wallclock(results, out_png: str) -> str:
    """Variance vs wall-clock — the headline trade-off axis
    (BASELINE.json:2)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rs = _results(results)
    wc, var = _wc_var(rs)
    labels = [str(r["config"].get("n_rounds", "")) for r in rs]
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.loglog(wc, var, "o-")
    for x, y, l in zip(wc, var, labels):
        ax.annotate(f"T={l}", (x, y), fontsize=7,
                    textcoords="offset points", xytext=(4, 4))
    ax.set_xlabel("wall-clock per estimate [s]")
    ax.set_ylabel("estimator variance")
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_variance_vs_pairs(results, out_png: str) -> str:
    """Variance vs sampled-pair budget B (incomplete U) [SURVEY §1.1]."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rs = _results(results)
    B = [r["config"]["n_pairs"] for r in rs]
    var = [r["variance"] for r in rs]
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.loglog(B, var, "o-", label=r"incomplete $\tilde{U}_B$")
    ax.set_xlabel("sampled pairs B")
    ax.set_ylabel("estimator variance")
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_learning_curve(history, out_png: str,
                        auc_before: Optional[float] = None,
                        auc_after: Optional[float] = None) -> str:
    """Pairwise-SGD training curve [SURVEY §2 L5]: per-step surrogate
    loss, with before/after test AUC annotated when provided."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    loss = np.asarray(history["loss"])
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.plot(np.arange(len(loss)), loss, lw=1.2)
    ax.set_xlabel("SGD step")
    ax.set_ylabel("pairwise surrogate loss")
    if auc_before is not None and auc_after is not None:
        ax.set_title(
            f"test AUC {auc_before:.3f} -> {auc_after:.3f}", fontsize=9
        )
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png


def plot_frontier(groups, out_png: str) -> str:
    """The headline axis in one picture [BASELINE.json:2]: estimator
    variance vs wall-clock per estimate for every scheme family.
    ``groups`` maps a series label to a list of harness result dicts;
    each point is one committed experiment."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(5.5, 4))
    markers = {"complete": "*", "incomplete": "o", "repartitioned": "s",
               "local": "D"}
    for label, rs in groups.items():
        rs = _results(rs)
        if not rs:  # tolerate not-yet-populated series
            continue
        wc, var = _wc_var(rs)
        scheme = rs[0]["config"]["scheme"]
        ax.loglog(wc, var, markers.get(scheme, "o"),
                  ls="-" if len(rs) > 1 else "",
                  ms=9 if scheme == "complete" else 5, label=label)
    ax.set_xlabel("wall-clock per estimate [s]")
    ax.set_ylabel("estimator variance")
    ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(out_png, dpi=150)
    plt.close(fig)
    return out_png
