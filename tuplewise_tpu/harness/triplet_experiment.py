"""BASELINE config 4: degree-3 triplet metric-learning statistics on
MNIST embeddings [SURVEY §1.1 "Degree-3", §3 "Triplet kernel"].

For each class c, the degree-(2,1) triplet U-statistic takes (anchor,
positive) pairs from class c and negatives from the other classes:

    U_c = mean_{i != j in c, k not in c} h(x_i, x_j, y_k)

and the reported statistic averages U_c over classes — with the
indicator kernel this is the class-balanced triplet accuracy of the
embedding (the fraction of relative-similarity constraints satisfied).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tuplewise_tpu.data import load_mnist_embeddings
from tuplewise_tpu.estimators.estimator import Estimator


def triplet_mnist_statistic(
    kernel: str = "triplet_indicator",
    backend: str = "jax",
    n: int = 2000,
    n_pairs: Optional[int] = 20_000,
    classes: Optional[list] = None,
    seed: int = 0,
    path: Optional[str] = None,
    **backend_opts,
) -> dict:
    """Per-class triplet U-statistics over MNIST embeddings.

    n_pairs None -> complete statistic (O(n_c^2 * n) — small n only);
    otherwise the incomplete estimator with B=n_pairs sampled triplets.
    """
    E, labels, meta = load_mnist_embeddings(path=path, n=n, seed=seed)
    est = Estimator(kernel, backend=backend, **backend_opts)
    per_class = {}
    for c in sorted(set(classes or np.unique(labels).tolist())):
        Xc = E[labels == c]
        Yc = E[labels != c]
        if len(Xc) < 2 or len(Yc) < 1:
            continue
        if n_pairs is None:
            per_class[int(c)] = est.complete(Xc, Yc)
        else:
            per_class[int(c)] = est.incomplete(
                Xc, Yc, n_pairs=n_pairs, seed=seed
            )
    values = list(per_class.values())
    return {
        "per_class": per_class,
        "mean": float(np.mean(values)),
        "kernel": kernel,
        "backend": backend,
        "n": n,
        "n_pairs": n_pairs,
        "data_meta": meta,
    }
