"""BASELINE config 4: degree-3 triplet metric-learning statistics on
MNIST embeddings [SURVEY §1.1 "Degree-3", §3 "Triplet kernel"].

For each class c, the degree-(2,1) triplet U-statistic takes (anchor,
positive) pairs from class c and negatives from the other classes:

    U_c = mean_{i != j in c, k not in c} h(x_i, x_j, y_k)

and the reported statistic averages U_c over classes — with the
indicator kernel this is the class-balanced triplet accuracy of the
embedding (the fraction of relative-similarity constraints satisfied).

Checkpoint/resume [ISSUE 4]: the per-class loop is the long-running
part (complete statistics are O(n_c^2 * n)), so progress is
checkpointed per COMPLETED CLASS through ``utils.checkpoint``; a
preempted sweep resumes at the next class. Per-class values are
independent (each estimator call is keyed by the class data + ``seed``,
never by loop state), so a resumed sweep is bit-identical to a
straight one.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tuplewise_tpu.data import load_mnist_embeddings
from tuplewise_tpu.estimators.estimator import Estimator


def triplet_mnist_statistic(
    kernel: str = "triplet_indicator",
    backend: str = "jax",
    n: int = 2000,
    n_pairs: Optional[int] = 20_000,
    classes: Optional[list] = None,
    seed: int = 0,
    path: Optional[str] = None,
    checkpoint_path: Optional[str] = None,
    chaos=None,
    **backend_opts,
) -> dict:
    """Per-class triplet U-statistics over MNIST embeddings.

    n_pairs None -> complete statistic (O(n_c^2 * n) — small n only);
    otherwise the incomplete estimator with B=n_pairs sampled triplets.

    ``checkpoint_path``: persist (class, U_c) pairs after every class
    and resume a preempted sweep from the next one — bit-identical to
    the uninterrupted sweep (per-class values are order-independent).
    ``chaos``: fired at the ``checkpoint`` hook after each save (the
    ``sigkill`` action models preemption with durable state).
    """
    E, labels, meta = load_mnist_embeddings(path=path, n=n, seed=seed)
    est = Estimator(kernel, backend=backend, chaos=chaos, **backend_opts)
    todo = sorted(set(classes or np.unique(labels).tolist()))

    from tuplewise_tpu.utils.checkpoint import (
        resume_progress, save_checkpoint,
    )

    ck_config = {"kernel": kernel, "backend": backend, "n": n,
                 "n_pairs": n_pairs, "classes": [int(c) for c in todo],
                 "seed": seed, "n_done": len(todo)}
    start, ck = resume_progress(
        checkpoint_path, ck_config, progress_key="n_done",
        requested=len(todo))
    per_class = {}
    if ck is not None:
        per_class = {int(c): float(v) for c, v in zip(
            ck["extra"]["class_ids"], ck["extra"]["values"])}
    for i in range(start, len(todo)):
        c = todo[i]
        Xc = E[labels == c]
        Yc = E[labels != c]
        if len(Xc) >= 2 and len(Yc) >= 1:
            if n_pairs is None:
                per_class[int(c)] = est.complete(Xc, Yc)
            else:
                per_class[int(c)] = est.incomplete(
                    Xc, Yc, n_pairs=n_pairs, seed=seed
                )
        if checkpoint_path:
            save_checkpoint(
                checkpoint_path, step=i + 1,
                extra={
                    "class_ids": np.asarray(sorted(per_class),
                                            dtype=np.int64),
                    "values": np.asarray(
                        [per_class[c] for c in sorted(per_class)]),
                },
                config=ck_config,
            )
            if chaos is not None:
                chaos.fire("checkpoint")
    values = list(per_class.values())
    return {
        "per_class": per_class,
        "mean": float(np.mean(values)),
        "kernel": kernel,
        "backend": backend,
        "n": n,
        "n_pairs": n_pairs,
        "data_meta": meta,
        "recovery": {"resumed_from": int(start)},
    }
